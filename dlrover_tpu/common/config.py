"""Global tunables singleton.

Role parity: ``dlrover/python/common/global_context.py`` — one process-wide
``Context`` with named knobs (timeouts, thresholds, feature gates), each
overridable from the environment (``DLROVER_TPU_<UPPER_NAME>``) or at runtime
(e.g. by a cluster-level optimizer service).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict


class Context:
    _instance = None
    _lock = threading.Lock()

    def __init__(self):
        # control-loop cadences (seconds)
        self.master_service_timeout = 600
        self.seconds_to_wait_failed_ps = 600
        self.train_speed_record_num = 50
        self.seconds_for_stable_worker_count = 60
        self.seconds_interval_to_optimize = 30
        self.seconds_interval_to_report = 15
        self.seconds_to_start_autoscale_worker = 90
        self.step_to_adjust_worker = 200
        self.seconds_to_timeout_task = 1800
        self.hang_cpu_usage_percentage = 0.05
        self.hang_detection_secs = 1800
        self.heartbeat_timeout_secs = 300
        self.seconds_to_wait_pending_pod = 900
        # rendezvous
        self.rdzv_timeout_secs = 600
        self.rdzv_round_wait_secs = 3
        self.network_check_timeout_secs = 300
        # relaunch policy
        self.relaunch_on_worker_failure = 3
        self.max_relaunch_count = 5
        self.relaunch_always = False
        # elasticity
        self.auto_scale_enabled = True
        self.dynamic_sharding_enabled = True
        # cooldown between executed scale plans: a scale-up implies a new
        # rendezvous + recompile, and the stats window needs to refill
        # with post-scale samples before the optimizer can judge again
        self.seconds_between_scale_plans = 60
        # optimizer
        self.oom_memory_factor = 2.0
        self.optimize_worker_cpu_threshold = 0.8
        # checkpoint
        self.ckpt_async = True
        self.ckpt_host_staging = True
        # numerics debugging: opt-in jax_debug_nans (traps the first NaN
        # inside jit with a traceback; expensive — debug runs only)
        self.jax_debug_nans = False
        # guardrail: steps between non-finite loss/grad checks (0 = off);
        # each check reads one device scalar, so keep it off the per-step
        # hot path
        self.check_finite_every_steps = 10
        # async dispatch pipeline: how many train-step dispatches may be
        # in flight before the oldest one's metrics are materialized
        # (hooks/logging/finite-check consume LAGGED host values; 0 =
        # fully synchronous — materialize right after each dispatch)
        self.train_window = 4
        # multi-step fusion: optimizer steps per compiled call (K>1 =
        # a lax.scan over K stacked batches; one host dispatch per K
        # steps). Consumed by ElasticTrainer at construction.
        self.steps_per_call = 1
        # live elastic recovery: survivable membership changes (peer
        # lost, scale plan, another node preempted) are absorbed
        # IN-PROCESS — drain the dispatch window, snapshot TrainState to
        # host DRAM, rebuild the mesh for the survivor world, reshard
        # via device_put — instead of restarting the worker process
        # (docs/operations.md decision tree). Off = every change takes
        # the process-restart path.
        self.live_recovery = True
        # peer-redundant host snapshots (checkpoint-free pod-scale
        # recovery, docs/elasticity.md recovery ladder): how many PEER
        # DRAM replicas of each node's snapshot regions the master
        # should assign (0 = plane off). The budget admission can
        # degrade below this — fewer replicas, never a worker OOM.
        self.snapshot_replicas = 0
        # replication cadence: materialized steps between snapshot
        # pushes, floored by a wall-time interval so a fast-stepping
        # job cannot tax itself with per-step-scale replication
        self.replica_cadence_steps = 16
        self.replica_min_interval_secs = 15.0
        # host-DRAM budget (MB) this node grants to PEER replicas —
        # the admission input the master prices plans against (capped
        # at a quarter of the host's available memory at registration).
        # 0 = uncapped; NEGATIVE = lend nothing (the node is never a
        # peer-replica holder, while its OWN regions — budget-exempt
        # on its store — still replicate out to peers)
        self.replica_budget_mb = 512.0
        # chunk size of the replica wire stream (KB): each chunk is
        # length-prefixed + crc32-checksummed and retried individually
        self.replica_chunk_kb = 256
        # port the worker's replica store serves on (0 = ephemeral)
        self.replica_port = 0
        # recovering workers try the peer-rebuild path before the
        # Orbax/mirror restore (only meaningful with replicas > 0);
        # a stale peer snapshot older than the newest checkpoint
        # falls back to storage
        self.peer_restore = True
        # recovery-readiness plane (master/monitor/readiness.py,
        # docs/operations.md "Reading a readiness report"): wall
        # seconds between durability-audit sweeps of the replica
        # directory against the stores' live inventories (0 = the
        # continuous audit is off; forced sweeps — the RPC's refresh,
        # tests — still run)
        self.readiness_sweep_secs = 30.0
        # staleness allowance: a replica group whose committed step
        # trails the owner's reported step by more than this factor
        # times the master-computed cadence is STALE (coverage a
        # rebuild would roll the job back past one cadence is not
        # durability)
        self.readiness_stale_factor = 2.0
        # what to do on a non-finite step after reporting the failure:
        # "halt" | "rollback" (restore last checkpoint) | "ignore"
        self.on_nonfinite = "halt"
        # xprof trace capture ("" = off): the executor records
        # trace_num_steps steps starting at trace_start_step into
        # trace_dir (open with tensorboard/xprof). Env:
        # DLROVER_TPU_TRACE_DIR etc.
        self.trace_dir = ""
        self.trace_start_step = 5
        self.trace_num_steps = 3
        # telemetry (dlrover_tpu.telemetry / docs/observability.md):
        # master switch for the metrics registry, event timeline, and
        # host-span tracing (each instrument site holds handles fetched
        # through get_registry(), which goes null when this is off)
        self.telemetry_enabled = True
        # append-only JSONL event-timeline sink ("" = in-memory ring
        # only); DLROVER_TPU_EVENTS_FILE overrides per process and is
        # what the agent hands its workers so one file holds the job
        self.telemetry_events_file = ""
        # Prometheus exposition port on the agent/master (0 = off)
        self.telemetry_metrics_port = 0
        # event-timeline rotation cap in MB (0 = never rotate): past
        # this size the file rotates to <path>.1 and a fresh file opens;
        # read_events / mttr / goodput read the rotated pair
        self.telemetry_events_max_mb = 64
        # cluster diagnosis plane (master-side, docs/observability.md):
        # cadence of the workers' NodeRuntimeReport pushes (optimizer
        # steps between reports; 0 disables the hook)
        self.runtime_report_steps = 32
        # straggler verdict: a node is flagged when its windowed
        # step-time p50 exceeds the median of its peers by this ratio...
        self.diagnosis_straggler_ratio = 2.0
        # ...for this many CONSECUTIVE report windows (rides out the
        # one-off box-noise spikes a single window would flag)
        self.diagnosis_confirm_windows = 3
        # a node whose last runtime report is older than this while a
        # peer is still reporting is diagnosed hung (0 = off)
        self.diagnosis_hang_secs = 120.0
        # signal name ("" = off, e.g. "USR2") that opens an on-demand
        # bounded jax.profiler trace window in the executor
        self.profile_signal = ""
        # runtime optimization loop (master/optimizer; the telemetry ->
        # planner -> live-reshard control loop, docs/operations.md
        # "Self-tuning"): master switch for re-planning on diagnosis
        # verdicts / world changes
        self.runtime_optimizer_enabled = True
        # hysteresis: a candidate plan must predict at least this
        # speedup over the calibrated estimate of the CURRENT config to
        # be published (1.2 = 20% — below that the drain + swap churn
        # outweighs the win)
        self.replan_min_speedup = 1.2
        # cooldown/dedup window: the identical plan proposed twice
        # within this many seconds is suppressed (flapping triggers
        # cannot thrash the job through the same plan)
        self.replan_cooldown_secs = 60.0
        # input-bound replan gate (docs/operations.md "Self-tuning"):
        # when a node's input_wait_fraction sits >= 0.1 above the peer
        # median, the job is data-starved and a mesh/steps_per_call
        # replan cannot help — the optimizer rejects program plans with
        # reason=input_bound instead of paying a futile drain. Host
        # knobs (train_window) still apply.
        self.replan_input_bound_gate = True
        # worker-side: wall seconds between get_parallel_config polls
        # for a master-published plan (0 = the OptimizerPlanHook is off)
        self.plan_poll_secs = 30.0
        # worker-side: materialized steps after a live plan apply
        # before the realized speedup is measured and OPTIMIZER_APPLIED
        # is emitted (the post-convergence window)
        self.plan_measure_steps = 16
        # performance-attribution plane (telemetry.attribution,
        # docs/observability.md): capture a per-compiled-program
        # attribution record (exact FLOPs, bytes-accessed, per-
        # collective bytes, compiled peak HBM) once per program and
        # derive live MFU / exposed-comm-fraction gauges from it.
        # Requires telemetry_enabled; off = no capture, gauges absent.
        self.attribution_enabled = True
        # hardware peak FLOPs/s per device for the MFU denominator
        # (0 = sniff the device kind against the planner's TPU_SPECS;
        # CPU meshes fall back to the v5e datasheet so the gauge stays
        # defined — set this explicitly for meaningful CPU numbers)
        self.device_peak_flops = 0.0
        # per-device HBM budget in BYTES for the G107 graph lint and
        # the optimizer's memory-feasibility gate (0 = the device
        # spec's capacity, with the planner's 0.8 fit headroom where it
        # applies)
        self.device_hbm_budget_bytes = 0.0
        # comm/compute overlap (docs/parallelism.md "Hiding the
        # network"): chunked expert dispatch — how many static chunks
        # the grouped_ep MoE row exchange splits into (1 = the serial
        # one-shot all_to_all). Resolved at TRACE time by ops.moe, so
        # ElasticTrainer.retune can re-chunk a running job; the runtime
        # optimizer enumerates {1, 2, 4, 8} as a knob family.
        self.dispatch_chunks = 1
        # FSDP layer prefetch: gather layer l+1's params while layer l
        # computes (a double-buffered carry through the scan-over-
        # layers; same math, float-roundoff-level schedule differences
        # vs the plain scan). Resolved at trace time by models that
        # support it (llama). Off by default: with heavy tensor
        # sharding the replicate-gather it issues can cost more than
        # it hides.
        self.fsdp_prefetch = False
        # low-precision MoE wire (docs/parallelism.md "Low-precision"):
        # the grouped_ep row exchanges' wire format — "bf16" (the
        # compute dtype, no quantization), "fp8" (block-scaled e4m3
        # values + f32 per-block scales, ~0.56x the bytes; G109 lints
        # the numerics drift, G106 audits the bytes), or "fp8_qdq"
        # (the bitwise reference oracle / debug mode). Resolved at
        # TRACE time by ops.moe, so ElasticTrainer.retune can swap a
        # running job's wire precision through the program cache; the
        # runtime optimizer enumerates {bf16, fp8} as a knob family.
        self.moe_precision = "bf16"
        # low-precision DENSE wire (docs/parallelism.md "Low-precision
        # / The dense wire"): what the per-layer FSDP param gathers of
        # the scan-over-layers ship — "bf16" (the param dtype, no
        # quantization), "fp8" (block-scaled e4m3 + f32 scales, ~1/4
        # of an f32 gather; dequant-exact at consumption, gradients
        # straight-through) or "fp8_qdq" (the bitwise reference
        # oracle). Resolved at TRACE time by models that support it
        # (llama), so ElasticTrainer.retune can swap a running job's
        # dense wire through the program cache; the runtime optimizer
        # enumerates {bf16, fp8} as a knob family.
        self.fsdp_precision = "bf16"
        # low-precision GRADIENT path: "bf16" (exact, today's math) or
        # "fp8" — the per-shard gradient tree is quantized with an
        # ERROR-FEEDBACK residual (decompression error carried in
        # TrainState alongside optimizer state, added back before the
        # next quantize so the error telescopes instead of
        # accumulating). Unlike the dense gathers this changes
        # training numerics (bounded; G109 ratchets the drift) and the
        # residual is part of the training state, so it is a BUILD-time
        # knob of accelerate/ElasticTrainer, not a live-retune family.
        # ("fp8_nofb" quantizes WITHOUT feedback — the degradation
        # control the telescoping tests compare against; never use it
        # to train.)
        self.grad_precision = "bf16"
        # -- serving tier (dlrover_tpu.serving, docs/serving.md) -----
        # fixed slot-batch width of the continuous-batching decode
        # loop (the compiled batch dimension; the runtime optimizer
        # retunes it live through the serve program cache)
        self.serve_slots = 8
        # prompt tokens prefilled per chunk, interleaved into the
        # decode stream so long prompts cannot stall the batch (also
        # optimizer-retunable)
        self.serve_prefill_chunk = 32
        # KV-page storage precision: "f32" | "bf16" | "int8" (int8 =
        # values + f32 per-block scales, ~1/4 of f32 residency; probe
        # fallback to f32; the G109 "kv" family ratchets the drift)
        self.serve_kv_precision = "f32"
        # in-flight decode dispatches before the oldest one's tokens
        # materialize on host (the PR 3 async window, re-aimed at
        # decode; 0 = synchronous)
        self.serve_window = 2
        # shared prefix pool, in pages (0 = off): device-resident
        # refcounted KV pages beside the slot pool, radix-indexed
        # host-side; admission COPIES matched pages into the slot
        # (copy-on-admit) and prefills only the unmatched tail. Pool
        # bytes ride the same HBM feasibility gate the slot pool does;
        # the runtime optimizer retunes this live (docs/serving.md
        # "Prefix reuse").
        self.serve_prefix_pool_pages = 0
        # router-side soft session affinity: lease same-prefix
        # requests to the worker whose pool already holds the pages
        # (correctness never depends on it — a worker without the
        # pages just misses and prefills)
        self.serve_prefix_affinity = True
        # planner prior for the expected prefix hit rate before any
        # worker has observed one (0 = price prefill undiscounted, so
        # the optimizer only spends pool HBM once traffic proves
        # prefix sharing — or an operator declares it)
        self.serve_prefix_expected_hit_rate = 0.0
        # speculative decode (self-drafting: host n-gram prompt-lookup
        # proposer + one batched multi-token verify step; bitwise
        # identical to plain greedy at every acceptance pattern —
        # docs/serving.md "Speculative decoding"). Master switch: when
        # False the draft length is pinned to 0 everywhere and the
        # optimizer refuses to enumerate K.
        self.serve_spec_enabled = True
        # draft tokens verified per slot per step (K; 0 = off). K is
        # static per compiled program — the optimizer retunes it live
        # from the OBSERVED acceptance rate through the program cache
        # (a pure program swap: zero recompiles once prewarmed).
        self.serve_spec_draft_len = 0
        # master-side: a leased request whose worker has not touched
        # the router for this long is re-leased to a live worker
        # (the shard-timeout machinery re-pointed at requests)
        self.serve_lease_timeout_secs = 120.0
        # -- serving SLO plane (master/monitor/serve_slo.py;
        # docs/operations.md "Reading an SLO violation") --------------
        # declared SLO targets, evaluated over rolling windows with
        # multi-window burn-rate confirmation. 0 = target OFF (both
        # off = the SLO engine never evaluates — the default: SLOs are
        # a deployment declaration, not a framework guess)
        self.serve_slo_ttft_p95_secs = 0.0
        self.serve_slo_queue_depth = 0.0
        # rolling evaluation window, and how many consecutive
        # over-budget (or, for recovery, under-budget) windows confirm
        # (0 = follow diagnosis_confirm_windows)
        self.serve_slo_window_secs = 30.0
        self.serve_slo_confirm_windows = 0
        # SLO-driven serving scale policy: per-direction proposal
        # cooldown (a flapping SLO cannot thrash the serving world),
        # and how many consecutive all-idle ticks propose a scale-in
        # (0 = scale-in off)
        self.serve_scale_cooldown_secs = 120.0
        self.serve_scale_idle_windows = 0
        self._apply_env_overrides()

    def _apply_env_overrides(self):
        for name, val in vars(self).items():
            if name.startswith("_"):
                continue
            env = os.environ.get("DLROVER_TPU_" + name.upper())
            if env is None:
                continue
            try:
                if isinstance(val, bool):
                    setattr(self, name, env.lower() in ("1", "true", "yes"))
                elif isinstance(val, int):
                    setattr(self, name, int(env))
                elif isinstance(val, float):
                    setattr(self, name, float(env))
                else:
                    setattr(self, name, env)
            except ValueError:
                import logging

                logging.getLogger("dlrover_tpu").warning(
                    "ignoring malformed env override DLROVER_TPU_%s=%r",
                    name.upper(), env,
                )

    def set_params(self, params: Dict[str, Any]):
        """Runtime override (the reference's ``set_params_from_brain``)."""
        for k, v in params.items():
            if hasattr(self, k) and not k.startswith("_"):
                setattr(self, k, v)

    @classmethod
    def singleton_instance(cls) -> "Context":
        if cls._instance is None:
            with cls._lock:
                if cls._instance is None:
                    cls._instance = cls()
        return cls._instance


def get_context() -> Context:
    return Context.singleton_instance()
