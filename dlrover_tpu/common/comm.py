"""Wire messages between agents and the master.

Role parity: ``dlrover/proto/elastic_training.proto`` (~30 rpcs). Every
message here is a registered dataclass (see ``serialize.message``); the
master exposes exactly two unary rpcs — ``get`` (query) and ``report``
(fire-and-forget-ish state push) — and dispatches on message type, which is
the shape the reference's servicer converges to as well.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from dlrover_tpu.common.serialize import message

# --------------------------------------------------------------------------
# envelope
# --------------------------------------------------------------------------


@message
class BaseRequest:
    node_id: int = -1
    node_type: str = ""


@message
class Response:
    success: bool = True
    reason: str = ""
    data: Optional[object] = None


# --------------------------------------------------------------------------
# data sharding
# --------------------------------------------------------------------------


@message
class DatasetShardParams:
    """Registers a dataset with the master's task manager."""

    dataset_name: str = ""
    dataset_size: int = 0
    batch_size: int = 0
    num_epochs: int = 1
    shuffle: bool = False
    num_minibatches_per_shard: int = 2
    storage_type: str = "table"  # table | text | stream
    task_type: str = "training"  # training | evaluation


@message
class TaskRequest:
    dataset_name: str = ""
    node_id: int = -1


@message
class Shard:
    name: str = ""
    start: int = 0
    end: int = 0
    record_indices: Optional[List[int]] = None


@message
class Task:
    task_id: int = -1
    task_type: str = ""
    shard: Optional[Shard] = None
    epoch: int = 0

    @property
    def exists(self) -> bool:
        return self.task_id >= 0


@message
class TaskResult:
    dataset_name: str = ""
    task_id: int = -1
    err_message: str = ""
    node_id: int = -1


@message
class BatchDoneReport:
    dataset_name: str = ""
    node_id: int = -1
    record_count: int = 0


@message
class ShardCheckpointRequest:
    dataset_name: str = ""


@message
class ShardCheckpoint:
    dataset_name: str = ""
    content: str = ""  # JSON blob owned by the dataset manager


# --------------------------------------------------------------------------
# rendezvous
# --------------------------------------------------------------------------


@message
class RendezvousParams:
    """Pushed once by node rank 0 before joining."""

    min_nodes: int = 1
    max_nodes: int = 1
    waiting_timeout: float = 30.0
    node_unit: int = 1  # world size must be a multiple (TPU slice hosts)
    rdzv_name: str = ""


@message
class JoinRendezvousRequest:
    node_rank: int = -1
    local_world_size: int = 1
    rdzv_name: str = ""
    node_id: int = -1
    slice_index: int = 0
    addr: str = ""  # host addr usable as jax.distributed coordinator


@message
class CommWorldRequest:
    rdzv_name: str = ""
    node_rank: int = -1


@message
class CommWorld:
    """The agreed world for one rendezvous round.

    ``world`` maps node_rank -> local_world_size (number of JAX processes the
    host will start). ``coordinator_addr`` is the jax.distributed coordinator
    (host of the smallest participating node rank) — the TPU analogue of the
    reference handing out the c10d store address.
    """

    rdzv_name: str = ""
    round: int = 0
    group: int = 0
    world: Optional[Dict[int, int]] = None
    coordinator_addr: str = ""


@message
class WaitingNodeNumRequest:
    rdzv_name: str = ""


@message
class NetworkReadyRequest:
    pass


@message
class NetworkCheckResult:
    node_rank: int = -1
    normal: bool = True
    elapsed_time: float = 0.0


@message
class StragglerExistRequest:
    pass


@message
class AbnormalNodesRequest:
    pass


@message
class NodeRankList:
    ranks: Optional[List[int]] = None
    # master-clock timestamp of the response: pollers reuse it as the
    # next window start so cross-host clock skew can't drop records
    server_time: float = 0.0


@message
class RendezvousState:
    round: int = 0
    waiting_num: int = 0


# --------------------------------------------------------------------------
# kv store / sync
# --------------------------------------------------------------------------


@message
class KVStoreSetRequest:
    key: str = ""
    value: str = ""  # base64 when binary


@message
class KVStoreGetRequest:
    key: str = ""


@message
class KVStoreValue:
    key: str = ""
    value: str = ""
    found: bool = False


@message
class KVStoreAddRequest:
    key: str = ""
    amount: int = 0


@message
class SyncJoinRequest:
    sync_name: str = ""
    node_rank: int = -1


@message
class SyncFinishRequest:
    sync_name: str = ""


@message
class BarrierRequest:
    barrier_name: str = ""
    notify: bool = False


# --------------------------------------------------------------------------
# failures / monitoring
# --------------------------------------------------------------------------


@message
class FailedNodesRequest:
    """Query node ids with hard failures since a timestamp (the engine's
    dead-rank watcher polls this instead of waiting out task timeouts)."""

    since_timestamp: float = 0.0


@message
class NodeFailure:
    node_id: int = -1
    node_rank: int = -1
    restart_count: int = 0
    error_data: str = ""
    level: str = "process"  # TrainingExceptionLevel


@message
class ResourceStats:
    node_id: int = -1
    node_type: str = ""
    cpu_percent: float = 0.0
    memory_mb: int = 0
    chips: int = 0
    duty_cycle: float = 0.0  # accelerator busy fraction, if known


@message
class GlobalStep:
    step: int = 0
    timestamp: float = 0.0
    elapsed_time_per_step: float = 0.0
    # True when the reported step REWINDS the truth (non-finite
    # rollback, live reshard resuming from a snapshot): the master's
    # monotone max() gauge and speed window must reset, not ignore it
    reset: bool = False


@message
class NodeRuntimeReport:
    """Node-tagged snapshot of the worker's runtime instruments
    (cumulative histogram bucket counts — the master diffs consecutive
    reports into per-window series; see master/monitor/node_series.py).
    """

    node_id: int = -1
    node_type: str = "worker"
    timestamp: float = 0.0
    step: int = 0
    steps_total: float = 0.0
    # shared bucket bounds (+Inf bucket is the extra last count)
    bounds: Optional[List[float]] = None
    step_time_counts: Optional[List[int]] = None
    dispatch_counts: Optional[List[int]] = None
    host_sync_counts: Optional[List[int]] = None
    window_occupancy: float = 0.0
    lagged_age: float = 0.0
    rss_mb: float = 0.0
    # None = the backend exposes no memory stats (CPU): the master must
    # report the gauge ABSENT, never a fake 0
    device_mem_mb: Optional[float] = None
    hbm_headroom_mb: Optional[float] = None
    # performance-attribution derived gauges (None until the worker
    # captured a per-program attribution record)
    mfu: Optional[float] = None
    exposed_comm_frac: Optional[float] = None
    flops_per_step: Optional[float] = None
    peak_hbm_mb: Optional[float] = None
    # data plane: fraction of the worker's last materialization window
    # spent blocked waiting for the next host batch (None until the
    # executor measured a window — absent, never a fake 0)
    input_wait_frac: Optional[float] = None
    # serving tier (reports with node_type="serve", pushed by
    # ServeRuntimeReportHook): ``step_time_counts`` carries the
    # cumulative DECODE-step histogram and ``steps_total`` the decode
    # steps; these fields carry the serving-only facts. None on
    # training reports — the master exports the serve gauges only for
    # serve nodes.
    serve_tokens_total: Optional[float] = None
    serve_queue_len: Optional[float] = None
    serve_slot_occupancy: Optional[float] = None
    serve_slots: Optional[float] = None
    # speculative decode: cumulative drafted/accepted totals — the
    # master diffs consecutive reports into a windowed acceptance-rate
    # gauge (None while K=0 or on training reports)
    serve_spec_drafted_total: Optional[float] = None
    serve_spec_accepted_total: Optional[float] = None


@message
class AttributionRequest:
    """Query the master's performance-attribution view: per-node
    derived MFU / exposed-comm / HBM gauges from the node series plus
    the optimizer's memory-feasibility rejections (the ``tpurun
    attribution --addr`` view). Answered with a DiagnosisReport-style
    JSON blob."""

    node_id: int = -1
    limit: int = 0  # 0 = every retained memory rejection


@message
class DataShardRequest:
    """Query the master's shard-dispatch ledger: per-dataset
    todo/doing/done queues, epoch progress + ETA, timeout recoveries
    and per-node consumption rates (the ``tpurun data --addr`` view).
    Answered with a DiagnosisReport-style JSON blob."""

    dataset_name: str = ""  # "" = every registered dataset


@message
class DiagnosisRequest:
    """Query the master's cluster diagnosis: node series summaries plus
    straggler/hang verdicts (node_id -1 = whole cluster)."""

    node_id: int = -1


@message
class DiagnosisReport:
    # JSON blob (nodes, verdicts, stragglers, hung) — the diagnosis
    # schema is owned by master/monitor, not the wire layer
    report_json: str = ""


@message
class NodeHeartbeat:
    node_id: int = -1
    timestamp: float = 0.0


@message
class NodeStatusReport:
    node_id: int = -1
    node_type: str = ""
    status: str = ""


@message
class DatasetMetric:
    dataset_name: str = ""
    dataset_size: int = 0
    storage_type: str = ""


@message
class ModelInfo:
    num_params: int = 0
    flops_per_step: float = 0.0
    hidden_size: int = 0
    num_layers: int = 0
    seq_len: int = 0
    # MoE shape: lets the runtime optimizer's calibrated ModelSpec
    # price the dispatch-comm terms (and enumerate dispatch_chunks)
    # instead of seeing a dense model
    num_experts: int = 0
    moe_top_k: int = 1
    ffn_mult: float = 0.0  # intermediate/hidden (0 = spec default)


@message
class ParallelConfig:
    """Mesh/partition decisions the master can push to agents at runtime.

    The runtime optimizer (``master/optimizer``) publishes its chosen
    plans through this message: a non-empty ``plan_id`` marks an
    optimizer plan, and workers polling ``get_parallel_config``
    (``OptimizerPlanHook``) apply it LIVE — ``restart=False`` means
    drain the window and retune/reshard in place; sentinel values
    (``train_window=-1``, ``steps_per_call=0``) leave a knob unchanged.
    """

    mesh_shape: Optional[Dict[str, int]] = None
    remat_policy: str = ""
    grad_accum_steps: int = 1
    restart: bool = False
    # -1 / 0 / "" = leave the knob as the worker currently runs it
    train_window: int = -1
    steps_per_call: int = 0
    moe_dispatch: str = ""
    # grouped_ep chunked dispatch degree (0 = leave unchanged): a
    # COMPILED-program knob, applied through the same prewarmed
    # program-cache swap as steps_per_call / mesh overrides
    dispatch_chunks: int = 0
    # grouped_ep wire precision ("" = leave unchanged; "bf16"/"fp8"):
    # the same prewarmed program-cache swap contract as dispatch_chunks
    moe_precision: str = ""
    # dense FSDP gather wire precision ("" = leave unchanged;
    # "bf16"/"fp8"): the same prewarmed program-cache swap contract —
    # a backend whose fp8 probe fails negative-acks the plan
    fsdp_precision: str = ""
    # serving-tier knobs (0 = leave unchanged): the continuous-batching
    # slot width and the prefill chunk, applied by serve workers through
    # the SAME prewarmed program-cache swap as the training knobs
    serve_slots: int = 0
    serve_prefill_chunk: int = 0
    # shared prefix pool pages. 0 is a REAL value here (pool off), so
    # the leave-unchanged sentinel is -1, unlike its 0-sentinel siblings
    serve_prefix_pool_pages: int = -1
    # speculative draft length K. 0 is a REAL value (spec off), so the
    # leave-unchanged sentinel is -1 like the pool knob
    serve_spec_draft_len: int = -1
    # optimizer decision identity: the worker echoes plan_id back in its
    # TrainerConfigReport ack, and every OPTIMIZER_* event on both sides
    # carries trace_id so the decision trail merges per incident
    plan_id: str = ""
    trace_id: str = ""
    predicted_speedup: float = 0.0
    # standby-compile the candidate program before swapping, so the swap
    # itself pays zero recompiles (ElasticTrainer.prewarm)
    prewarm: bool = True


@message
class ParallelConfigRequest:
    node_id: int = -1


@message
class TrainerConfigReport:
    """Worker -> master: the config the trainer is ACTUALLY running —
    the runtime optimizer's running-config input (sent at train start
    and after every live reshard/retune). A non-empty ``plan_id`` acks
    an applied optimizer plan, carrying the realized speedup the
    post-apply window measured."""

    node_id: int = -1
    world: int = 0  # devices in the active mesh
    mesh_shape: Optional[Dict[str, int]] = None
    train_window: int = 0
    steps_per_call: int = 1
    moe_dispatch: str = ""
    # the grouped_ep chunk degree this worker actually runs (0 = not
    # reported / not applicable)
    dispatch_chunks: int = 0
    # the grouped_ep wire precision this worker actually runs ("" =
    # not reported / not applicable)
    moe_precision: str = ""
    # the dense FSDP gather wire precision this worker actually runs
    # ("" = not reported): what unlocks the optimizer's fsdp_precision
    # knob family — always known for a trainer-managed job
    fsdp_precision: str = ""
    # the gradient-path precision (error-feedback residual) this worker
    # was BUILT with — reported for observability; never enumerated by
    # the optimizer (the residual is TrainState structure)
    grad_precision: str = ""
    global_batch: int = 0
    plan_id: str = ""
    predicted_speedup: float = 0.0
    realized_speedup: float = 0.0
    # negative ack: the plan could not be applied (rebuild failed, or
    # the knobs are unsupported on this deployment) — the optimizer
    # blacklists the knob tuple instead of re-proposing it forever
    apply_failed: bool = False


# --------------------------------------------------------------------------
# peer-redundant host snapshots (checkpoint-free pod-scale recovery)
# --------------------------------------------------------------------------


@message
class ReplicaEndpointReport:
    """Worker -> master: this node serves a replica store at ``addr``.

    Re-reported on every push cycle so the master's ReplicaDirectory
    tracks liveness and snapshot freshness without a second heartbeat
    channel. ``budget_mb`` is the host-DRAM budget this node grants to
    PEER replicas (the admission input of the replica plan);
    ``snapshot_mb`` the size of one full snapshot on this node (the
    numerator of the per-owner share the plan prices)."""

    node_id: int = -1
    addr: str = ""
    budget_mb: float = 0.0
    snapshot_mb: float = 0.0
    step: int = -1  # newest replicated (committed) step, -1 = none yet
    timestamp: float = 0.0
    # last completed push cycle's wall seconds / bytes shipped: the
    # readiness auditor's continuous link-bandwidth calibration (a push
    # streams exactly the bytes a rebuild fetches back, over the same
    # RPC path). 0 = no completed cycle yet.
    push_seconds: float = 0.0
    push_bytes: float = 0.0


@message
class ReplicaPlanRequest:
    """Worker -> master: which peers should hold my snapshot regions?"""

    node_id: int = -1


@message
class ReplicaPlan:
    """The master-chosen, rendezvous-stable peer assignment for one
    owner. ``replicas`` may be below the configured k when the budget
    pricing degraded the plan (``degraded``/``reason`` say why) — an
    infeasible plan ships fewer replicas, never an OOM."""

    owner: int = -1
    peers: Optional[List[Dict]] = None  # [{"node_id": int, "addr": str}]
    replicas: int = 0
    requested: int = 0
    # the FULL live owner group the byte partition is computed over —
    # every owner must slice against the same group or the per-owner
    # regions cannot reassemble (k < n-1 means peers ⊂ group)
    group: Optional[List[int]] = None
    # MASTER-computed effective cadence in steps (0 = master has no
    # step-time series yet; workers fall back to their local knob +
    # wall floor). One value for the whole cluster: per-node wall
    # floors drift nodes onto disjoint push-step schedules, and a
    # rebuild needs ONE step with full owner coverage.
    cadence_steps: int = 0
    degraded: bool = False
    reason: str = ""


@message
class RecoveryPlanRequest:
    """Rebuilding worker -> master: map every owner's snapshot regions
    to live replica holders (answered with a DiagnosisReport JSON
    blob: {"owners": {owner: [endpoints...]}, "replicas": k,
    "predicted_mttr": {rung: seconds} — the priced recovery ladder
    the worker's rung choice consults)."""

    node_id: int = -1


@message
class ReadinessRequest:
    """Operator/CLI -> master: the recovery-readiness report — the
    durability audit's posture, per-node blast-radius verdicts and
    predicted-MTTR-per-rung table, and the pricer's calibration state
    (answered with a DiagnosisReport JSON blob; `tpurun readiness`'s
    live view)."""

    node_id: int = -1


@message
class ReplicaPut:
    """One length-prefixed, checksummed snapshot chunk (or the commit
    manifest that seals a step) pushed peer-to-peer into a holder's
    ReplicaStore. ``frame`` is the base64 chunk frame
    (``checkpoint.replication.encode_chunk``)."""

    node_id: int = -1  # the PUSHING node (the region owner)
    frame: str = ""


@message
class ReplicaFetchRequest:
    """Fetch one stored chunk of a committed snapshot from a holder."""

    owner: int = -1
    step: int = -1
    leaf: int = -1
    seq: int = 0


@message
class ReplicaFrame:
    frame: str = ""  # base64 chunk frame; "" when not held
    found: bool = False


@message
class ReplicaInfoRequest:
    """Holder inventory: which (owner, step) snapshots are committed
    here, with per-leaf coverage. Answered with a DiagnosisReport
    JSON blob."""

    owner: int = -1  # -1 = every owner this store holds


# --------------------------------------------------------------------------
# serving (request router + serve workers)
# --------------------------------------------------------------------------


@message
class ServeSubmit:
    """Enqueue one inference request on the master's request router."""

    request_id: str = ""  # "" = router-assigned
    prompt: Optional[List[int]] = None
    max_new_tokens: int = 16
    eos_id: int = -1


@message
class ServeLeaseRequest:
    """Worker -> master: lease up to ``max_requests`` queued requests
    (the serving twin of TaskRequest)."""

    node_id: int = -1
    max_requests: int = 1


@message
class ServeLeases:
    # list of ServeRequest wire dicts (request_id/prompt/
    # max_new_tokens/eos_id) — the router owns the schema
    requests: Optional[List[Dict]] = None


@message
class ServeResult:
    """Worker -> master: one request finished (tokens + the latency
    facts the router's histograms account)."""

    node_id: int = -1
    request_id: str = ""
    tokens: Optional[List[int]] = None
    ttft_s: Optional[float] = None
    e2e_s: Optional[float] = None
    error_code: str = ""
    # prompt tokens whose KV pages were COPIED from the worker's
    # shared prefix pool instead of prefilled (0 = miss or pool off) —
    # the router's saved-token ledger input
    prefix_hit_tokens: int = 0
    # speculative decode: draft tokens this request proposed into
    # verify steps and the subset accepted (drafted - accepted =
    # wasted) — the router's conservation-checked spec ledger input
    spec_drafted_tokens: int = 0
    spec_accepted_tokens: int = 0


@message
class ServeTouch:
    """Worker liveness for the lease-expiry scan (rate-limited by the
    worker; absence past ``serve_lease_timeout_secs`` re-leases its
    requests)."""

    node_id: int = -1


@message
class ServeReportRequest:
    """Query the router ledger (``tpurun requests --addr``): queue /
    lease / completion counts, latency percentiles, per-node rows.
    Answered with a DiagnosisReport-style JSON blob."""

    pass


@message
class ServeConfigReport:
    """Serve worker -> master: the serving config actually running —
    the runtime optimizer's serve-knob input and plan-apply ack (the
    TrainerConfigReport pattern for the serving workload)."""

    node_id: int = -1
    world: int = 0
    serve_slots: int = 0
    prefill_chunk: int = 0
    kv_precision: str = ""
    max_seq: int = 0
    # the REAL pool geometry (the worker's KVCacheSpec): without it
    # the optimizer's HBM gate would price a GQA model's pool at the
    # full query-head count — up to heads/kv_heads too large — and
    # memory-reject slot widths that actually fit
    num_layers: int = 0
    kv_heads: int = 0
    head_dim: int = 0
    # shared prefix pool actually running (pages; 0 = off), its page
    # grain, and the hit rate this worker has OBSERVED — the
    # optimizer's pricing input for the prefill discount (observation
    # beats the serve_prefix_expected_hit_rate prior)
    prefix_pool_pages: int = 0
    page_size: int = 0
    prefix_hit_rate: float = -1.0
    # speculative decode actually running (draft length K; 0 = off)
    # and the acceptance rate this worker has OBSERVED (-1 = no draft
    # yet): the optimizer prices K ONLY from evidence — zero evidence
    # prices every K>0 at exactly 1.0x (no assumed speedup)
    spec_draft_len: int = 0
    spec_accept_rate: float = -1.0
    plan_id: str = ""
    apply_failed: bool = False


@message
class ServeSLORequest:
    """Query the master's serving SLO plane (``tpurun serve slo
    --addr``): declared targets, current burn rates, active violation
    verdicts and the scale proposals the policy loop issued. Answered
    with a DiagnosisReport-style JSON blob."""

    pass


@message
class PlanRequest:
    """Query the master's runtime optimizer: running config, calibration
    factors, candidate tables and the decision trail (the ``tpurun plan
    --addr`` view). Answered with a DiagnosisReport-style JSON blob."""

    limit: int = 0  # 0 = the full retained decision trail


# --------------------------------------------------------------------------
# PS-strategy parity (elastic PS cluster versioning)
# --------------------------------------------------------------------------


@message
class ClusterVersionRequest:
    task_type: str = ""
    task_id: int = 0
    version_type: str = "global"  # global | local | restored


@message
class ClusterVersion:
    version: int = 0


@message
class ClusterVersionUpdate:
    task_type: str = ""
    task_id: int = 0
    version_type: str = "global"
    version: int = 0
    # Compare-and-set guard: apply only while the current value equals
    # `expected` (-1 = unconditional). Makes concurrent global-version
    # bumps race-free server-side.
    expected: int = -1


@message
class QueryPsNodesRequest:
    pass


@message
class PsNodes:
    addrs: Optional[List[str]] = None
    ready: bool = False
    new_ps_ready: bool = False


# --------------------------------------------------------------------------
# job control
# --------------------------------------------------------------------------


@message
class JobExitRequest:
    node_id: int = -1
    success: bool = True
    reason: str = ""


@message
class ScaleRequest:
    """Manual scaling hook (the reference's user-submitted ScalePlan CR)."""

    worker_num: int = 0


def is_message(obj) -> bool:
    return dataclasses.is_dataclass(obj) and not isinstance(obj, type)
