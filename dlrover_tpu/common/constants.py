"""Shared enums and constants for the control plane.

Role parity: ``dlrover/python/common/constants.py`` in the reference (node
types, statuses, distribution strategies, rendezvous names, env-var contract).
Values are our own; TPU-specific notions (slices, ICI) are first-class.
"""

from __future__ import annotations


class PlatformType:
    LOCAL = "local"
    KUBERNETES = "k8s"
    RAY = "ray"


class NodeType:
    MASTER = "master"
    WORKER = "worker"
    CHIEF = "chief"
    PS = "ps"
    EVALUATOR = "evaluator"


class NodeStatus:
    INITIAL = "Initial"
    PENDING = "Pending"
    RUNNING = "Running"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"
    DELETED = "Deleted"
    BREAKDOWN = "Breakdown"  # failed the network/ICI health check
    UNKNOWN = "Unknown"

    @classmethod
    def end_states(cls):
        return {cls.SUCCEEDED, cls.FAILED, cls.DELETED}


class NodeEventType:
    ADDED = "ADDED"
    MODIFIED = "MODIFIED"
    DELETED = "DELETED"


class NodeExitReason:
    SUCCEEDED = "Succeeded"
    KILLED = "Killed"
    OOM = "OOMKilled"
    FATAL_ERROR = "FatalError"
    HARDWARE_ERROR = "HardwareError"  # TPU chip / ICI link failure
    PREEMPTED = "Preempted"
    UNKNOWN_ERROR = "UnknownError"


class JobExitReason:
    SUCCEEDED = "Succeeded"
    CODE_ERROR = "CodeError"
    NODE_OOM_ERROR = "NodeOOMError"
    NODE_ERROR = "NodeError"
    RDZV_TIMEOUT_ERROR = "RendezvousTimeoutError"
    HANG_ERROR = "HangError"
    UNKNOWN_ERROR = "UnknownError"


class DistributionStrategy:
    """How the training processes coordinate.

    SPMD is the TPU-native analogue of the reference's "AllreduceStrategy"
    (one program, XLA collectives over ICI/DCN); PS is kept for parity with
    the reference's parameter-server jobs; LOCAL is single-process.
    """

    SPMD = "spmd"
    PS = "ps"
    LOCAL = "local"


class RendezvousName:
    TRAINING = "elastic-training"
    NETWORK_CHECK = "network-check"


class JobStage:
    CREATE = "create"
    WORKER_INITIAL = "worker_initial"
    RUNNING = "running"
    STOPPING = "stopping"


class TrainingExceptionLevel:
    PROCESS_ERROR = "process"
    NODE_ERROR = "node"
    RDZV_ERROR = "rdzv"
    WARNING = "warning"
    INFO = "info"


class NodeEnv:
    """Env-var contract between agent and training processes."""

    MASTER_ADDR = "DLROVER_TPU_MASTER_ADDR"
    JOB_NAME = "DLROVER_TPU_JOB_NAME"
    # unique per job LAUNCH (name + launch epoch, set by the scalers):
    # stable across worker relaunches within one job instance, rotates
    # when a fresh job reuses the name — the checkpoint staging
    # provenance token prefers it over the bare job name
    RUN_ID = "DLROVER_TPU_RUN_ID"
    NODE_ID = "DLROVER_TPU_NODE_ID"
    NODE_RANK = "DLROVER_TPU_NODE_RANK"
    NODE_NUM = "DLROVER_TPU_NODE_NUM"
    NODE_TYPE = "DLROVER_TPU_NODE_TYPE"
    AUTO_MONITOR_WORKLOAD = "DLROVER_TPU_AUTO_MONITOR"
    # Handed to each training process at (re-)rendezvous:
    COORDINATOR_ADDR = "DLROVER_TPU_COORDINATOR_ADDR"
    PROCESS_ID = "DLROVER_TPU_PROCESS_ID"
    NUM_PROCESSES = "DLROVER_TPU_NUM_PROCESSES"
    RESTART_ROUND = "DLROVER_TPU_RESTART_ROUND"
    # set by the agent when hang-relaunch is on; workers touch
    # "<dir>/hb_<LOCAL_RANK>" each step (diagnosis.hang_detector)
    HEARTBEAT_DIR = "DLROVER_TPU_HEARTBEAT_DIR"


class DefaultValues:
    SERVICE_PORT = 0  # 0 = pick a free port
    RELAUNCH_ON_WORKER_FAILURE = 3
    MAX_RELAUNCH_COUNT = 5
    SECONDS_TO_START_AUTOSCALE_WORKER = 90
    RDZV_TIMEOUT_SECS = 600
    NETWORK_CHECK_TIMEOUT_SECS = 300
    MONITOR_INTERVAL_SECS = 5.0
    REPORT_RESOURCE_INTERVAL_SECS = 15.0


class GraftPlatform:
    """Accelerator platform tags used by resource descriptions."""

    TPU = "tpu"
    CPU = "cpu"
