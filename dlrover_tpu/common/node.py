"""Node model for the control plane.

Role parity: ``dlrover/python/common/node.py`` (``Node``, ``NodeResource``,
``NodeGroupResource``) — the master's in-memory picture of every node in a
job, plus the resource quantities the optimizer/scaler act on.

TPU-first: a node is a *host* in a TPU slice; its accelerator resource is a
(platform, chip-count, topology) triple rather than a GPU count, and nodes
carry a ``slice_index`` so rendezvous can keep worlds whole-slice
(``node_unit`` semantics in the reference, ``rdzv_manager.py:118-120``).
"""

from __future__ import annotations

import copy
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from dlrover_tpu.common.constants import (
    NodeExitReason,
    NodeStatus,
    NodeType,
)


@dataclass
class AcceleratorResource:
    """Accelerator attached to one host."""

    platform: str = "tpu"  # "tpu" | "cpu"
    chips: int = 0  # chips attached to this host (v5p: 4 per host)
    topology: str = ""  # e.g. "2x2x4" for the slice this host belongs to


@dataclass
class NodeResource:
    """CPU is in cores, memory in MiB (matching the reference's units)."""

    cpu: float = 0.0
    memory: int = 0
    accelerator: AcceleratorResource = field(default_factory=AcceleratorResource)
    priority: str = ""

    def to_dict(self) -> Dict:
        return {
            "cpu": self.cpu,
            "memory": self.memory,
            "chips": self.accelerator.chips,
            "platform": self.accelerator.platform,
        }

    @classmethod
    def resource_str(cls, res: "NodeResource") -> str:
        return f"cpu={res.cpu},mem={res.memory}Mi,chips={res.accelerator.chips}"


@dataclass
class NodeGroupResource:
    """Resource request for a homogeneous group of nodes (e.g. all workers)."""

    count: int = 0
    node_resource: NodeResource = field(default_factory=NodeResource)

    def update(self, count: Optional[int] = None, cpu: Optional[float] = None,
               memory: Optional[int] = None):
        if count is not None and count > 0:
            self.count = count
        if cpu is not None and cpu > 0:
            self.node_resource.cpu = cpu
        if memory is not None and memory > 0:
            self.node_resource.memory = memory


class Node:
    """One host of a job, with lifecycle state.

    The master mutates these objects from watcher events and agent reports;
    the job manager reads them to decide relaunch/scale actions.
    """

    def __init__(
        self,
        node_type: str = NodeType.WORKER,
        node_id: int = 0,
        rank_index: Optional[int] = None,
        name: str = "",
        status: str = NodeStatus.INITIAL,
        config_resource: Optional[NodeResource] = None,
        max_relaunch_count: int = 3,
        relaunchable: bool = True,
        critical: bool = False,
        slice_index: int = 0,
        service_addr: str = "",
    ):
        self.type = node_type
        self.id = node_id
        self.rank_index = rank_index if rank_index is not None else node_id
        self.name = name or f"{node_type}-{node_id}"
        self.status = status
        self.config_resource = config_resource or NodeResource()
        self.used_resource = NodeResource()
        self.max_relaunch_count = max_relaunch_count
        self.relaunch_count = 0
        self.relaunchable = relaunchable
        self.critical = critical
        self.slice_index = slice_index
        self.service_addr = service_addr

        self.exit_reason: str = ""
        self.is_released = False
        # When the master materialized this node object; the pending-timeout
        # early-stop check measures from here.
        self.create_time: Optional[float] = time.time()
        self.start_time: Optional[float] = None
        self.finish_time: Optional[float] = None
        self.heartbeat_time: float = 0.0
        self.start_hang_time: float = 0.0
        self.reported_status: str = ""
        self.restart_training = False
        self.migrated = False
        self.paral_config: Dict = {}

    # -- lifecycle ----------------------------------------------------------

    def update_status(self, status: str):
        if status and status != NodeStatus.UNKNOWN:
            self.status = status
            if status == NodeStatus.RUNNING and self.start_time is None:
                self.start_time = time.time()
            if status in NodeStatus.end_states() and self.finish_time is None:
                self.finish_time = time.time()

    def inc_relaunch_count(self):
        self.relaunch_count += 1

    def exited(self) -> bool:
        return self.status in NodeStatus.end_states()

    def is_unrecoverable_failure(self) -> bool:
        """Failures that relaunching this node cannot fix."""
        if self.relaunch_count >= self.max_relaunch_count:
            return True
        if self.exit_reason == NodeExitReason.FATAL_ERROR:
            return True
        return False

    def update_reported_status(self, status: str):
        self.reported_status = status

    def update_resource_usage(self, cpu: float, memory: int):
        self.used_resource.cpu = cpu
        self.used_resource.memory = memory

    def update_heartbeat(self, ts: Optional[float] = None):
        self.heartbeat_time = ts if ts is not None else time.time()

    def get_relaunch_node(self, new_id: int) -> "Node":
        """Build the replacement node the scaler should create."""
        node = Node(
            node_type=self.type,
            node_id=new_id,
            rank_index=self.rank_index,
            status=NodeStatus.INITIAL,
            config_resource=copy.deepcopy(self.config_resource),
            max_relaunch_count=self.max_relaunch_count,
            critical=self.critical,
            slice_index=self.slice_index,
        )
        node.relaunch_count = self.relaunch_count + 1
        return node

    def __repr__(self):
        return (
            f"Node({self.type}-{self.id} rank={self.rank_index} "
            f"status={self.status} relaunch={self.relaunch_count})"
        )
