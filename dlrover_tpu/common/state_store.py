"""Pluggable master-state backends.

Role parity: ``dlrover/python/util/state/`` (``memory_store.py``,
``stats_backend.py``, ``store_mananger.py``) — an interface for durable
master state (shard checkpoints, rendezvous rounds, job metadata) with
an in-memory default. The durable backend here is a JSON-file store
(checkpointable to a PVC/GCS-fuse mount); the interface is the seam for
anything stronger.
"""

from __future__ import annotations

import json
import os
import threading
import time
from abc import ABC, abstractmethod
from typing import Any, ClassVar, Dict, List, Optional


class StateBackend(ABC):
    @abstractmethod
    def set(self, key: str, value: Any) -> None:
        ...

    @abstractmethod
    def get(self, key: str, default: Any = None) -> Any:
        ...

    @abstractmethod
    def delete(self, key: str) -> bool:
        ...

    @abstractmethod
    def keys(self, prefix: str = "") -> List[str]:
        ...

    def update(self, values: Dict[str, Any]) -> None:
        for k, v in values.items():
            self.set(k, v)


class MemoryStateBackend(StateBackend):
    """Default: master state lives and dies with the process
    (reference memory_store.py)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._data: Dict[str, Any] = {}

    def set(self, key, value):
        with self._lock:
            self._data[key] = value

    def get(self, key, default=None):
        with self._lock:
            return self._data.get(key, default)

    def delete(self, key):
        with self._lock:
            return self._data.pop(key, _MISSING) is not _MISSING

    def keys(self, prefix=""):
        with self._lock:
            return [k for k in self._data if k.startswith(prefix)]


_MISSING = object()


class FileStateBackend(StateBackend):
    """JSON-file-backed state: every mutation rewrites the file
    atomically (tmp + rename), so a relaunched master resumes from the
    last consistent snapshot. Values must be JSON-serializable."""

    def __init__(self, path: str, flush_every: float = 0.0):
        self._path = path
        self._lock = threading.Lock()
        self._flush_every = flush_every
        self._last_flush = 0.0
        self._data: Dict[str, Any] = {}
        if os.path.exists(path):
            try:
                with open(path) as f:
                    self._data = json.load(f)
            except (OSError, ValueError):
                self._data = {}

    def _flush_locked(self, force: bool = False):
        now = time.time()
        if not force and self._flush_every and (
            now - self._last_flush < self._flush_every
        ):
            return
        tmp = f"{self._path}.tmp"
        with open(tmp, "w") as f:
            json.dump(self._data, f)
        os.replace(tmp, self._path)
        self._last_flush = now

    def set(self, key, value):
        json.dumps(value)  # fail fast on non-serializable values
        with self._lock:
            self._data[key] = value
            self._flush_locked()

    def get(self, key, default=None):
        with self._lock:
            return self._data.get(key, default)

    def delete(self, key):
        with self._lock:
            existed = self._data.pop(key, _MISSING) is not _MISSING
            if existed:
                self._flush_locked()
            return existed

    def keys(self, prefix=""):
        with self._lock:
            return [k for k in self._data if k.startswith(prefix)]

    def flush(self):
        with self._lock:
            self._flush_locked(force=True)


class StoreManager:
    """Backend registry/factory (reference store_mananger.py): named
    stores, each independently backed."""

    _lock = threading.Lock()
    _stores: ClassVar[Dict[str, StateBackend]] = {}

    @classmethod
    def build_store(cls, name: str, backend: str = "memory",
                    path: str = "") -> StateBackend:
        with cls._lock:
            if name in cls._stores:
                existing = cls._stores[name]
                wanted = (
                    FileStateBackend if backend == "file"
                    else MemoryStateBackend
                )
                if not isinstance(existing, wanted):
                    raise ValueError(
                        f"store {name!r} already exists with backend "
                        f"{type(existing).__name__}, requested {backend!r}"
                    )
                return existing
            if backend == "memory":
                store: StateBackend = MemoryStateBackend()
            elif backend == "file":
                if not path:
                    raise ValueError("file backend requires path")
                store = FileStateBackend(path)
            else:
                raise ValueError(f"unknown state backend {backend!r}")
            cls._stores[name] = store
            return store

    @classmethod
    def get_store(cls, name: str) -> Optional[StateBackend]:
        with cls._lock:
            return cls._stores.get(name)

    @classmethod
    def reset(cls):
        with cls._lock:
            cls._stores.clear()
