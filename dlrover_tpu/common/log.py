"""Shared logger (role parity: ``dlrover/python/common/log.py``)."""

import logging
import os
import sys

_FORMAT = "[%(asctime)s] [%(levelname)s] [%(name)s:%(lineno)d] %(message)s"


def _build_logger() -> logging.Logger:
    logger = logging.getLogger("dlrover_tpu")
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT))
        logger.addHandler(handler)
        level = os.environ.get("DLROVER_TPU_LOG_LEVEL", "INFO").upper()
        if level not in logging._nameToLevel:
            level = "INFO"
        logger.setLevel(level)
        logger.propagate = False
    return logger


default_logger = _build_logger()


def get_logger(name: str = "") -> logging.Logger:
    if not name:
        return default_logger
    return default_logger.getChild(name)
