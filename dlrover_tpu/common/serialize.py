"""Dataclass <-> JSON wire codec.

The reference speaks protobuf (``dlrover/proto/elastic_training.proto``); we
frame registered ``@dataclass`` messages as JSON instead, which keeps the
control plane free of a codegen step while staying debuggable on the wire.
Only registered message classes deserialize — unknown types raise — so the
surface is closed like a .proto file.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, Type

_MESSAGE_REGISTRY: Dict[str, Type] = {}


def message(cls):
    """Class decorator: make a dataclass a wire message."""
    cls = dataclasses.dataclass(cls)
    _MESSAGE_REGISTRY[cls.__name__] = cls
    return cls


def registered_messages() -> Dict[str, Type]:
    return dict(_MESSAGE_REGISTRY)


def _encode_value(value: Any) -> Any:
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        name = type(value).__name__
        if _MESSAGE_REGISTRY.get(name) is not type(value):
            raise ValueError(
                f"{name} is not a registered wire message; decorate it with "
                "@serialize.message to send it"
            )
        out = {"__type__": name}
        for f in dataclasses.fields(value):
            out[f.name] = _encode_value(getattr(value, f.name))
        return out
    if isinstance(value, dict):
        # JSON keys must be strings; tag int-keyed dicts so they round-trip
        # (rendezvous worlds are {node_rank: local_world_size}).
        if value and all(isinstance(k, int) for k in value):
            return {"__intkeys__": {str(k): _encode_value(v) for k, v in value.items()}}
        return {str(k): _encode_value(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_encode_value(v) for v in value]
    return value


def _decode_value(value: Any) -> Any:
    if isinstance(value, dict):
        if "__intkeys__" in value:
            return {int(k): _decode_value(v) for k, v in value["__intkeys__"].items()}
        if "__type__" in value:
            name = value["__type__"]
            cls = _MESSAGE_REGISTRY.get(name)
            if cls is None:
                raise ValueError(f"unknown wire message type: {name}")
            kwargs = {
                f.name: _decode_value(value[f.name])
                for f in dataclasses.fields(cls)
                if f.name in value
            }
            return cls(**kwargs)
        return {k: _decode_value(v) for k, v in value.items()}
    if isinstance(value, list):
        return [_decode_value(v) for v in value]
    return value


def dumps(msg: Any) -> bytes:
    return json.dumps(_encode_value(msg), separators=(",", ":")).encode("utf-8")


def loads(data: bytes) -> Any:
    return _decode_value(json.loads(data.decode("utf-8")))
