"""Shared binary tensor framing.

One codec for every place the framework moves dicts of numpy arrays as raw
bytes — the shm data ring (``native/shm_ring.py``) and the PS data plane
(``ps/wire.py``). Layout::

    [4-byte big-endian header length][header JSON][buf0][buf1]...

Header::

    {"meta": {...}, "tensors": [{"name","dtype","shape","nbytes"}, ...]}

No base64, no copies beyond the single ``b"".join`` on pack; unpack is
zero-copy ``frombuffer`` views unless ``copy=True`` (required when the
backing buffer is a reused shm slot).
"""

from __future__ import annotations

import json
import struct
from typing import Any, Dict, Tuple

import numpy as np

_LEN = struct.Struct(">I")


def pack_frame(meta: Dict[str, Any],
               tensors: Dict[str, np.ndarray] | None = None) -> bytes:
    tensors = tensors or {}
    manifest = []
    bufs = []
    for name in sorted(tensors):
        arr = np.ascontiguousarray(tensors[name])
        manifest.append({
            "name": name,
            "dtype": arr.dtype.str,
            "shape": list(arr.shape),
            "nbytes": arr.nbytes,
        })
        bufs.append(arr.tobytes())
    header = json.dumps({"meta": meta, "tensors": manifest}).encode()
    return b"".join([_LEN.pack(len(header)), header] + bufs)


def unpack_frame(frame, copy: bool = False
                 ) -> Tuple[Dict[str, Any], Dict[str, np.ndarray]]:
    """``frame``: bytes or memoryview. ``copy=True`` materializes each
    array (use when the buffer will be overwritten, e.g. shm ring slots)."""
    view = memoryview(frame)
    (hlen,) = _LEN.unpack_from(view, 0)
    header = json.loads(bytes(view[4:4 + hlen]))
    tensors: Dict[str, np.ndarray] = {}
    offset = 4 + hlen
    for entry in header["tensors"]:
        n = entry["nbytes"]
        arr = np.frombuffer(
            view[offset:offset + n], dtype=np.dtype(entry["dtype"])
        ).reshape(entry["shape"])
        tensors[entry["name"]] = arr.copy() if copy else arr
        offset += n
    return header["meta"], tensors
