"""Node status state machine.

Role parity: ``dlrover/python/master/node/status_flow.py`` — a transition
table that tells the job manager which (from, to) edges are legal and whether
an edge should trigger a relaunch decision.
"""

from __future__ import annotations

from dataclasses import dataclass

from dlrover_tpu.common.constants import NodeStatus


@dataclass(frozen=True)
class NodeStateFlow:
    from_status: str
    to_status: str
    should_relaunch: bool


ALLOWED_TRANSITIONS = [
    NodeStateFlow(NodeStatus.INITIAL, NodeStatus.PENDING, False),
    NodeStateFlow(NodeStatus.INITIAL, NodeStatus.RUNNING, False),
    NodeStateFlow(NodeStatus.INITIAL, NodeStatus.SUCCEEDED, False),
    NodeStateFlow(NodeStatus.INITIAL, NodeStatus.FAILED, True),
    NodeStateFlow(NodeStatus.INITIAL, NodeStatus.DELETED, True),
    NodeStateFlow(NodeStatus.PENDING, NodeStatus.RUNNING, False),
    NodeStateFlow(NodeStatus.PENDING, NodeStatus.SUCCEEDED, False),
    NodeStateFlow(NodeStatus.PENDING, NodeStatus.FAILED, True),
    NodeStateFlow(NodeStatus.PENDING, NodeStatus.DELETED, True),
    NodeStateFlow(NodeStatus.RUNNING, NodeStatus.SUCCEEDED, False),
    NodeStateFlow(NodeStatus.RUNNING, NodeStatus.FAILED, True),
    NodeStateFlow(NodeStatus.RUNNING, NodeStatus.DELETED, True),
    NodeStateFlow(NodeStatus.RUNNING, NodeStatus.BREAKDOWN, True),
    NodeStateFlow(NodeStatus.BREAKDOWN, NodeStatus.DELETED, True),
    NodeStateFlow(NodeStatus.SUCCEEDED, NodeStatus.DELETED, False),
    NodeStateFlow(NodeStatus.FAILED, NodeStatus.DELETED, False),
]

_TRANSITION_INDEX = {
    (t.from_status, t.to_status): t for t in ALLOWED_TRANSITIONS
}


def get_node_state_flow(from_status: str, to_status: str):
    """Return the flow for a transition, or None if it is not allowed.

    Same-status events are ignored (None); arriving at DELETED from an
    unknown intermediate state is always allowed (pods can vanish from any
    state) and triggers a relaunch decision unless the node already ended.
    """
    if from_status == to_status:
        return None
    flow = _TRANSITION_INDEX.get((from_status, to_status))
    if flow is not None:
        return flow
    if to_status == NodeStatus.DELETED:
        ended = from_status in NodeStatus.end_states()
        return NodeStateFlow(from_status, to_status, not ended)
    return None
