"""GPT-2 decoder (the nanoGPT-parity model, BASELINE config #2).

Functional init/apply in the same style as ``models.llama``: scan over
stacked layers, learned positional embeddings, pre-LN blocks, GELU MLP,
weight-tied LM head (nanoGPT convention).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, replace
from functools import partial
from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from dlrover_tpu.models.losses import masked_lm_loss
from jax.ad_checkpoint import checkpoint_name

from dlrover_tpu.ops.attention_ref import mha_reference
from dlrover_tpu.ops.flash_attention import flash_attention_auto
from dlrover_tpu.ops.remat import apply_remat, remat_enabled


@dataclass(frozen=True)
class GPT2Config:
    vocab_size: int = 50304  # nanoGPT pads 50257 up for tiling
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    max_seq_len: int = 1024
    ln_eps: float = 1e-5
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    remat_policy: str = "dots_saveable"
    use_flash: bool = True

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


def gpt2_124m(**overrides) -> GPT2Config:
    return replace(GPT2Config(), **overrides)


def gpt2_tiny(**overrides) -> GPT2Config:
    return replace(
        GPT2Config(vocab_size=256, hidden_size=64, num_layers=2,
                   num_heads=4, max_seq_len=128,
                   compute_dtype=jnp.float32, use_flash=False),
        **overrides,
    )


def init(rng: jax.Array, config: GPT2Config) -> Dict:
    c = config
    dt = c.param_dtype
    keys = iter(jax.random.split(rng, 12))
    l, d = c.num_layers, c.hidden_size
    std = 0.02

    def normal(key, shape, scale=std):
        return jax.random.normal(key, shape, dt) * scale

    return {
        "embed_tokens": {"embedding": normal(next(keys), (c.vocab_size, d))},
        "embed_pos": {"embedding": normal(next(keys), (c.max_seq_len, d))},
        "layers": {
            "ln_1": {"scale": jnp.ones((l, d), dt),
                     "bias": jnp.zeros((l, d), dt)},
            "q_proj": {"kernel": normal(next(keys), (l, d, d))},
            "k_proj": {"kernel": normal(next(keys), (l, d, d))},
            "v_proj": {"kernel": normal(next(keys), (l, d, d))},
            # gpt2 residual-scaled init
            "o_proj": {"kernel": normal(next(keys), (l, d, d),
                                        std / math.sqrt(2 * l))},
            "ln_2": {"scale": jnp.ones((l, d), dt),
                     "bias": jnp.zeros((l, d), dt)},
            "up_proj": {"kernel": normal(next(keys), (l, d, 4 * d)),
                        "bias": jnp.zeros((l, 4 * d), dt)},
            "down_proj": {"kernel": normal(next(keys), (l, 4 * d, d),
                                           std / math.sqrt(2 * l)),
                          "bias": jnp.zeros((l, d), dt)},
        },
        "ln_f": {"scale": jnp.ones((d,), dt), "bias": jnp.zeros((d,), dt)},
    }


def _layer_norm(x, scale, bias, eps):
    xf = x.astype(jnp.float32)
    mean = xf.mean(axis=-1, keepdims=True)
    var = ((xf - mean) ** 2).mean(axis=-1, keepdims=True)
    return ((xf - mean) * lax.rsqrt(var + eps)).astype(x.dtype) * scale + bias


def _block(c: GPT2Config):
    """Scan body over stacked layer params; shared by the plain and the
    pipelined forward so the two cannot drift (shapes read from the
    running activation, which is the microbatch inside a pipeline)."""

    def block(x, layer):
        b, s = x.shape[0], x.shape[1]
        h = _layer_norm(x, layer["ln_1"]["scale"], layer["ln_1"]["bias"],
                        c.ln_eps)
        q = (h @ layer["q_proj"]["kernel"]).reshape(b, s, c.num_heads,
                                                    c.head_dim)
        k = (h @ layer["k_proj"]["kernel"]).reshape(b, s, c.num_heads,
                                                    c.head_dim)
        v = (h @ layer["v_proj"]["kernel"]).reshape(b, s, c.num_heads,
                                                    c.head_dim)
        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
        if c.use_flash:
            attn = flash_attention_auto(q, k, v, True)
        else:
            attn = mha_reference(q, k, v, causal=True)
        # named for the "attn_saveable" remat policy (which otherwise
        # silently saves nothing for this family)
        attn = checkpoint_name(attn, "attn_out")
        attn = attn.transpose(0, 2, 1, 3).reshape(b, s, c.hidden_size)
        x = x + attn @ layer["o_proj"]["kernel"]
        h = _layer_norm(x, layer["ln_2"]["scale"], layer["ln_2"]["bias"],
                        c.ln_eps)
        h = jax.nn.gelu(h @ layer["up_proj"]["kernel"]
                        + layer["up_proj"]["bias"])
        x = x + h @ layer["down_proj"]["kernel"] + layer["down_proj"]["bias"]
        return x, None

    return block


def apply(params: Dict, input_ids: jax.Array, config: GPT2Config,
          rng: Optional[jax.Array] = None) -> jax.Array:
    """Returns logits [B, S, V] (f32); LM head tied to token embedding."""
    c = config
    s = input_ids.shape[1]
    x = params["embed_tokens"]["embedding"][input_ids]
    x = x + params["embed_pos"]["embedding"][:s][None]
    x = x.astype(c.compute_dtype)

    block = apply_remat(_block(c), c.remat_policy)
    x, _ = lax.scan(block, x, params["layers"])
    x = _layer_norm(x, params["ln_f"]["scale"], params["ln_f"]["bias"],
                    c.ln_eps)
    logits = x @ params["embed_tokens"]["embedding"].astype(
        c.compute_dtype).T
    return logits.astype(jnp.float32)


def apply_pipelined(
    params: Dict,
    input_ids: jax.Array,
    config: GPT2Config,
    num_stages: int,
    num_microbatches: int,
    num_virtual: int = 1,
    stage_depths: Optional[Sequence[int]] = None,
) -> jax.Array:
    """Forward pass with the GPT-2 blocks as a GPipe / interleaved
    pipeline over the "pipe" mesh axis, same formulation as the other
    decoder families (``models.llama.apply_pipelined``): embed and the
    tied final-norm/head stay outside in the surrounding GSPMD program
    (the head spread over pipe). Use with the "gpt2_pp" rule set.
    ``stage_depths``: uneven per-chunk layer counts in visit order."""
    from dlrover_tpu.parallel.pipeline import (
        dispatch_pipeline,
        masked_layer_scan,
        merge_microbatches,
        pipe_batch_constraint,
        split_microbatches,
    )

    c = config
    s = input_ids.shape[1]
    x = params["embed_tokens"]["embedding"][input_ids]
    x = x + params["embed_pos"]["embedding"][:s][None]
    x = x.astype(c.compute_dtype)

    def stage_fn(chunk_and_mask, x):
        layers_chunk, mask = chunk_and_mask
        block = apply_remat(_block(c), c.remat_policy)
        return masked_layer_scan(block, x, layers_chunk, mask)

    x_mb = split_microbatches(x, num_microbatches)
    out_mb = dispatch_pipeline(
        stage_fn, params["layers"], x_mb,
        num_stages, num_virtual, stage_depths,
        remat_stage=remat_enabled(c.remat_policy),
    )
    x = merge_microbatches(out_mb)

    x = pipe_batch_constraint(x)
    x = _layer_norm(x, params["ln_f"]["scale"], params["ln_f"]["bias"],
                    c.ln_eps)
    logits = x @ params["embed_tokens"]["embedding"].astype(
        c.compute_dtype).T
    return logits.astype(jnp.float32)


def make_init_fn(config: GPT2Config):
    return partial(init, config=config)


def make_loss_fn(config: GPT2Config):
    def loss_fn(params, batch, rng):
        logits = apply(params, batch["input_ids"], config, rng)
        return masked_lm_loss(logits, batch["labels"]), {}

    return loss_fn
