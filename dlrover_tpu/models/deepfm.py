"""DeepFM for CTR prediction (BASELINE config #4: Criteo with sparse
embeddings fed by the dynamic DataShardService).

TPU-first notes: the embedding table is the dominant memory consumer; its
rows are sharded on the fsdp axis (FSDP_AUTO picks the vocab dim) and the
gather lowers to an all-gather-free dynamic-slice pattern under GSPMD. The
reference serves this family through TF PS jobs (`dlrover/trainer/
tensorflow/`); here it is the same SPMD path as every other model.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from functools import partial
from typing import Dict, Sequence

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class DeepFMConfig:
    num_sparse_features: int = 26  # criteo categorical fields
    num_dense_features: int = 13  # criteo continuous fields
    vocab_size: int = 100000  # hashed feature space (per-table unified)
    embed_dim: int = 16
    mlp_dims: Sequence[int] = (400, 400, 400)


def criteo_deepfm(**overrides) -> DeepFMConfig:
    return replace(DeepFMConfig(), **overrides)


def deepfm_tiny(**overrides) -> DeepFMConfig:
    return replace(
        DeepFMConfig(num_sparse_features=4, num_dense_features=3,
                     vocab_size=128, embed_dim=8, mlp_dims=(32, 16)),
        **overrides,
    )


def init(rng: jax.Array, config: DeepFMConfig) -> Dict:
    c = config
    keys = iter(jax.random.split(rng, 4 + len(c.mlp_dims)))
    params: Dict = {
        # second-order FM embeddings [V, K] + first-order weights [V, 1]
        "embedding": {"table": jax.random.normal(
            next(keys), (c.vocab_size, c.embed_dim)) * 0.01},
        "linear": {"table": jax.random.normal(
            next(keys), (c.vocab_size, 1)) * 0.01},
        "dense_proj": {"kernel": jax.random.normal(
            next(keys), (c.num_dense_features, c.embed_dim)) * 0.05},
    }
    in_dim = (c.num_sparse_features + 1) * c.embed_dim
    mlp = {}
    for i, out_dim in enumerate(c.mlp_dims):
        mlp[f"dense{i}"] = {
            "kernel": jax.random.normal(next(keys), (in_dim, out_dim)) * (
                1.0 / jnp.sqrt(in_dim)),
            "bias": jnp.zeros((out_dim,)),
        }
        in_dim = out_dim
    mlp["out"] = {
        "kernel": jax.random.normal(next(keys), (in_dim, 1)) * 0.05,
        "bias": jnp.zeros((1,)),
    }
    params["mlp"] = mlp
    return params


def apply(params: Dict, sparse_ids: jax.Array,
          dense_values: jax.Array) -> jax.Array:
    """sparse_ids: [B, F_s] hashed ids; dense_values: [B, F_d].
    Returns logits [B] (pre-sigmoid CTR)."""
    emb = params["embedding"]["table"][sparse_ids]  # [B, F_s, K]
    dense_emb = (
        dense_values[:, :, None] * params["dense_proj"]["kernel"][None]
    ).sum(axis=1, keepdims=True)  # [B, 1, K]
    fields = jnp.concatenate([emb, dense_emb], axis=1)  # [B, F_s+1, K]

    # first order
    first = params["linear"]["table"][sparse_ids][..., 0].sum(axis=1)

    # second order FM: 0.5 * ((sum v)^2 - sum v^2)
    summed = fields.sum(axis=1)
    fm = 0.5 * ((summed ** 2) - (fields ** 2).sum(axis=1)).sum(axis=-1)

    # deep part
    x = fields.reshape(fields.shape[0], -1)
    mlp = params["mlp"]
    i = 0
    while f"dense{i}" in mlp:
        x = jax.nn.relu(x @ mlp[f"dense{i}"]["kernel"]
                        + mlp[f"dense{i}"]["bias"])
        i += 1
    deep = (x @ mlp["out"]["kernel"] + mlp["out"]["bias"])[:, 0]
    return first + fm + deep


def make_init_fn(config: DeepFMConfig):
    return partial(init, config=config)


def make_loss_fn(config: DeepFMConfig):
    def loss_fn(params, batch, rng):
        logits = apply(params, batch["sparse"], batch["dense"])
        labels = batch["label"].astype(jnp.float32)
        loss = jnp.mean(
            jnp.maximum(logits, 0) - logits * labels
            + jnp.log1p(jnp.exp(-jnp.abs(logits)))
        )
        auc_proxy = ((logits > 0) == (labels > 0.5)).mean()
        return loss, {"accuracy": auc_proxy}

    return loss_fn
