"""Llama-family decoder (the flagship model of this framework).

Role parity: the reference accelerates HF Llama through module surgery
(``atorch/modules/transformer/layers.py:1268`` LlamaAttentionFA swap-in,
Megatron TP rewrites, FSDP wrapping). Here the model is written TPU-first:

  * functional init/apply (no module tree) so every parameter path has a
    sharding rule (``parallel.sharding_rules.llama_rules``);
  * **scan over layers**: layer params are stacked [L, ...] and the block
    runs under ``lax.scan`` — one layer's XLA program compiled once,
    which keeps 7B-scale compile times sane and makes remat-per-layer
    trivial;
  * attention via the in-tree Pallas flash kernel (TPU) or the XLA
    reference (CPU tests), with an optional ring-attention path over the
    "seq" mesh axis for long context;
  * optional switch-MoE FFN (expert parallelism over the expert submesh).

Numerics follow Llama-2: RMSNorm (f32), RoPE, GQA, SwiGLU, untied head.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, replace
from functools import partial
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name

from dlrover_tpu.models.common import (
    cast_floats,
    dense_init as _dense,
    param_count as common_param_count,
    rms_norm as _rms_norm,
    segment_positions,
)
from dlrover_tpu.models.losses import chunked_lm_head_loss, masked_lm_loss
from dlrover_tpu.ops import moe as moe_ops
from dlrover_tpu.ops.attention_ref import mha_reference
from dlrover_tpu.ops.flash_attention import flash_attention_auto
from dlrover_tpu.ops.remat import apply_remat, remat_enabled
from dlrover_tpu.ops.ring_attention import (
    ring_attention,
    ring_attention_local,
)


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    remat_policy: str = "dots_saveable"
    use_flash: bool = True  # pallas kernel on TPU; reference otherwise
    # pallas flash kernel tiling (VMEM working-set vs grid overhead
    # trade; sweepable via bench BENCH_BLOCK_Q/BENCH_BLOCK_K)
    flash_block_q: int = 512
    flash_block_k: int = 1024
    # backward-kernel tiles (0 = same as forward): the dKV/dQ passes
    # hold more live VMEM than the forward, so their optimum is often
    # smaller — a long-context tuning lever
    flash_block_q_bwd: int = 0
    flash_block_k_bwd: int = 0
    # None = auto (interpret off TPU); False forces the Mosaic kernel —
    # required when TRACING on a CPU host but COMPILING for a deviceless
    # TPU topology (parallel.aot), where the backend-sniffing default
    # would silently lower the interpreter emulation
    flash_interpret: Any = None
    # sequence parallelism: set seq_axis="seq" and pass the Mesh to run
    # ring attention (shard_map) inside the jitted GSPMD program; with
    # mesh=None the model must itself be running under shard_map.
    seq_axis: Optional[str] = None
    mesh: Any = None
    # MoE (0 = dense)
    num_experts: int = 0
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # "gather" (fast, capacity) | "einsum" (reference oracle) |
    # "grouped" (dropless Pallas kernel — per-shard experts) |
    # "grouped_ep" (dropless + expert-parallel: shard_map + all_to_all
    # over the ``moe_ep_axes`` expert submesh; pair with the "moe_ep"
    # rule set so expert weights shard where the all-to-all lands them)
    moe_dispatch: str = "gather"
    # "grouped_ep" only: the expert submesh axes. Defaults to the
    # canonical (data x fsdp) expert submesh; the mesh itself resolves
    # ambiently per accelerate (elastic-safe), or from ``mesh`` above.
    moe_ep_axes: Tuple[str, ...] = ("data", "fsdp")
    # "grouped_ep" only: chunked double-buffered dispatch — split the
    # row exchange into this many ppermute-ring chunks so the grouped
    # GEMM overlaps the in-flight exchange (ops.moe). 0 = resolve the
    # Context knob (``dispatch_chunks``) at trace time, which is how
    # the runtime optimizer's chosen chunking reaches a retuned program.
    moe_dispatch_chunks: int = 0
    # "grouped_ep" only: the wire precision of the row exchanges —
    # "bf16" | "fp8" (block-scaled e4m3 + f32 scales, ~half the wire
    # bytes) | "fp8_qdq" (the bitwise reference oracle). "" = resolve
    # the Context knob (``moe_precision``) at trace time, the same
    # retune-without-rebuild contract as the chunk knob (ops.moe).
    moe_precision: str = ""
    # FSDP layer prefetch: gather layer l+1's params while layer l
    # computes (double-buffered carry through the scan-over-layers).
    # None = the Context knob (``fsdp_prefetch``). Same math, but the
    # scan(L-1)+epilogue restructure changes fusion/reduction order, so
    # results match the plain scan to float roundoff, not bitwise.
    fsdp_prefetch: Any = None
    # dense FSDP wire precision: what the per-layer param gathers of
    # the scan-over-layers ship — "bf16" (the param dtype, today's
    # wire), "fp8" (the stacked per-layer weight matrices quantize to
    # block-scaled e4m3 + f32 scales BEFORE the scan, the layer slice
    # moves quantized, and dequant happens at consumption inside the
    # block — ~1/4 of the f32 wire) or "fp8_qdq" (the bitwise
    # reference oracle: the identical quantize->dequantize applied to
    # the stack, with the wire itself left at full precision). A pure-
    # forward transform: gradients pass straight through to the
    # original params (the gather is dequant-exact, so no error
    # feedback is needed — unlike the gradient direction, see
    # ``parallel.accelerate``). "" = resolve the Context knob
    # (``fsdp_precision``) at TRACE time, the retune-without-rebuild
    # contract shared with moe_precision/dispatch_chunks.
    fsdp_precision: str = ""

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


def llama2_7b(**overrides) -> LlamaConfig:
    return replace(LlamaConfig(), **overrides)


def llama2_13b(**overrides) -> LlamaConfig:
    return replace(
        LlamaConfig(hidden_size=5120, intermediate_size=13824,
                    num_layers=40, num_heads=40, num_kv_heads=40),
        **overrides,
    )


def llama3_8b(**overrides) -> LlamaConfig:
    """Llama-3-8B shape: GQA 32/8, 128k vocab, theta 5e5."""
    return replace(
        LlamaConfig(vocab_size=128256, hidden_size=4096,
                    intermediate_size=14336, num_layers=32,
                    num_heads=32, num_kv_heads=8, max_seq_len=8192,
                    rope_theta=500000.0),
        **overrides,
    )


def llama3_70b(**overrides) -> LlamaConfig:
    return replace(
        LlamaConfig(vocab_size=128256, hidden_size=8192,
                    intermediate_size=28672, num_layers=80,
                    num_heads=64, num_kv_heads=8, max_seq_len=8192,
                    rope_theta=500000.0),
        **overrides,
    )


def llama_tiny(**overrides) -> LlamaConfig:
    """Test-scale config (runs on the 8-device CPU mesh)."""
    return replace(
        LlamaConfig(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128,
            compute_dtype=jnp.float32, use_flash=False,
        ),
        **overrides,
    )


# -- init -------------------------------------------------------------------


def init(rng: jax.Array, config: LlamaConfig) -> Dict:
    c = config
    dt = c.param_dtype
    keys = iter(jax.random.split(rng, 16))
    l, d, f = c.num_layers, c.hidden_size, c.intermediate_size
    h, kv, hd = c.num_heads, c.num_kv_heads, c.head_dim

    layers = {
        "input_norm": {"scale": jnp.ones((l, d), dt)},
        "q_proj": {"kernel": _dense(next(keys), (l, d, h * hd), dt)},
        "k_proj": {"kernel": _dense(next(keys), (l, d, kv * hd), dt)},
        "v_proj": {"kernel": _dense(next(keys), (l, d, kv * hd), dt)},
        "o_proj": {"kernel": _dense(next(keys), (l, h * hd, d), dt)},
        "post_norm": {"scale": jnp.ones((l, d), dt)},
    }
    if c.num_experts > 0:
        e = c.num_experts
        layers["router"] = {
            "kernel": _dense(next(keys), (l, d, e), dt)
        }
        layers["experts"] = {
            "up": {"kernel": _dense(next(keys), (l, e, d, f), dt)},
            "down": {"kernel": _dense(
                next(keys), (l, e, f, d), dt, scale=1.0 / math.sqrt(f))},
        }
    else:
        layers["gate_proj"] = {"kernel": _dense(next(keys), (l, d, f), dt)}
        layers["up_proj"] = {"kernel": _dense(next(keys), (l, d, f), dt)}
        layers["down_proj"] = {
            "kernel": _dense(next(keys), (l, f, d), dt,
                             scale=1.0 / math.sqrt(f))
        }

    return {
        "embed_tokens": {
            "embedding": jax.random.normal(
                next(keys), (c.vocab_size, d), dt) * 0.02,
        },
        "layers": layers,
        "norm": {"scale": jnp.ones((d,), dt)},
        "lm_head": {"kernel": _dense(next(keys), (d, c.vocab_size), dt)},
    }


# -- forward ----------------------------------------------------------------


def _rope(x, positions, theta):
    """x: [B, S, H, Dh]; rotate pairs (even, odd halves)."""
    b, s, h, hd = x.shape
    half = hd // 2
    freqs = jnp.exp(
        -math.log(theta) * jnp.arange(0, half, dtype=jnp.float32) / half
    )
    angles = positions[:, :, None].astype(jnp.float32) * freqs[None, None, :]
    cos = jnp.cos(angles)[:, :, None, :]  # [B, S, 1, half]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return rotated.astype(x.dtype)


def _ring_impl(c: LlamaConfig):
    """See ``ops.ring_attention.impl_from_flags`` — the shared mapping
    from (use_flash, flash_interpret) to the ring impl selector."""
    from dlrover_tpu.ops.ring_attention import impl_from_flags

    return impl_from_flags(c.use_flash, c.flash_interpret)


def _attention_block(x, layer, config: LlamaConfig, positions,
                     segment_ids=None, return_kv: bool = False):
    c = config
    b, s, d = x.shape
    h, kv, hd = c.num_heads, c.num_kv_heads, c.head_dim
    q = (x @ layer["q_proj"]["kernel"]).reshape(b, s, h, hd)
    k = (x @ layer["k_proj"]["kernel"]).reshape(b, s, kv, hd)
    v = (x @ layer["v_proj"]["kernel"]).reshape(b, s, kv, hd)
    q = _rope(q, positions, c.rope_theta)
    k = _rope(k, positions, c.rope_theta)
    # serving prefill captures the post-RoPE K/V — exactly what the
    # decode steps will read back from the KV pages
    kv_out = (k, v) if return_kv else None
    # GQA kv heads are NOT repeated: the flash/ring kernels index the
    # shared KV head per query group, so HBM holds (and the ring
    # rotates) only the kv heads — h/kv less traffic than the repeat
    # the reference pays before its CUDA kernel (layers.py:1268).
    q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))  # [B,H,S,Dh]
    ring_mesh = None
    if c.seq_axis:
        # an explicit config mesh wins; otherwise the AMBIENT mesh
        # (rebuilt by every accelerate) keeps ring configs elastic-safe
        from dlrover_tpu.ops.ring_attention import ambient_ring_mesh

        ring_mesh = (c.mesh if c.mesh is not None
                     else ambient_ring_mesh(c.seq_axis))
    if segment_ids is not None:
        # packed sequences: per-document masking fused into the kernel;
        # under sequence parallelism the segment ids ride the ring with
        # the KV shards (documents may span ring shards)
        if c.seq_axis and ring_mesh is not None:
            out = ring_attention(
                q, k, v, ring_mesh, axis_name=c.seq_axis,
                causal=True,
                batch_axes=("data", "fsdp"), head_axis="tensor",
                block_q=c.flash_block_q, block_k=c.flash_block_k,
                segment_ids=segment_ids, impl=_ring_impl(c),
                block_q_bwd=c.flash_block_q_bwd,
                block_k_bwd=c.flash_block_k_bwd,
            )
        elif c.seq_axis:
            out = ring_attention_local(
                q, k, v, axis_name=c.seq_axis, causal=True,
                block_q=c.flash_block_q, block_k=c.flash_block_k,
                segment_ids=segment_ids, impl=_ring_impl(c),
                block_q_bwd=c.flash_block_q_bwd,
                block_k_bwd=c.flash_block_k_bwd,
            )
        else:
            from dlrover_tpu.ops.flash_attention import (
                segmented_attention,
            )

            out = segmented_attention(
                q, k, v, segment_ids, c.use_flash,
                block_q=c.flash_block_q, block_k=c.flash_block_k,
                interpret=c.flash_interpret,
                block_q_bwd=c.flash_block_q_bwd,
                block_k_bwd=c.flash_block_k_bwd,
            )
    elif c.seq_axis and ring_mesh is not None:
        out = ring_attention(
            q, k, v, ring_mesh, axis_name=c.seq_axis, causal=True,
            batch_axes=("data", "fsdp"), head_axis="tensor",
            block_q=c.flash_block_q, block_k=c.flash_block_k,
            impl=_ring_impl(c),
            block_q_bwd=c.flash_block_q_bwd,
            block_k_bwd=c.flash_block_k_bwd,
        )
    elif c.seq_axis:
        out = ring_attention_local(q, k, v, axis_name=c.seq_axis,
                                   causal=True,
                                   block_q=c.flash_block_q,
                                   block_k=c.flash_block_k,
                                   impl=_ring_impl(c),
                                   block_q_bwd=c.flash_block_q_bwd,
                                   block_k_bwd=c.flash_block_k_bwd)
    elif c.use_flash:
        # auto-routes through shard_map under a non-trivial mesh (GSPMD
        # cannot partition the Mosaic call itself)
        out = flash_attention_auto(q, k, v, True,
                                   block_q=c.flash_block_q,
                                   block_k=c.flash_block_k,
                                   interpret=c.flash_interpret,
                                   block_q_bwd=c.flash_block_q_bwd,
                                   block_k_bwd=c.flash_block_k_bwd)
    else:
        out = mha_reference(q, k, v, causal=True)
    out = checkpoint_name(out, "attn_out")
    out = out.transpose(0, 2, 1, 3).reshape(b, s, h * hd)
    out = out @ layer["o_proj"]["kernel"]
    if return_kv:
        return out, kv_out
    return out


def _ffn_block(x, layer, config: LlamaConfig, rng):
    """Returns (out, aux_loss, dropped_frac, expert_load) — the last two
    are the MoE load-balance observability signals (zeros for dense)."""
    if config.num_experts > 0:
        moe_params = {
            "router": layer["router"],
            "experts": {
                "up": layer["experts"]["up"],
                "down": layer["experts"]["down"],
            },
        }
        cfg = moe_ops.MoEConfig(
            num_experts=config.num_experts,
            capacity_factor=config.moe_capacity_factor,
            top_k=config.moe_top_k,
            dispatch=config.moe_dispatch,
            # the grouped kernel follows the flash knob: False forces
            # Mosaic (deviceless-AOT tracing), None auto-detects
            kernel_interpret=config.flash_interpret,
            # grouped_ep: an explicit config mesh wins; otherwise the
            # AMBIENT mesh (rebuilt by every accelerate) keeps the
            # expert-parallel shard_map elastic-safe, mirroring the
            # ring-attention mesh convention above
            ep_axes=tuple(config.moe_ep_axes),
            mesh=config.mesh,
            dispatch_chunks=config.moe_dispatch_chunks,
            precision=config.moe_precision,
        )
        out, aux, metrics = moe_ops.moe_ffn(
            moe_params, x, cfg, activation=jax.nn.silu, rng=rng
        )
        return out, aux, metrics["dropped_frac"], metrics["expert_load"]
    gate = jax.nn.silu(x @ layer["gate_proj"]["kernel"])
    up = x @ layer["up_proj"]["kernel"]
    zero = jnp.zeros((), jnp.float32)
    return ((gate * up) @ layer["down_proj"]["kernel"], zero, zero,
            jnp.zeros((1,), jnp.float32))




def _prefetch_enabled(c: LlamaConfig) -> bool:
    """The FSDP layer-prefetch toggle: the config wins when set, else
    the Context knob (``fsdp_prefetch``) — resolved at TRACE time so a
    re-accelerate picks up a changed knob."""
    if c.fsdp_prefetch is not None:
        return bool(c.fsdp_prefetch)
    from dlrover_tpu.common.config import get_context

    return bool(getattr(get_context(), "fsdp_prefetch", False))


# -- dense FSDP wire (low-precision param gathers) --------------------------


def resolve_fsdp_precision(config: LlamaConfig) -> str:
    """The effective dense-wire precision at TRACE time: an explicit
    ``config.fsdp_precision`` wins; "" resolves the global Context knob
    (``fsdp_precision``) — how the runtime optimizer's chosen precision
    reaches a re-traced program without rebuilding the model config
    (the ``moe_precision`` pattern, ops.moe.resolve_moe_precision). A
    quantized choice degrades to "bf16" (logged, never raised) when the
    backend fails the fp8 capability probe."""
    p = (getattr(config, "fsdp_precision", "") or "").strip()
    if not p:
        from dlrover_tpu.common.config import get_context

        p = str(getattr(get_context(), "fsdp_precision", "bf16")
                or "bf16").strip() or "bf16"
    from dlrover_tpu.ops.quantize import PRECISIONS

    if p not in PRECISIONS:
        raise ValueError(
            f"unknown FSDP wire precision {p!r}; choose one of "
            f"{PRECISIONS}"
        )
    if p != "bf16":
        from dlrover_tpu.ops.shard_compat import fp8_wire_supported

        if not fp8_wire_supported():
            import logging

            logging.getLogger("dlrover_tpu.models.llama").warning(
                "fsdp precision %r requested but the backend fails the "
                "fp8 probe; falling back to the bf16 wire", p,
            )
            return "bf16"
    return p


def _wire_leaf(a) -> bool:
    """Which stacked layer params ride the quantized wire: the rank-3
    per-layer weight matrices ([L, in, out] — the bytes that dominate
    the per-layer gather). Vector params (norm scales, [L, D]) are a
    rounding error of the traffic and stay exact; rank-4 expert
    tensors are consumed shard-local inside the grouped_ep shard_map
    (never gathered), so quantizing them would add drift for zero wire
    win."""
    return (getattr(a, "ndim", 0) == 3
            and jnp.issubdtype(a.dtype, jnp.floating))


def _quantize_layer_stack(layers: Dict, mode: str) -> Dict[str, Dict]:
    """path -> wire form of every wired leaf of the STACKED layer tree.

    Quantization runs on the stacked, still-sharded params (elementwise
    per 32-channel block along the last dim, so it computes shardwise
    and commutes with the per-layer slice the scan takes): the scan's
    xs then carry e4m3 values + f32 scales and the per-layer gather
    moves the quantized bytes. "fp8_qdq" dequantizes here instead —
    identical numbers (slice commutes with the elementwise decode), but
    the wire ships full precision: the dequant-exact oracle the bitwise
    tests pin fp8 against."""
    from dlrover_tpu.ops.quantize import (
        dequantize_block_scaled,
        quantize_block_scaled,
    )

    wire: Dict[str, Dict] = {}
    for path, leaf in _flatten_layers(layers):
        if not _wire_leaf(leaf):
            continue
        v, s = quantize_block_scaled(leaf)
        if mode == "fp8":
            wire[path] = {"v": v, "s": s}
        else:  # fp8_qdq: decode locally, wire at full precision
            wire[path] = {"dq": dequantize_block_scaled(v, s, leaf.dtype)}
    return wire


def _flatten_layers(layers: Dict):
    """(path, leaf) pairs of a nested-dict layer tree, "/"-joined —
    the addressing `_consume_wire` uses to splice dequantized leaves
    back into the per-layer param tree."""
    out = []

    def walk(node, prefix):
        if isinstance(node, dict):
            for k in sorted(node):
                walk(node[k], prefix + (k,))
        else:
            out.append(("/".join(prefix), node))

    walk(layers, ())
    return out


def _set_path(tree: Dict, path: str, value):
    keys = path.split("/")
    node = tree
    for k in keys[:-1]:
        node = node[k]
    node[keys[-1]] = value


def _get_path(tree: Dict, path: str):
    node = tree
    for k in path.split("/"):
        node = node[k]
    return node


@jax.custom_vjp
def _consume_fp8(v, s, w):
    """Dequantize one wired leaf at consumption. ``w`` (the original
    full-precision slice) contributes NO forward value — it exists so
    the backward has a full-precision cotangent path back to the
    stacked params: the transform is pure-forward (straight-through),
    and without this route the scan's xs cotangent would be e4m3,
    which cannot carry a gradient. The forward never reads ``w``, so
    its f32 slice is dead code the compiler drops — the layer gather
    moves only the quantized bytes."""
    from dlrover_tpu.ops.quantize import dequantize_block_scaled

    return dequantize_block_scaled(v, s, w.dtype)


def _consume_fp8_fwd(v, s, w):
    return _consume_fp8(v, s, w), (v, s)


def _consume_fp8_bwd(res, g):
    v, s = res
    return jnp.zeros(v.shape, v.dtype), jnp.zeros(s.shape, s.dtype), g


_consume_fp8.defvjp(_consume_fp8_fwd, _consume_fp8_bwd)


@jax.custom_vjp
def _consume_qdq(dq, w):
    """The fsdp_qdq oracle's consumption: the pre-decoded value, with
    the identical straight-through backward as ``_consume_fp8`` — so
    fp8 and fp8_qdq are bitwise equal fwd AND bwd."""
    return dq


def _consume_qdq_fwd(dq, w):
    return dq, (dq,)


def _consume_qdq_bwd(res, g):
    (dq,) = res
    return jnp.zeros(dq.shape, dq.dtype), g


_consume_qdq.defvjp(_consume_qdq_fwd, _consume_qdq_bwd)


def _consume_wire(wire_slice: Dict[str, Dict], orig_slice: Dict) -> Dict:
    """Per-layer param tree with every wired leaf replaced by its
    dequantized wire form (non-wired leaves come from ``orig_slice``
    untouched)."""
    out = jax.tree.map(lambda x: x, orig_slice)  # fresh containers
    for path, form in wire_slice.items():
        w = _get_path(orig_slice, path)
        if "dq" in form:
            _set_path(out, path, _consume_qdq(form["dq"], w))
        else:
            _set_path(out, path, _consume_fp8(form["v"], form["s"], w))
    return out


def _wire_block(block, wired: bool):
    """Adapter running ``block`` over (wire, orig) xs pairs when the
    quantized wire is active — INSIDE the remat wrapper, so a remat'd
    backward re-derives the dequantized params from the quantized xs
    (the re-gather leg of the backward also moves fp8)."""
    if not wired:
        return block

    def wired_block(carry, xs):
        wire_slice, orig_slice = xs
        return block(carry, _consume_wire(wire_slice, orig_slice))

    return wired_block


def _prefetch_gather(tree):
    """Issue the gather of ONE layer's params now: a sharding
    constraint to replicated over the ambient mesh — exactly the
    all-gather FSDP pays per layer anyway, but as an op with NO data
    dependency on the current layer's compute, so XLA's latency-hiding
    scheduler can run it underneath (the HSDP-paper prefetch,
    PAPERS.md 2602.00277). Values are untouched (a sharding constraint
    never changes numerics); without an ambient mesh this is the
    identity."""
    from jax.sharding import NamedSharding, PartitionSpec

    from dlrover_tpu.ops.shard_compat import ambient_mesh

    mesh = ambient_mesh()
    if mesh is None:
        return tree
    try:
        rep = NamedSharding(mesh, PartitionSpec())
        return jax.tree.map(
            lambda x: jax.lax.with_sharding_constraint(x, rep), tree
        )
    except (ValueError, TypeError):  # mesh flavor unsupported here
        return tree


def _decoder_block(c: LlamaConfig, segment_ids=None, positions=None):
    """Scan body over stacked layer params; shared by the plain and the
    pipelined forward so the two cannot drift. ``positions`` is computed
    ONCE by the caller (it is layer-invariant; inside the scan body it
    would run per layer, and again per layer under remat)."""

    def block(carry, layer_params):
        x, block_rng = carry
        # params may be stored f32; compute in the configured dtype
        layer_params = cast_floats(layer_params, c.compute_dtype)
        pos = positions
        if pos is None:
            pos = jnp.broadcast_to(jnp.arange(x.shape[1]), x.shape[:2])
        block_rng, ffn_rng = jax.random.split(block_rng)
        attn_in = _rms_norm(x, layer_params["input_norm"]["scale"], c.rms_eps)
        x = x + _attention_block(attn_in, layer_params, c, pos,
                                 segment_ids)
        ffn_in = _rms_norm(x, layer_params["post_norm"]["scale"], c.rms_eps)
        ffn_out, aux, dropped, load = _ffn_block(
            ffn_in, layer_params, c, ffn_rng
        )
        return (x + ffn_out, block_rng), (aux, dropped, load)

    return block


def apply_hidden(
    params: Dict, input_ids: jax.Array, config: LlamaConfig,
    rng: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
    with_moe_metrics: bool = False,
):
    """Returns (final hidden states [B, S, D] in compute dtype,
    moe_aux_loss scalar) — everything except the lm head. With
    ``with_moe_metrics`` a third element is returned: the layer-averaged
    load-balance dict {"moe_dropped_frac", "moe_expert_load" [E]}.

    ``segment_ids`` [B, S]: packed-sequence mode — per-document
    attention masking and segment-relative RoPE positions."""
    c = config
    x = params["embed_tokens"]["embedding"][input_ids].astype(c.compute_dtype)
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    positions = (segment_positions(segment_ids)
                 if segment_ids is not None else None)
    wire_mode = resolve_fsdp_precision(c)
    layers = params["layers"]
    wire_stack = (_quantize_layer_stack(layers, wire_mode)
                  if wire_mode != "bf16" else {})
    wired = bool(wire_stack)
    block = apply_remat(
        _wire_block(_decoder_block(c, segment_ids, positions), wired),
        c.remat_policy,
    )
    # wired xs: (quantized wire forms, original tree) — the original
    # rides along as the straight-through gradient route; its wired
    # leaves are never read forward, so only quantized bytes move
    scan_xs = (wire_stack, layers) if wired else layers
    if _prefetch_enabled(c) and c.num_layers >= 2:
        # FSDP layer prefetch: the scan carries layer l's ALREADY
        # GATHERED params and issues layer l+1's gather before the
        # block compute — a double-buffered carry, so the per-layer
        # param all-gather runs under the previous layer's compute
        # instead of on the critical path. The last layer runs as an
        # epilogue (its params were gathered during layer L-2). The
        # gather stays OUTSIDE the remat'd block: the backward re-plays
        # compute, not the exchange schedule. Same blocks, same order,
        # same rng chain — but the restructure changes XLA's fusion /
        # reduction order, so outputs match the plain scan to float
        # roundoff, NOT bitwise (pinned with allclose). On the
        # quantized wire only the WIRE forms ride the prefetched
        # (constraint-issued) gather and the double-buffered carry —
        # dequant still happens at consumption inside the block, and
        # the gradient-route originals stay out of the carry.
        if wired:
            wire_first = jax.tree.map(lambda a: a[0], wire_stack)
            wire_rest = jax.tree.map(lambda a: a[1:], wire_stack)
            orig_head = jax.tree.map(lambda a: a[:-1], layers)
            orig_last = jax.tree.map(lambda a: a[-1], layers)

            def pf_block(carry, xs_i):
                inner, cur_wire = carry
                wire_next, orig_cur = xs_i
                gathered = _prefetch_gather(wire_next)  # prefetch l+1
                inner, ys = block(inner, (cur_wire, orig_cur))
                return (inner, gathered), ys

            (inner, last_wire), (aux_losses, dropped, load) = lax.scan(
                pf_block,
                ((x, rng), _prefetch_gather(wire_first)),
                (wire_rest, orig_head),
            )
            inner, (aux_l, drop_l, load_l) = block(
                inner, (last_wire, orig_last))
        else:
            first = jax.tree.map(lambda a: a[0], layers)
            rest = jax.tree.map(lambda a: a[1:], layers)

            def pf_block(carry, next_sharded):
                inner, cur = carry
                gathered = _prefetch_gather(next_sharded)  # prefetch l+1
                inner, ys = block(inner, cur)  # compute layer l
                return (inner, gathered), ys

            (inner, last), (aux_losses, dropped, load) = lax.scan(
                pf_block, ((x, rng), _prefetch_gather(first)), rest
            )
            inner, (aux_l, drop_l, load_l) = block(inner, last)
        x, _ = inner
        aux_losses = jnp.concatenate([aux_losses, aux_l[None]])
        dropped = jnp.concatenate([dropped, drop_l[None]])
        load = jnp.concatenate([load, load_l[None]], axis=0)
    else:
        (x, _), (aux_losses, dropped, load) = lax.scan(
            block, (x, rng), scan_xs
        )
    x = _rms_norm(x, params["norm"]["scale"], c.rms_eps)
    if with_moe_metrics:
        metrics = {
            "moe_dropped_frac": jnp.mean(dropped),
            "moe_expert_load": jnp.mean(load, axis=0),
        }
        return x, jnp.sum(aux_losses), metrics
    return x, jnp.sum(aux_losses)


def apply(params: Dict, input_ids: jax.Array, config: LlamaConfig,
          rng: Optional[jax.Array] = None,
          segment_ids: Optional[jax.Array] = None,
          with_moe_metrics: bool = False,
          ):
    """Returns (logits [B, S, V] in f32, moe_aux_loss scalar) — plus
    the load-balance metrics dict when ``with_moe_metrics``."""
    c = config
    out = apply_hidden(params, input_ids, config, rng, segment_ids,
                       with_moe_metrics=with_moe_metrics)
    x = out[0]
    logits = (x @ params["lm_head"]["kernel"].astype(c.compute_dtype))
    return (logits.astype(jnp.float32),) + out[1:]


def apply_pipelined(
    params: Dict,
    input_ids: jax.Array,
    config: LlamaConfig,
    num_stages: int,
    num_microbatches: int,
    rng: Optional[jax.Array] = None,
    num_virtual: int = 1,
    stage_depths: Optional[Sequence[int]] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Forward pass with the decoder blocks run as a GPipe pipeline over
    the "pipe" mesh axis (``parallel.pipeline``); embed/final-norm/head
    stay outside the pipeline in the surrounding GSPMD program.

    Equivalent to ``apply`` up to bf16 rounding for dense configs. For
    MoE configs the math intentionally differs: expert capacity is
    computed per *microbatch* (B/M tokens) rather than per batch, and
    each stage restarts the rng chain, so routing overflow/jitter
    decisions are not bit-identical to ``apply``. Use with the
    "llama_pp" rule set so the stacked layer dim lands on "pipe".

    ``stage_depths``: per-stage-chunk layer counts (V*P entries in visit
    order, summing to num_layers) for UNEVEN stage splits — a lighter
    first/last stage, or L % (V*P) != 0. Padded layer slots are skipped
    via a validity mask; see ``pipeline.stack_stages_uneven`` for the
    cost model (wall-clock equals the heaviest stage either way).
    """
    from dlrover_tpu.parallel.pipeline import (
        dispatch_pipeline,
        merge_microbatches,
        pipe_batch_constraint,
        split_microbatches,
    )

    c = config
    x = params["embed_tokens"]["embedding"][input_ids].astype(c.compute_dtype)
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    def stage_fn(chunk_and_mask, state):
        layers_chunk, mask = chunk_and_mask
        x, aux = state
        block = apply_remat(_decoder_block(c), c.remat_policy)
        if mask is None:  # even split: plain scan over the chunk
            (x, _), (auxs, _, _) = lax.scan(block, (x, rng), layers_chunk)
            return (x, aux + jnp.sum(auxs))

        def slot(carry, inp):
            layer, valid = inp
            new_carry, (aux_l, _, _) = block(carry, layer)
            x_new, rng_new = new_carry
            x_old, _ = carry
            # padded slot: carry the state through untouched (zero
            # params keep the garbage compute finite, so the masked
            # branch cannot poison the selected one's gradient); the
            # rng chain advances regardless so depth layout never
            # changes a real layer's dropout/jitter stream position
            x_sel = jnp.where(valid > 0, x_new, x_old)
            return (x_sel, rng_new), aux_l * valid
        (x, _), auxs = lax.scan(slot, (x, rng), (layers_chunk, mask))
        return (x, aux + jnp.sum(auxs))

    x_mb = split_microbatches(x, num_microbatches)
    aux_mb = jnp.zeros((num_microbatches,), jnp.float32)
    out_mb, aux_out = dispatch_pipeline(
        stage_fn, params["layers"], (x_mb, aux_mb),
        num_stages, num_virtual, stage_depths,
        remat_stage=remat_enabled(c.remat_policy),
    )
    x = merge_microbatches(out_mb)
    aux = jnp.sum(aux_out)

    # the outer final-norm/head must not replicate over the pipe axis
    # (see pipe_batch_constraint: comm-free slice, head FLOPs / pipe)
    x = pipe_batch_constraint(x)

    x = _rms_norm(x, params["norm"]["scale"], c.rms_eps)
    logits = (x @ params["lm_head"]["kernel"].astype(c.compute_dtype))
    return logits.astype(jnp.float32), aux


# -- serving: single-token decode over the paged KV cache --------------------
#
# The decode-step apply of the serving tier (``dlrover_tpu.serving``):
# the same stacked-layer params, the same scan-over-layers, but the
# sequence dimension is replaced by a KV-page READ — attention for slot
# ``s`` is a plain slice of its own contiguous pages (gather-free; see
# ``serving.kv_cache`` for the slot-major pool layout). Numerics follow
# the training forward (f32 attention logits, ``finfo.min`` masking,
# f32 softmax — the ``mha_reference`` conventions), so prefill+decode
# matches the one-shot forward to float roundoff; ``prefill_sequence``
# goes further and routes the whole prompt through ``_attention_block``
# itself — ring attention included for long-context ``seq_axis``
# configs — so its hidden states (and the first generated token) are
# BITWISE the training forward's.


def _kv_write_token(k_l, scale_l, new_kv, pos, active, spec):
    """Write one token's K (or V) into its slot page at ``pos``,
    masked by ``active`` (an admitted-and-decoding slot). The write
    touches exactly one page row per slot — a scatter at
    ``(slot, pos)`` — and inactive slots keep their old row, so a slot
    mid-prefill (or parked) is never corrupted by the batch-wide
    decode step."""
    from dlrover_tpu.serving.kv_cache import encode_kv

    s = k_l.shape[0]
    idx = jnp.arange(s)
    t = k_l.shape[1]
    vals, scales = encode_kv(new_kv, spec)
    # masked scatter by index redirection: an inactive slot's row index
    # is pushed out of bounds, and mode="drop" discards the update —
    # no gather of the old row just to feed a where() (the gather-free
    # decode invariant, G110: per-slot random reads belong to the host)
    row = jnp.where(active, jnp.clip(pos, 0, t - 1), t)
    k_l = k_l.at[idx, row].set(vals, mode="drop")
    if scales is not None and scale_l is not None:
        scale_l = scale_l.at[idx, row].set(scales, mode="drop")
    return k_l, scale_l


def _paged_attention(q, k_l, ks_l, v_l, vs_l, pos, spec, config):
    """Decode attention: ``q [S, H, HD]`` against each slot's own pages
    ``[S, T, KV, HD]`` with the causal mask ``t <= pos[s]``. GQA via a
    grouped einsum (KV heads are never repeated — the pages hold, and
    the read moves, only the KV heads). Mirrors ``mha_reference``:
    f32 logits, ``finfo.min`` mask, f32 softmax."""
    from dlrover_tpu.serving.kv_cache import decode_kv

    s, h, hd = q.shape
    kvh = k_l.shape[2]
    t = k_l.shape[1]
    group = h // kvh
    k = decode_kv(k_l, ks_l, spec)      # [S, T, KV, HD] f32
    v = decode_kv(v_l, vs_l, spec)
    qg = q.reshape(s, kvh, group, hd)
    logits = jnp.einsum(
        "skgd,stkd->skgt", qg, k, preferred_element_type=jnp.float32
    ) * (1.0 / (hd ** 0.5))
    mask = jnp.arange(t)[None, :] <= pos[:, None]  # [S, T]
    logits = jnp.where(mask[:, None, None, :], logits,
                       jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("skgt,stkd->skgd", probs.astype(v.dtype), v)
    return out.reshape(s, h * hd).astype(config.compute_dtype)


def _kv_write_tokens(k_l, scale_l, new_kv, pos, valid, spec):
    """Masked MULTI-token append: write up to ``K+1`` tokens' K (or V)
    per slot at rows ``pos[s] .. pos[s]+K``, guarded by ``valid
    [S, K+1]`` — the speculative-verify generalization of
    ``_kv_write_token``. Same discipline: masked scatter by index
    redirection (an invalid row index is pushed out of bounds and
    ``mode="drop"`` discards it), so there is no gather of old rows to
    feed a ``where()`` and the G110 gather-free invariant holds. Rows
    past the pool end are also dropped (the host caps draft lengths so
    this only guards against a buggy caller, not silent clamping)."""
    from dlrover_tpu.serving.kv_cache import encode_kv

    s, k1 = new_kv.shape[0], new_kv.shape[1]
    t = k_l.shape[1]
    idx = jnp.arange(s)[:, None]
    vals, scales = encode_kv(new_kv, spec)
    rows_raw = pos[:, None] + jnp.arange(k1)[None, :]   # [S, K+1]
    ok = valid & (rows_raw < t)
    rows = jnp.where(ok, jnp.clip(rows_raw, 0, t - 1), t)
    k_l = k_l.at[idx, rows].set(vals, mode="drop")
    if scales is not None and scale_l is not None:
        scale_l = scale_l.at[idx, rows].set(scales, mode="drop")
    return k_l, scale_l


def _verify_attention(q, k_l, ks_l, v_l, vs_l, pos, spec, config):
    """Speculative-verify attention: ``q [S, K+1, H, HD]`` — every
    slot's current token plus its drafts — against each slot's own
    pages, causal mask ``t <= pos[s] + i``. The batched-over-slots
    generalization of ``_chunk_attention`` (same grouped einsum, f32
    logits, ``finfo.min`` mask, f32 softmax), which is what makes the
    verified positions compute-per-position identical to the decode
    path — the per-row parity the bitwise acceptance contract rests
    on."""
    from dlrover_tpu.serving.kv_cache import decode_kv

    s, k1, h, hd = q.shape
    kvh = k_l.shape[2]
    t = k_l.shape[1]
    group = h // kvh
    k = decode_kv(k_l, ks_l, spec)      # [S, T, KV, HD] f32
    v = decode_kv(v_l, vs_l, spec)
    qg = q.reshape(s, k1, kvh, group, hd)
    logits = jnp.einsum(
        "sikgd,stkd->sikgt", qg, k, preferred_element_type=jnp.float32
    ) * (1.0 / (hd ** 0.5))
    mask = (jnp.arange(t)[None, None, :]
            <= (pos[:, None] + jnp.arange(k1)[None, :])[:, :, None])
    logits = jnp.where(mask[:, :, None, None, :], logits,
                       jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("sikgt,stkd->sikgd", probs.astype(v.dtype), v)
    return out.reshape(s, k1, h * hd).astype(config.compute_dtype)


def _chunk_attention(q, k_slot, ks_slot, v_slot, vs_slot, start, spec,
                     config):
    """Prefill-chunk attention: chunk queries ``[C, H, HD]`` against
    ONE slot's pages (which already contain the chunk's own K/V at
    ``start..start+C``), causal mask ``t <= start + i``."""
    from dlrover_tpu.serving.kv_cache import decode_kv

    cq, h, hd = q.shape
    kvh = k_slot.shape[1]
    t = k_slot.shape[0]
    group = h // kvh
    k = decode_kv(k_slot, ks_slot, spec)    # [T, KV, HD] f32
    v = decode_kv(v_slot, vs_slot, spec)
    qg = q.reshape(cq, kvh, group, hd)
    logits = jnp.einsum(
        "ckgd,tkd->ckgt", qg, k, preferred_element_type=jnp.float32
    ) * (1.0 / (hd ** 0.5))
    mask = (jnp.arange(t)[None, :]
            <= start + jnp.arange(cq)[:, None])  # [C, T]
    logits = jnp.where(mask[:, None, None, :], logits,
                       jnp.finfo(jnp.float32).min)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("ckgt,tkd->ckgd", probs.astype(v.dtype), v)
    return out.reshape(cq, h * hd).astype(config.compute_dtype)


def _cache_xs(cache):
    """(k, k_scale-or-None, v, v_scale-or-None) in scan-xs order; the
    scale leaves exist only for int8 pools."""
    return (cache["k"], cache.get("k_scale"), cache["v"],
            cache.get("v_scale"))


def _rebuild_cache(cache, k, ks, v, vs, length):
    out = dict(cache, k=k, v=v, length=length)
    if ks is not None:
        out["k_scale"] = ks
    if vs is not None:
        out["v_scale"] = vs
    return out


def decode_step(params, cache, tokens, active, config: LlamaConfig,
                spec):
    """One continuous-batching decode step for EVERY slot at once.

    ``tokens [S] int32``: each slot's current token (the one whose
    successor is being predicted). ``active [S] bool``: slots that are
    admitted and decoding — inactive (free / mid-prefill) slots compute
    harmlessly but neither write pages nor advance ``length``. Returns
    ``(next_tokens [S], logits [S, V] f32, cache)`` with greedy
    next-token selection done ON DEVICE, so the engine's dispatch
    window never needs a host sync to feed step k+1.

    Dense FFN only: MoE expert dispatch for single-token batches is a
    different kernel regime (ROADMAP item 3 names it) — a config with
    experts must serve through ``prefill_sequence`` + a dense head or
    wait for the MoE decode path.
    """
    c = config
    if c.num_experts > 0:
        raise NotImplementedError(
            "decode_step serves dense llama configs; MoE decode "
            "dispatch is not built yet (ROADMAP item 3)")
    s = tokens.shape[0]
    pos = cache["length"]  # the position this step writes
    x = params["embed_tokens"]["embedding"][tokens].astype(c.compute_dtype)

    def block(x_in, xs):
        layer, k_l, ks_l, v_l, vs_l = xs
        layer = cast_floats(layer, c.compute_dtype)
        h, kvh, hd = c.num_heads, c.num_kv_heads, c.head_dim
        attn_in = _rms_norm(x_in, layer["input_norm"]["scale"], c.rms_eps)
        q = (attn_in @ layer["q_proj"]["kernel"]).reshape(s, h, hd)
        k_new = (attn_in @ layer["k_proj"]["kernel"]).reshape(s, kvh, hd)
        v_new = (attn_in @ layer["v_proj"]["kernel"]).reshape(s, kvh, hd)
        # RoPE at each slot's own position (slots are a batch of
        # length-1 sequences)
        q = _rope(q[:, None], pos[:, None], c.rope_theta)[:, 0]
        k_new = _rope(k_new[:, None], pos[:, None], c.rope_theta)[:, 0]
        k_l, ks_l = _kv_write_token(k_l, ks_l, k_new, pos, active, spec)
        v_l, vs_l = _kv_write_token(v_l, vs_l, v_new, pos, active, spec)
        attn = _paged_attention(q, k_l, ks_l, v_l, vs_l, pos, spec, c)
        x_mid = x_in + attn @ layer["o_proj"]["kernel"]
        ffn_in = _rms_norm(x_mid, layer["post_norm"]["scale"], c.rms_eps)
        gate = jax.nn.silu(ffn_in @ layer["gate_proj"]["kernel"])
        up = ffn_in @ layer["up_proj"]["kernel"]
        ffn = (gate * up) @ layer["down_proj"]["kernel"]
        return x_mid + ffn, (k_l, ks_l, v_l, vs_l)

    k, ks, v, vs = _cache_xs(cache)
    xs = (params["layers"], k, ks, v, vs)
    x, (k, ks, v, vs) = lax.scan(block, x, xs)
    x = _rms_norm(x, params["norm"]["scale"], c.rms_eps)
    logits = (x @ params["lm_head"]["kernel"].astype(c.compute_dtype))
    logits = logits.astype(jnp.float32)
    next_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    length = jnp.where(active, pos + 1, pos).astype(jnp.int32)
    return next_tokens, logits, _rebuild_cache(cache, k, ks, v, vs,
                                               length)


def verify_step(params, cache, tokens, active, n_draft,
                config: LlamaConfig, spec):
    """One speculative-decode VERIFY step for every slot at once: run
    the model over each slot's current token plus up to K drafted
    continuations in one batched call, greedily accept the longest
    matching draft prefix on device, and commit exactly the accepted
    tokens' KV.

    ``tokens [S, K+1] int32``: column 0 is the slot's current token
    (what ``decode_step`` would be fed), columns 1..K are host-drafted
    candidates for the following positions. ``n_draft [S] int32``: how
    many of the K draft columns are real for each slot (mixed-K slots
    in ONE compiled program — K is static, validity is data).
    ``active [S] bool``: as in ``decode_step``.

    Acceptance contract (greedy, bitwise): with ``g[i] = argmax`` of
    the logits at position ``pos+i``, the accepted length ``a`` is the
    longest prefix with ``tokens[i+1] == g[i]`` for ``i < a``. The
    slot emits ``a+1`` tokens — ``g[0..a]``: the accepted drafts plus
    the bonus token the last verified position predicts — and its next
    current token is ``g[a]``. Since ``g[0]`` is computed over exactly
    the context ``decode_step`` would see, and each accepted draft
    equals the token greedy decode would have produced, the emitted
    stream is token-for-token what plain greedy decode emits at EVERY
    acceptance pattern (induction over accepted prefixes; per-position
    compute parity is ``_verify_attention``'s contract).

    Rollback is a cursor rewind, not a wipe: rejected positions
    ``pos+a+1 .. pos+n_draft`` hold garbage K/V rows, but every
    attention mask is position-bounded by the committed length and
    future writes land in order, overwriting them before they could
    ever be read.

    Returns ``(greedy [S, K+1], accepted [S], next_tokens [S],
    cache)`` — the host reads ``greedy[:, :accepted+1]`` once per
    verify step, amortized over up to K+1 emitted tokens.
    """
    c = config
    if c.num_experts > 0:
        raise NotImplementedError(
            "verify_step serves dense llama configs; MoE decode "
            "dispatch is not built yet (ROADMAP item 3)")
    s, k1 = tokens.shape
    pos = cache["length"]               # first position this step writes
    offs = jnp.arange(k1)
    valid = active[:, None] & (offs[None, :] <= n_draft[:, None])
    positions = pos[:, None] + offs[None, :]        # [S, K+1]
    x = params["embed_tokens"]["embedding"][tokens].astype(c.compute_dtype)

    def block(x_in, xs):
        layer, k_l, ks_l, v_l, vs_l = xs
        layer = cast_floats(layer, c.compute_dtype)
        h, kvh, hd = c.num_heads, c.num_kv_heads, c.head_dim
        attn_in = _rms_norm(x_in, layer["input_norm"]["scale"], c.rms_eps)
        q = (attn_in @ layer["q_proj"]["kernel"]).reshape(s, k1, h, hd)
        k_new = (attn_in @ layer["k_proj"]["kernel"]).reshape(
            s, k1, kvh, hd)
        v_new = (attn_in @ layer["v_proj"]["kernel"]).reshape(
            s, k1, kvh, hd)
        q = _rope(q, positions, c.rope_theta)
        k_new = _rope(k_new, positions, c.rope_theta)
        k_l, ks_l = _kv_write_tokens(k_l, ks_l, k_new, pos, valid, spec)
        v_l, vs_l = _kv_write_tokens(v_l, vs_l, v_new, pos, valid, spec)
        attn = _verify_attention(q, k_l, ks_l, v_l, vs_l, pos, spec, c)
        x_mid = x_in + attn @ layer["o_proj"]["kernel"]
        ffn_in = _rms_norm(x_mid, layer["post_norm"]["scale"], c.rms_eps)
        gate = jax.nn.silu(ffn_in @ layer["gate_proj"]["kernel"])
        up = ffn_in @ layer["up_proj"]["kernel"]
        ffn = (gate * up) @ layer["down_proj"]["kernel"]
        return x_mid + ffn, (k_l, ks_l, v_l, vs_l)

    k, ks, v, vs = _cache_xs(cache)
    xs = (params["layers"], k, ks, v, vs)
    x, (k, ks, v, vs) = lax.scan(block, x, xs)
    x = _rms_norm(x, params["norm"]["scale"], c.rms_eps)
    logits = (x @ params["lm_head"]["kernel"].astype(c.compute_dtype))
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [S, K+1]
    if k1 > 1:
        match = ((tokens[:, 1:] == greedy[:, :-1])
                 & (jnp.arange(1, k1)[None, :] <= n_draft[:, None]))
        accepted = jnp.cumprod(
            match.astype(jnp.int32), axis=1).sum(axis=1)
    else:
        accepted = jnp.zeros((s,), jnp.int32)
    accepted = jnp.where(active, accepted, 0).astype(jnp.int32)
    # rank-2 take_along_axis: a benign table gather, not a pool gather
    next_tokens = jnp.take_along_axis(
        greedy, accepted[:, None], axis=1)[:, 0].astype(jnp.int32)
    length = jnp.where(active, pos + accepted + 1, pos).astype(jnp.int32)
    return greedy, accepted, next_tokens, _rebuild_cache(
        cache, k, ks, v, vs, length)


def prefill_chunk(params, cache, tokens, slot, start, n_valid,
                  config: LlamaConfig, spec):
    """Prefill ONE chunk of one slot's prompt: write the chunk's K/V
    pages and return ``(cache, last_logits [V])`` — the logits of token
    ``n_valid - 1``, which seed the first decode step when this is the
    prompt's final chunk.

    ``tokens [C] int32`` (fixed chunk shape — the ``prefill_chunk``
    knob), ``slot`` / ``start`` / ``n_valid`` traced scalars, so
    admission at any slot with any prompt length is the SAME compiled
    program: chunked prefill interleaves with the decode stream and a
    long prompt can never stall the batch behind a monolithic prefill.
    Chunks past the first attend to the slot's earlier pages through
    the cache, exactly like decode. Trailing padding (``n_valid < C``)
    is written but never read: decode's next write lands at
    ``start + n_valid``, and every mask is position-bounded."""
    c = config
    if c.num_experts > 0:
        raise NotImplementedError(
            "prefill_chunk serves dense llama configs; use "
            "prefill_sequence for MoE prompts")
    cq = tokens.shape[0]
    positions = start + jnp.arange(cq)
    x = params["embed_tokens"]["embedding"][tokens].astype(c.compute_dtype)

    def block(x_in, xs):
        from dlrover_tpu.serving.kv_cache import encode_kv

        layer, k_l, ks_l, v_l, vs_l = xs
        layer = cast_floats(layer, c.compute_dtype)
        h, kvh, hd = c.num_heads, c.num_kv_heads, c.head_dim
        attn_in = _rms_norm(x_in, layer["input_norm"]["scale"], c.rms_eps)
        q = (attn_in @ layer["q_proj"]["kernel"]).reshape(cq, h, hd)
        k_new = (attn_in @ layer["k_proj"]["kernel"]).reshape(cq, kvh, hd)
        v_new = (attn_in @ layer["v_proj"]["kernel"]).reshape(cq, kvh, hd)
        q = _rope(q[None], positions[None], c.rope_theta)[0]
        k_new = _rope(k_new[None], positions[None], c.rope_theta)[0]
        kv_vals, kv_scales = encode_kv(k_new, spec)
        vv_vals, vv_scales = encode_kv(v_new, spec)
        k_l = lax.dynamic_update_slice(
            k_l, kv_vals[None], (slot, start, 0, 0))
        v_l = lax.dynamic_update_slice(
            v_l, vv_vals[None], (slot, start, 0, 0))
        if ks_l is not None:
            ks_l = lax.dynamic_update_slice(
                ks_l, kv_scales[None], (slot, start, 0, 0))
            vs_l = lax.dynamic_update_slice(
                vs_l, vv_scales[None], (slot, start, 0, 0))
        k_slot = lax.dynamic_index_in_dim(k_l, slot, 0, keepdims=False)
        v_slot = lax.dynamic_index_in_dim(v_l, slot, 0, keepdims=False)
        ks_slot = (lax.dynamic_index_in_dim(ks_l, slot, 0, False)
                   if ks_l is not None else None)
        vs_slot = (lax.dynamic_index_in_dim(vs_l, slot, 0, False)
                   if vs_l is not None else None)
        attn = _chunk_attention(q, k_slot, ks_slot, v_slot, vs_slot,
                                start, spec, c)
        x_mid = x_in + attn @ layer["o_proj"]["kernel"]
        ffn_in = _rms_norm(x_mid, layer["post_norm"]["scale"], c.rms_eps)
        gate = jax.nn.silu(ffn_in @ layer["gate_proj"]["kernel"])
        up = ffn_in @ layer["up_proj"]["kernel"]
        ffn = (gate * up) @ layer["down_proj"]["kernel"]
        return x_mid + ffn, (k_l, ks_l, v_l, vs_l)

    k, ks, v, vs = _cache_xs(cache)
    xs = (params["layers"], k, ks, v, vs)
    x, (k, ks, v, vs) = lax.scan(block, x, xs)
    x = _rms_norm(x, params["norm"]["scale"], c.rms_eps)
    last = lax.dynamic_index_in_dim(
        x, jnp.clip(n_valid - 1, 0, cq - 1), 0, keepdims=False)
    logits = (last @ params["lm_head"]["kernel"].astype(c.compute_dtype))
    length = cache["length"]
    length = length.at[slot].set((start + n_valid).astype(jnp.int32))
    return _rebuild_cache(cache, k, ks, v, vs, length), \
        logits.astype(jnp.float32)


def prefill_sequence(params, cache, tokens, slot, config: LlamaConfig,
                     spec):
    """One-shot prefill of a whole prompt into slot ``slot`` (start
    must be 0: a freshly admitted slot), returning ``(cache,
    last_logits [V])``.

    Unlike ``prefill_chunk`` this routes the prompt through the
    TRAINING forward itself — ``_attention_block`` with ``return_kv``,
    so flash kernels, packed-segment masking and the ``seq_axis`` RING
    attention path (``ops.ring_attention``) all apply for long-context
    configs, and the hidden states (hence the first generated token)
    are bitwise the training ``apply``'s. The long-prompt path of the
    promotion scenario; continuous batching admits through
    ``prefill_chunk`` so the batch never stalls."""
    c = config
    p = tokens.shape[0]
    x = params["embed_tokens"]["embedding"][tokens][None].astype(
        c.compute_dtype)
    positions = jnp.broadcast_to(jnp.arange(p), (1, p))

    def block(carry, xs):
        from dlrover_tpu.serving.kv_cache import encode_kv

        x_in, block_rng = carry
        layer, k_l, ks_l, v_l, vs_l = xs
        layer = cast_floats(layer, c.compute_dtype)
        block_rng, ffn_rng = jax.random.split(block_rng)
        attn_in = _rms_norm(x_in, layer["input_norm"]["scale"], c.rms_eps)
        attn, (k_new, v_new) = _attention_block(
            attn_in, layer, c, positions, return_kv=True)
        x_mid = x_in + attn
        kv_vals, kv_scales = encode_kv(k_new[0], spec)
        vv_vals, vv_scales = encode_kv(v_new[0], spec)
        k_l = lax.dynamic_update_slice(
            k_l, kv_vals[None], (slot, 0, 0, 0))
        v_l = lax.dynamic_update_slice(
            v_l, vv_vals[None], (slot, 0, 0, 0))
        if ks_l is not None:
            ks_l = lax.dynamic_update_slice(
                ks_l, kv_scales[None], (slot, 0, 0, 0))
            vs_l = lax.dynamic_update_slice(
                vs_l, vv_scales[None], (slot, 0, 0, 0))
        ffn_in = _rms_norm(x_mid, layer["post_norm"]["scale"], c.rms_eps)
        ffn_out, _aux, _dropped, _load = _ffn_block(
            ffn_in, layer, c, ffn_rng)
        return (x_mid + ffn_out, block_rng), (k_l, ks_l, v_l, vs_l)

    k, ks, v, vs = _cache_xs(cache)
    xs = (params["layers"], k, ks, v, vs)
    (x, _), (k, ks, v, vs) = lax.scan(
        block, (x, jax.random.PRNGKey(0)), xs)
    x = _rms_norm(x, params["norm"]["scale"], c.rms_eps)
    logits = (x[0, -1] @ params["lm_head"]["kernel"].astype(
        c.compute_dtype))
    length = cache["length"].at[slot].set(jnp.int32(p))
    return _rebuild_cache(cache, k, ks, v, vs, length), \
        logits.astype(jnp.float32)


# -- training glue ----------------------------------------------------------


def make_init_fn(config: LlamaConfig):
    return partial(init, config=config)


def make_loss_fn(config: LlamaConfig, z_loss_weight: float = 0.0,
                 head_chunk: int = 0):
    """Causal-LM loss over batches {"input_ids", "labels"} (labels==-100
    are masked, HF convention).

    ``head_chunk`` > 0 fuses the lm head with the cross entropy over
    sequence chunks (``losses.chunked_lm_head_loss``) so the [B, S, V]
    f32 logits never materialize — the memory lever for long sequences
    and large vocabularies.
    """

    def loss_fn(params, batch, rng):
        segment_ids = batch.get("segment_ids")
        moe = config.num_experts > 0
        extra = {}
        if head_chunk > 0:
            out = apply_hidden(
                params, batch["input_ids"], config, rng,
                segment_ids=segment_ids, with_moe_metrics=moe,
            )
            hidden, moe_aux = out[0], out[1]
            loss = chunked_lm_head_loss(
                hidden, params["lm_head"]["kernel"], batch["labels"],
                chunk_size=head_chunk, z_loss_weight=z_loss_weight,
            )
        else:
            out = apply(params, batch["input_ids"], config,
                        rng, segment_ids=segment_ids, with_moe_metrics=moe)
            moe_aux = out[1]
            loss = masked_lm_loss(out[0], batch["labels"], z_loss_weight)
        if moe:
            loss = loss + config.moe_aux_weight * moe_aux / max(
                1, config.num_layers
            )
            # load-balance observability: ride the step-metrics dict
            # (switch_gating.py:24-195 parity — overflow accounting)
            extra = dict(out[2])
        return loss, extra

    return loss_fn


def param_count(config: LlamaConfig) -> int:
    return common_param_count(partial(init, config=config))


def flops_per_token(config: LlamaConfig) -> float:
    """6N + attention flops approximation for MFU accounting."""
    n = param_count(config)
    attn = 12 * config.num_layers * config.hidden_size * config.max_seq_len
    return 6.0 * n + attn
