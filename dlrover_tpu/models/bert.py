"""BERT-family bidirectional encoder.

Role parity: the reference accelerates HF Bert via module surgery
(``atorch/modules/distributed_modules/transformer.py:39`` sharded Bert
attention/MLP, ``modules/transformer/layers.py:729`` BertAttentionFA).
TPU-first like ``models.llama``: functional init/apply, scan over
stacked layers, Pallas flash attention (non-causal) or the XLA
reference, post-LN residuals per the original architecture.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, replace
from functools import partial
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from dlrover_tpu.models.losses import masked_lm_loss
from dlrover_tpu.models.common import (
    cast_floats,
    dense_init as _dense,
    layer_norm as _layer_norm,
    param_count as common_param_count,
)
from jax.ad_checkpoint import checkpoint_name

from dlrover_tpu.ops.attention_ref import mha_reference
from dlrover_tpu.ops.flash_attention import flash_attention_auto
from dlrover_tpu.ops.remat import apply_remat, remat_enabled


@dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    intermediate_size: int = 3072
    num_layers: int = 12
    num_heads: int = 12
    max_position: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    remat_policy: str = "dots_saveable"
    use_flash: bool = True

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


def bert_base(**overrides) -> BertConfig:
    return replace(BertConfig(), **overrides)


def bert_large(**overrides) -> BertConfig:
    return replace(
        BertConfig(hidden_size=1024, intermediate_size=4096,
                   num_layers=24, num_heads=16),
        **overrides,
    )


def bert_tiny(**overrides) -> BertConfig:
    """Test-scale config (CPU mesh friendly)."""
    return replace(
        BertConfig(vocab_size=128, hidden_size=32, intermediate_size=64,
                   num_layers=2, num_heads=4, max_position=64,
                   compute_dtype=jnp.float32, use_flash=False),
        **overrides,
    )


def init(rng: jax.Array, config: BertConfig) -> Dict:
    c = config
    dt = c.param_dtype
    keys = iter(jax.random.split(rng, 16))
    l, d, f, h = c.num_layers, c.hidden_size, c.intermediate_size, c.num_heads
    hd = c.head_dim

    return {
        "embeddings": {
            "word": {"embedding": jax.random.normal(
                next(keys), (c.vocab_size, d), dt) * 0.02},
            "position": {"embedding": jax.random.normal(
                next(keys), (c.max_position, d), dt) * 0.02},
            "token_type": {"embedding": jax.random.normal(
                next(keys), (c.type_vocab_size, d), dt) * 0.02},
            "norm": {"scale": jnp.ones((d,), dt),
                     "bias": jnp.zeros((d,), dt)},
        },
        "layers": {
            "q_proj": {"kernel": _dense(next(keys), (l, d, h * hd), dt),
                       "bias": jnp.zeros((l, h * hd), dt)},
            "k_proj": {"kernel": _dense(next(keys), (l, d, h * hd), dt),
                       "bias": jnp.zeros((l, h * hd), dt)},
            "v_proj": {"kernel": _dense(next(keys), (l, d, h * hd), dt),
                       "bias": jnp.zeros((l, h * hd), dt)},
            "o_proj": {"kernel": _dense(next(keys), (l, h * hd, d), dt),
                       "bias": jnp.zeros((l, d), dt)},
            "attn_norm": {"scale": jnp.ones((l, d), dt),
                          "bias": jnp.zeros((l, d), dt)},
            "up_proj": {"kernel": _dense(next(keys), (l, d, f), dt),
                        "bias": jnp.zeros((l, f), dt)},
            "down_proj": {"kernel": _dense(next(keys), (l, f, d), dt,
                                           scale=1.0 / math.sqrt(f)),
                          "bias": jnp.zeros((l, d), dt)},
            "ffn_norm": {"scale": jnp.ones((l, d), dt),
                         "bias": jnp.zeros((l, d), dt)},
        },
        "pooler": {"kernel": _dense(next(keys), (d, d), dt),
                   "bias": jnp.zeros((d,), dt)},
        "mlm_head": {"kernel": _dense(next(keys), (d, c.vocab_size), dt),
                     "bias": jnp.zeros((c.vocab_size,), dt)},
    }


def _attention(x, layer, config: BertConfig, mask):
    c = config
    b, s, d = x.shape
    h, hd = c.num_heads, c.head_dim
    q = (x @ layer["q_proj"]["kernel"] + layer["q_proj"]["bias"])
    k = (x @ layer["k_proj"]["kernel"] + layer["k_proj"]["bias"])
    v = (x @ layer["v_proj"]["kernel"] + layer["v_proj"]["bias"])
    q, k, v = (
        t.reshape(b, s, h, hd).transpose(0, 2, 1, 3) for t in (q, k, v)
    )
    if mask is None and c.use_flash:
        out = flash_attention_auto(q, k, v, False)
    else:
        bias = None
        if mask is not None:
            # [B, S] 1/0 attention mask -> additive bias on keys
            bias = jnp.where(
                mask[:, None, None, :] > 0, 0.0,
                jnp.finfo(jnp.float32).min,
            )
        out = mha_reference(q, k, v, causal=False, bias=bias)
    # named for the "attn_saveable" remat policy
    out = checkpoint_name(out, "attn_out")
    out = out.transpose(0, 2, 1, 3).reshape(b, s, h * hd)
    return out @ layer["o_proj"]["kernel"] + layer["o_proj"]["bias"]


def _encoder_block(c: BertConfig, mask):
    def block(x, layer):
        layer = cast_floats(layer, c.compute_dtype)
        attn = _attention(x, layer, c, mask)
        x = _layer_norm(x + attn, layer["attn_norm"]["scale"],
                        layer["attn_norm"]["bias"], c.layer_norm_eps)
        ffn = jax.nn.gelu(
            x @ layer["up_proj"]["kernel"] + layer["up_proj"]["bias"]
        )
        ffn = ffn @ layer["down_proj"]["kernel"] + layer["down_proj"]["bias"]
        x = _layer_norm(x + ffn, layer["ffn_norm"]["scale"],
                        layer["ffn_norm"]["bias"], c.layer_norm_eps)
        return x, None

    return block


def apply(
    params: Dict,
    input_ids: jax.Array,  # [B, S]
    config: BertConfig,
    token_type_ids: Optional[jax.Array] = None,
    attention_mask: Optional[jax.Array] = None,  # [B, S] 1=attend
) -> Tuple[jax.Array, jax.Array]:
    """Returns (sequence_output [B, S, D], pooled [B, D])."""
    c = config
    b, s = input_ids.shape
    emb = params["embeddings"]
    x = emb["word"]["embedding"][input_ids]
    x = x + emb["position"]["embedding"][None, :s, :]
    types = token_type_ids if token_type_ids is not None else (
        jnp.zeros_like(input_ids)
    )
    x = x + emb["token_type"]["embedding"][types]
    x = _layer_norm(x, emb["norm"]["scale"], emb["norm"]["bias"],
                    c.layer_norm_eps).astype(c.compute_dtype)

    block = apply_remat(_encoder_block(c, attention_mask), c.remat_policy)
    x, _ = lax.scan(block, x, params["layers"])

    pooled = jnp.tanh(
        x[:, 0, :] @ params["pooler"]["kernel"] + params["pooler"]["bias"]
    )
    return x, pooled


def apply_pipelined(
    params: Dict,
    input_ids: jax.Array,
    config: BertConfig,
    num_stages: int,
    num_microbatches: int,
    token_type_ids: Optional[jax.Array] = None,
    attention_mask: Optional[jax.Array] = None,
    num_virtual: int = 1,
    stage_depths: Optional[Sequence[int]] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Encoder blocks as a GPipe / interleaved pipeline over the "pipe"
    mesh axis — the same formulation as the decoder families
    (``models.llama.apply_pipelined``). The per-example attention mask
    rides the pipeline state beside its microbatch (like GLM's
    prefix-LM context); embeddings, pooler and the MLM head stay
    outside, the post-pipeline compute spread over pipe. Use with the
    "bert_pp" rule set. ``stage_depths``: uneven per-chunk layer
    counts in visit order."""
    from dlrover_tpu.parallel.pipeline import (
        dispatch_pipeline,
        masked_layer_scan,
        merge_microbatches,
        pipe_batch_constraint,
        split_microbatches,
    )

    c = config
    s = input_ids.shape[1]
    emb = params["embeddings"]
    x = emb["word"]["embedding"][input_ids]
    x = x + emb["position"]["embedding"][None, :s, :]
    types = token_type_ids if token_type_ids is not None else (
        jnp.zeros_like(input_ids)
    )
    x = x + emb["token_type"]["embedding"][types]
    x = _layer_norm(x, emb["norm"]["scale"], emb["norm"]["bias"],
                    c.layer_norm_eps).astype(c.compute_dtype)

    with_mask = attention_mask is not None

    def run_chunk(layers_chunk, x, mask, slot_mask):
        block = apply_remat(_encoder_block(c, mask), c.remat_policy)
        return masked_layer_scan(block, x, layers_chunk, slot_mask)

    if with_mask:
        state = (x, attention_mask)

        def stage_fn(chunk_and_mask, st):
            layers_chunk, slot_mask = chunk_and_mask
            x, mask = st
            return (run_chunk(layers_chunk, x, mask, slot_mask), mask)
    else:
        state = x

        def stage_fn(chunk_and_mask, x):
            layers_chunk, slot_mask = chunk_and_mask
            return run_chunk(layers_chunk, x, None, slot_mask)

    state_mb = split_microbatches(state, num_microbatches)
    out_mb = dispatch_pipeline(
        stage_fn, params["layers"], state_mb,
        num_stages, num_virtual, stage_depths,
        remat_stage=remat_enabled(c.remat_policy),
    )
    out_state = merge_microbatches(out_mb)
    x = out_state[0] if with_mask else out_state

    x = pipe_batch_constraint(x)
    pooled = jnp.tanh(
        x[:, 0, :] @ params["pooler"]["kernel"] + params["pooler"]["bias"]
    )
    return x, pooled


def apply_mlm(params, input_ids, config, **kwargs) -> jax.Array:
    """Masked-LM logits [B, S, V] in f32."""
    x, _ = apply(params, input_ids, config, **kwargs)
    logits = x @ params["mlm_head"]["kernel"].astype(x.dtype) + (
        params["mlm_head"]["bias"].astype(x.dtype)
    )
    return logits.astype(jnp.float32)


def make_init_fn(config: BertConfig):
    return partial(init, config=config)


def make_mlm_loss_fn(config: BertConfig):
    """MLM loss over {"input_ids", "labels"} (-100 = unmasked, HF
    convention)."""

    def loss_fn(params, batch, rng):
        del rng
        logits = apply_mlm(params, batch["input_ids"], config)
        return masked_lm_loss(logits, batch["labels"]), {}

    return loss_fn


def param_count(config: BertConfig) -> int:
    return common_param_count(partial(init, config=config))
