"""Shared loss functions for the model family."""

from __future__ import annotations

import jax
import jax.numpy as jnp

IGNORE_INDEX = -100  # HF convention: masked label positions


def masked_lm_loss(logits: jax.Array, labels: jax.Array,
                   z_loss_weight: float = 0.0) -> jax.Array:
    """Causal-LM cross entropy with ``IGNORE_INDEX`` masking and optional
    z-loss regularization on the logsumexp."""
    mask = (labels != IGNORE_INDEX).astype(jnp.float32)
    labels_safe = jnp.where(labels == IGNORE_INDEX, 0, labels)
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(
        logprobs, labels_safe[..., None], axis=-1
    )[..., 0]
    denom = jnp.maximum(mask.sum(), 1.0)
    loss = (nll * mask).sum() / denom
    if z_loss_weight > 0.0:
        z = jax.scipy.special.logsumexp(logits, axis=-1)
        loss = loss + z_loss_weight * ((z ** 2) * mask).sum() / denom
    return loss
