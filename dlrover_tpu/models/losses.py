"""Shared loss functions for the model family."""

from __future__ import annotations

import jax
import jax.numpy as jnp

IGNORE_INDEX = -100  # HF convention: masked label positions


def masked_lm_loss(logits: jax.Array, labels: jax.Array,
                   z_loss_weight: float = 0.0) -> jax.Array:
    """Causal-LM cross entropy with ``IGNORE_INDEX`` masking and optional
    z-loss regularization on the logsumexp."""
    mask = (labels != IGNORE_INDEX).astype(jnp.float32)
    labels_safe = jnp.where(labels == IGNORE_INDEX, 0, labels)
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(
        logprobs, labels_safe[..., None], axis=-1
    )[..., 0]
    denom = jnp.maximum(mask.sum(), 1.0)
    loss = (nll * mask).sum() / denom
    if z_loss_weight > 0.0:
        z = jax.scipy.special.logsumexp(logits, axis=-1)
        loss = loss + z_loss_weight * ((z ** 2) * mask).sum() / denom
    return loss


def chunked_lm_head_loss(
    hidden: jax.Array,  # [B, S, D] final hidden states (compute dtype)
    kernel: jax.Array,  # [D, V] lm head
    labels: jax.Array,  # [B, S]
    chunk_size: int = 512,
    z_loss_weight: float = 0.0,
) -> jax.Array:
    """Fused lm-head + cross entropy over sequence chunks.

    The full [B, S, V] f32 logits tensor (1 GB at B=4, S=2048, V=32k)
    never materializes: each chunk's logits live only inside its scan
    step, and ``jax.checkpoint`` recomputes them in the backward pass —
    peak extra memory is O(B * chunk * V).
    """
    b, s, d = hidden.shape
    if s % chunk_size:
        # keep the memory bound: largest divisor of S <= requested,
        # never a silent collapse to the full sequence
        chunk_size = min(chunk_size, s)
        while s % chunk_size:
            chunk_size -= 1
    n_chunks = s // chunk_size
    x_c = hidden.reshape(b, n_chunks, chunk_size, d).transpose(1, 0, 2, 3)
    l_c = labels.reshape(b, n_chunks, chunk_size).transpose(1, 0, 2)
    kernel_c = kernel.astype(hidden.dtype)

    @jax.checkpoint
    def chunk_fn(carry, xc_lc):
        nll_sum, mask_sum, z_sum = carry
        xc, lc = xc_lc
        logits = (xc @ kernel_c).astype(jnp.float32)  # [B, C, V]
        mask = (lc != IGNORE_INDEX).astype(jnp.float32)
        safe = jnp.where(lc == IGNORE_INDEX, 0, lc)
        logprobs = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logprobs, safe[..., None], axis=-1)[..., 0]
        nll_sum = nll_sum + (nll * mask).sum()
        mask_sum = mask_sum + mask.sum()
        if z_loss_weight > 0.0:
            z = jax.scipy.special.logsumexp(logits, axis=-1)
            z_sum = z_sum + ((z ** 2) * mask).sum()
        return (nll_sum, mask_sum, z_sum), None

    (nll_sum, mask_sum, z_sum), _ = jax.lax.scan(
        chunk_fn,
        (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32),
         jnp.zeros((), jnp.float32)),
        (x_c, l_c),
    )
    denom = jnp.maximum(mask_sum, 1.0)
    loss = nll_sum / denom
    if z_loss_weight > 0.0:
        loss = loss + z_loss_weight * z_sum / denom
    return loss
