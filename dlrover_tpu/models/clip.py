"""CLIP-style dual encoder (text tower + ViT vision tower).

Role parity: the reference ships sharded CLIP attention/MLP modules
(``atorch/modules/distributed_modules/transformer.py`` CLIP blocks,
``modules/transformer/layers.py`` CLIP FlashAttention adapters). Written
TPU-first: both towers are scan-over-layers pre-LN transformers sharing
one block implementation; the contrastive loss is computed in-batch (for
multi-host training wrap it with an all-gather over the data axis).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, replace
from functools import partial
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from dlrover_tpu.models.common import (
    cast_floats,
    dense_init as _dense,
    layer_norm as _layer_norm,
    param_count as common_param_count,
)
from jax.ad_checkpoint import checkpoint_name

from dlrover_tpu.ops.attention_ref import mha_reference
from dlrover_tpu.ops.flash_attention import flash_attention_auto
from dlrover_tpu.ops.remat import apply_remat


@dataclass(frozen=True)
class TowerConfig:
    hidden_size: int = 512
    intermediate_size: int = 2048
    num_layers: int = 12
    num_heads: int = 8

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


@dataclass(frozen=True)
class CLIPConfig:
    vocab_size: int = 49408
    max_text_len: int = 77
    image_size: int = 224
    patch_size: int = 32
    projection_dim: int = 512
    text: TowerConfig = TowerConfig()
    vision: TowerConfig = TowerConfig(hidden_size=768,
                                      intermediate_size=3072,
                                      num_heads=12)
    logit_scale_init: float = 2.6592  # ln(1/0.07), the CLIP paper value
    layer_norm_eps: float = 1e-5
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    remat_policy: str = "dots_saveable"
    use_flash: bool = True

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2


def clip_base(**overrides) -> CLIPConfig:
    return replace(CLIPConfig(), **overrides)


def clip_tiny(**overrides) -> CLIPConfig:
    """Test-scale config."""
    return replace(
        CLIPConfig(
            vocab_size=128, max_text_len=16, image_size=32, patch_size=8,
            projection_dim=32,
            text=TowerConfig(hidden_size=32, intermediate_size=64,
                             num_layers=2, num_heads=4),
            vision=TowerConfig(hidden_size=48, intermediate_size=96,
                               num_layers=2, num_heads=4),
            compute_dtype=jnp.float32, use_flash=False,
        ),
        **overrides,
    )


def _tower_init(rng, t: TowerConfig, dtype) -> Dict:
    keys = iter(jax.random.split(rng, 8))
    l, d, f, h = t.num_layers, t.hidden_size, t.intermediate_size, t.num_heads
    hd = t.head_dim
    return {
        "q_proj": {"kernel": _dense(next(keys), (l, d, h * hd), dtype)},
        "k_proj": {"kernel": _dense(next(keys), (l, d, h * hd), dtype)},
        "v_proj": {"kernel": _dense(next(keys), (l, d, h * hd), dtype)},
        "o_proj": {"kernel": _dense(next(keys), (l, h * hd, d), dtype)},
        "attn_norm": {"scale": jnp.ones((l, d), dtype),
                      "bias": jnp.zeros((l, d), dtype)},
        "up_proj": {"kernel": _dense(next(keys), (l, d, f), dtype)},
        "down_proj": {"kernel": _dense(next(keys), (l, f, d), dtype,
                                       scale=1.0 / math.sqrt(f))},
        "ffn_norm": {"scale": jnp.ones((l, d), dtype),
                     "bias": jnp.zeros((l, d), dtype)},
    }


def init(rng: jax.Array, config: CLIPConfig) -> Dict:
    c = config
    dt = c.param_dtype
    keys = iter(jax.random.split(rng, 12))
    td, vd, p = c.text.hidden_size, c.vision.hidden_size, c.projection_dim
    patch_dim = 3 * c.patch_size * c.patch_size

    return {
        "text": {
            "embed_tokens": {"embedding": jax.random.normal(
                next(keys), (c.vocab_size, td), dt) * 0.02},
            "pos_embed": jax.random.normal(
                next(keys), (c.max_text_len, td), dt) * 0.01,
            "layers": _tower_init(next(keys), c.text, dt),
            "final_norm": {"scale": jnp.ones((td,), dt),
                           "bias": jnp.zeros((td,), dt)},
            "projection": {"kernel": _dense(next(keys), (td, p), dt)},
        },
        "vision": {
            "patch_embed": {"kernel": _dense(
                next(keys), (patch_dim, vd), dt)},
            "cls_token": jax.random.normal(next(keys), (vd,), dt) * 0.02,
            "pos_embed": jax.random.normal(
                next(keys), (c.num_patches + 1, vd), dt) * 0.01,
            "layers": _tower_init(next(keys), c.vision, dt),
            "final_norm": {"scale": jnp.ones((vd,), dt),
                           "bias": jnp.zeros((vd,), dt)},
            "projection": {"kernel": _dense(next(keys), (vd, p), dt)},
        },
        "logit_scale": jnp.asarray(c.logit_scale_init, jnp.float32),
    }


def _attention(x, layer, t: TowerConfig, causal: bool, use_flash: bool):
    b, s, d = x.shape
    h, hd = t.num_heads, t.head_dim
    q = (x @ layer["q_proj"]["kernel"]).reshape(b, s, h, hd)
    k = (x @ layer["k_proj"]["kernel"]).reshape(b, s, h, hd)
    v = (x @ layer["v_proj"]["kernel"]).reshape(b, s, h, hd)
    q, k, v = (z.transpose(0, 2, 1, 3) for z in (q, k, v))
    if use_flash:
        out = flash_attention_auto(q, k, v, causal)
    else:
        out = mha_reference(q, k, v, causal=causal)
    # named for the "attn_saveable" remat policy
    out = checkpoint_name(out, "attn_out")
    return out.transpose(0, 2, 1, 3).reshape(b, s, h * hd) @ (
        layer["o_proj"]["kernel"]
    )


def _tower_block(t: TowerConfig, eps, causal, use_flash):
    """Pre-LN transformer block shared by both towers."""

    def block(x, layer):
        layer = cast_floats(layer, x.dtype)
        h = _layer_norm(x, layer["attn_norm"]["scale"],
                        layer["attn_norm"]["bias"], eps)
        x = x + _attention(h, layer, t, causal, use_flash)
        h = _layer_norm(x, layer["ffn_norm"]["scale"],
                        layer["ffn_norm"]["bias"], eps)
        h = jax.nn.gelu(h @ layer["up_proj"]["kernel"])
        x = x + h @ layer["down_proj"]["kernel"]
        return x, None

    return block


def encode_text(params: Dict, input_ids: jax.Array,
                config: CLIPConfig) -> jax.Array:
    """[B, S] token ids -> [B, proj] L2-normalized embeddings. Pooling:
    the last token position (CLIP uses argmax over EOT; with
    right-padded sequences the max id position — here simply the final
    position, callers pad with EOT)."""
    c = config
    tp = params["text"]
    s = input_ids.shape[1]
    x = tp["embed_tokens"]["embedding"][input_ids] + tp["pos_embed"][None, :s]
    x = x.astype(c.compute_dtype)
    block = apply_remat(
        _tower_block(c.text, c.layer_norm_eps, causal=True,
                     use_flash=c.use_flash),
        c.remat_policy,
    )
    x, _ = lax.scan(block, x, tp["layers"])
    x = _layer_norm(x, tp["final_norm"]["scale"], tp["final_norm"]["bias"],
                    c.layer_norm_eps)
    pooled = x[:, -1, :] @ tp["projection"]["kernel"].astype(x.dtype)
    pooled = pooled.astype(jnp.float32)
    return pooled / jnp.linalg.norm(pooled, axis=-1, keepdims=True)


def _patchify(pixels: jax.Array, patch: int) -> jax.Array:
    """[B, H, W, 3] -> [B, n_patches, patch*patch*3]."""
    b, hh, ww, ch = pixels.shape
    gh, gw = hh // patch, ww // patch
    x = pixels.reshape(b, gh, patch, gw, patch, ch)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, gh * gw, patch * patch * ch)


def encode_image(params: Dict, pixels: jax.Array,
                 config: CLIPConfig) -> jax.Array:
    """[B, H, W, 3] -> [B, proj] L2-normalized embeddings."""
    c = config
    vp = params["vision"]
    x = _patchify(pixels, c.patch_size) @ vp["patch_embed"]["kernel"]
    cls = jnp.broadcast_to(
        vp["cls_token"][None, None, :], (x.shape[0], 1, x.shape[-1])
    )
    x = jnp.concatenate([cls, x], axis=1) + vp["pos_embed"][None]
    x = x.astype(c.compute_dtype)
    block = apply_remat(
        _tower_block(c.vision, c.layer_norm_eps, causal=False,
                     use_flash=c.use_flash),
        c.remat_policy,
    )
    x, _ = lax.scan(block, x, vp["layers"])
    x = _layer_norm(x, vp["final_norm"]["scale"], vp["final_norm"]["bias"],
                    c.layer_norm_eps)
    pooled = x[:, 0, :] @ vp["projection"]["kernel"].astype(x.dtype)
    pooled = pooled.astype(jnp.float32)
    return pooled / jnp.linalg.norm(pooled, axis=-1, keepdims=True)


def contrastive_loss(
    params: Dict, text_emb: jax.Array, image_emb: jax.Array
) -> Tuple[jax.Array, Dict]:
    """Symmetric InfoNCE over the (global) batch."""
    # CLIP recipe: clamp the learnable temperature so exp(logit_scale)
    # never exceeds 100, preventing runaway contrastive logits.
    scale = jnp.exp(jnp.minimum(params["logit_scale"], math.log(100.0)))
    logits = scale * text_emb @ image_emb.T  # [B, B]
    labels = jnp.arange(logits.shape[0])
    t2i = -jnp.mean(
        jax.nn.log_softmax(logits, axis=-1)[labels, labels]
    )
    i2t = -jnp.mean(
        jax.nn.log_softmax(logits.T, axis=-1)[labels, labels]
    )
    loss = (t2i + i2t) / 2
    acc = jnp.mean(jnp.argmax(logits, axis=-1) == labels)
    return loss, {"t2i_loss": t2i, "i2t_loss": i2t, "accuracy": acc}


def make_init_fn(config: CLIPConfig):
    return partial(init, config=config)


def make_loss_fn(config: CLIPConfig):
    """Contrastive loss over {"input_ids", "pixel_values"}."""

    def loss_fn(params, batch, rng):
        del rng
        text = encode_text(params, batch["input_ids"], config)
        image = encode_image(params, batch["pixel_values"], config)
        return contrastive_loss(params, text, image)

    return loss_fn


def param_count(config: CLIPConfig) -> int:
    return common_param_count(partial(init, config=config))
