"""GPT-NeoX-family decoder (parallel-residual, partial-rotary).

Role parity: the reference ships Megatron-sharded GPT-NeoX attention/MLP
modules (``atorch/modules/distributed_modules/transformer.py`` GPTNeoX
entries in the shardable-operator registry) and FlashAttention adapters for
the family. Here the family is TPU-first like ``models.llama``:

  * functional init/apply, scan over stacked layers, flash attention;
  * the two NeoX signatures are architectural, not kernel-level:
    **parallel residual** ``x + attn(ln1(x)) + mlp(ln2(x))`` (one residual
    read, attention and MLP computable concurrently — XLA fuses them into
    one block with no sequential dependency), and **partial rotary** —
    RoPE on the first ``rotary_pct`` of each head's dims, pass-through on
    the rest;
  * LayerNorm with bias, biased projections, GELU MLP, untied head.

Sharding: ``parallel.sharding_rules.neox_rules`` (Megatron column/row split
with bias handling, same layout discipline as bert_rules).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, replace
from functools import partial
from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name

from dlrover_tpu.models.common import (
    cast_floats,
    dense_init as _dense,
    layer_norm as _layer_norm,
    param_count as common_param_count,
)
from dlrover_tpu.models.losses import masked_lm_loss
from dlrover_tpu.ops.attention_ref import mha_reference
from dlrover_tpu.ops.flash_attention import flash_attention_auto
from dlrover_tpu.ops.remat import apply_remat, remat_enabled


@dataclass(frozen=True)
class GPTNeoXConfig:
    vocab_size: int = 50432
    hidden_size: int = 2048
    num_layers: int = 16
    num_heads: int = 16
    intermediate_size: int = 8192
    max_seq_len: int = 2048
    rotary_pct: float = 0.25
    rope_theta: float = 10000.0
    ln_eps: float = 1e-5
    use_parallel_residual: bool = True
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    remat_policy: str = "dots_saveable"
    use_flash: bool = True
    flash_block_q: int = 512
    flash_block_k: int = 1024
    flash_interpret: Any = None
    # sequence parallelism (long context): seq_axis="seq" + the Mesh
    # runs ring attention inside the jitted GSPMD program — the same
    # contract as LlamaConfig and GLMConfig (whose prefix-LM mask gets
    # its own ring decomposition, ops/ring_attention._ring_prefix).
    seq_axis: Any = None
    mesh: Any = None

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def rotary_dims(self) -> int:
        # even number of rotated dims (pairs), NeoX convention
        return int(self.head_dim * self.rotary_pct) // 2 * 2


def pythia_1b(**overrides) -> GPTNeoXConfig:
    return replace(GPTNeoXConfig(), **overrides)


def pythia_6_9b(**overrides) -> GPTNeoXConfig:
    return replace(
        GPTNeoXConfig(hidden_size=4096, num_layers=32, num_heads=32,
                      intermediate_size=16384),
        **overrides,
    )


def neox_tiny(**overrides) -> GPTNeoXConfig:
    return replace(
        GPTNeoXConfig(vocab_size=256, hidden_size=64, num_layers=2,
                      num_heads=4, intermediate_size=128, max_seq_len=128,
                      compute_dtype=jnp.float32, use_flash=False),
        **overrides,
    )


# -- init -------------------------------------------------------------------


def init(rng: jax.Array, config: GPTNeoXConfig) -> Dict:
    c = config
    dt = c.param_dtype
    keys = iter(jax.random.split(rng, 12))
    l, d, f = c.num_layers, c.hidden_size, c.intermediate_size
    h, hd = c.num_heads, c.head_dim

    layers = {
        "input_norm": {"scale": jnp.ones((l, d), dt),
                       "bias": jnp.zeros((l, d), dt)},
        "post_norm": {"scale": jnp.ones((l, d), dt),
                      "bias": jnp.zeros((l, d), dt)},
        "q_proj": {"kernel": _dense(next(keys), (l, d, h * hd), dt),
                   "bias": jnp.zeros((l, h * hd), dt)},
        "k_proj": {"kernel": _dense(next(keys), (l, d, h * hd), dt),
                   "bias": jnp.zeros((l, h * hd), dt)},
        "v_proj": {"kernel": _dense(next(keys), (l, d, h * hd), dt),
                   "bias": jnp.zeros((l, h * hd), dt)},
        "o_proj": {"kernel": _dense(next(keys), (l, h * hd, d), dt),
                   "bias": jnp.zeros((l, d), dt)},
        "up_proj": {"kernel": _dense(next(keys), (l, d, f), dt),
                    "bias": jnp.zeros((l, f), dt)},
        "down_proj": {"kernel": _dense(next(keys), (l, f, d), dt,
                                       scale=1.0 / math.sqrt(f)),
                      "bias": jnp.zeros((l, d), dt)},
    }
    return {
        "embed_tokens": {"embedding": jax.random.normal(
            next(keys), (c.vocab_size, d), dt) * 0.02},
        "layers": layers,
        "final_norm": {"scale": jnp.ones((d,), dt),
                       "bias": jnp.zeros((d,), dt)},
        "lm_head": {"kernel": _dense(next(keys), (d, c.vocab_size), dt)},
    }


# -- forward ----------------------------------------------------------------


def _partial_rope(x, positions, theta, rot_dims):
    """Rotate only the first ``rot_dims`` of each head dim (NeoX style)."""
    if rot_dims == 0:
        return x
    half = rot_dims // 2
    freqs = jnp.exp(
        -math.log(theta) * jnp.arange(0, half, dtype=jnp.float32) / half
    )
    angles = positions[:, :, None].astype(jnp.float32) * freqs[None, None, :]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    rot, rest = x[..., :rot_dims], x[..., rot_dims:]
    x1, x2 = rot[..., :half], rot[..., half:]
    rotated = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    ).astype(x.dtype)
    return jnp.concatenate([rotated, rest], axis=-1)


def _attention(x, layer, c: GPTNeoXConfig, positions, segment_ids=None):
    b, s, d = x.shape
    h, hd = c.num_heads, c.head_dim
    q = (x @ layer["q_proj"]["kernel"] + layer["q_proj"]["bias"]
         ).reshape(b, s, h, hd)
    k = (x @ layer["k_proj"]["kernel"] + layer["k_proj"]["bias"]
         ).reshape(b, s, h, hd)
    v = (x @ layer["v_proj"]["kernel"] + layer["v_proj"]["bias"]
         ).reshape(b, s, h, hd)
    q = _partial_rope(q, positions, c.rope_theta, c.rotary_dims)
    k = _partial_rope(k, positions, c.rope_theta, c.rotary_dims)
    q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
    if c.seq_axis is not None:
        # long context: ring attention over the "seq" mesh axis (the
        # llama branch semantics exactly; segment ids, when present,
        # ride the ring with the KV shards). An explicit config mesh
        # wins; else the AMBIENT mesh (rebuilt by every accelerate)
        # keeps ring configs elastic-safe.
        from dlrover_tpu.ops.ring_attention import (
            ambient_ring_mesh,
            impl_from_flags,
            ring_attention,
            ring_attention_local,
        )

        impl = impl_from_flags(c.use_flash, c.flash_interpret)
        ring_mesh = (c.mesh if c.mesh is not None
                     else ambient_ring_mesh(c.seq_axis))
        if ring_mesh is not None:
            out = ring_attention(
                q, k, v, ring_mesh, axis_name=c.seq_axis, causal=True,
                batch_axes=("data", "fsdp"), head_axis="tensor",
                block_q=c.flash_block_q, block_k=c.flash_block_k,
                segment_ids=segment_ids, impl=impl,
            )
        else:
            out = ring_attention_local(
                q, k, v, axis_name=c.seq_axis, causal=True,
                block_q=c.flash_block_q, block_k=c.flash_block_k,
                segment_ids=segment_ids, impl=impl,
            )
    elif segment_ids is not None:
        from dlrover_tpu.ops.flash_attention import segmented_attention

        out = segmented_attention(
            q, k, v, segment_ids, c.use_flash,
            block_q=c.flash_block_q, block_k=c.flash_block_k,
            interpret=c.flash_interpret,
        )
    elif c.use_flash:
        out = flash_attention_auto(q, k, v, True,
                                   block_q=c.flash_block_q,
                                   block_k=c.flash_block_k,
                                   interpret=c.flash_interpret)
    else:
        out = mha_reference(q, k, v, causal=True)
    # named so the "attn_saveable" remat policy can keep exactly the
    # attention outputs (without the tag the policy silently saves
    # nothing for this family)
    out = checkpoint_name(out, "attn_out")
    out = out.transpose(0, 2, 1, 3).reshape(b, s, h * hd)
    return out @ layer["o_proj"]["kernel"] + layer["o_proj"]["bias"]


def _mlp(x, layer):
    up = x @ layer["up_proj"]["kernel"] + layer["up_proj"]["bias"]
    return jax.nn.gelu(up) @ layer["down_proj"]["kernel"] \
        + layer["down_proj"]["bias"]


def _block(c: GPTNeoXConfig, segment_ids=None, positions=None):
    def block(x, layer):
        layer = cast_floats(layer, c.compute_dtype)
        pos = positions
        if pos is None:
            pos = jnp.broadcast_to(jnp.arange(x.shape[1]), x.shape[:2])
        attn_in = _layer_norm(x, layer["input_norm"]["scale"],
                              layer["input_norm"]["bias"], c.ln_eps)
        attn_out = _attention(attn_in, layer, c, pos, segment_ids)
        if c.use_parallel_residual:
            # x + attn(ln1(x)) + mlp(ln2(x)): both branches read the SAME
            # residual stream — one add chain, no attn->mlp dependency
            mlp_in = _layer_norm(x, layer["post_norm"]["scale"],
                                 layer["post_norm"]["bias"], c.ln_eps)
            return x + attn_out + _mlp(mlp_in, layer), None
        x = x + attn_out
        mlp_in = _layer_norm(x, layer["post_norm"]["scale"],
                             layer["post_norm"]["bias"], c.ln_eps)
        return x + _mlp(mlp_in, layer), None

    return block


def apply(params: Dict, input_ids: jax.Array, config: GPTNeoXConfig,
          rng: Optional[jax.Array] = None,
          segment_ids: Optional[jax.Array] = None) -> jax.Array:
    """``segment_ids`` [B, S]: packed-sequence mode — per-document
    attention and segment-relative rotary positions."""
    c = config
    x = params["embed_tokens"]["embedding"][input_ids].astype(c.compute_dtype)
    positions = None
    if segment_ids is not None:
        from dlrover_tpu.models.common import segment_positions

        positions = segment_positions(segment_ids)
    block = apply_remat(_block(c, segment_ids, positions), c.remat_policy)
    x, _ = lax.scan(block, x, params["layers"])
    x = _layer_norm(x, params["final_norm"]["scale"],
                    params["final_norm"]["bias"], c.ln_eps)
    logits = x @ params["lm_head"]["kernel"].astype(c.compute_dtype)
    return logits.astype(jnp.float32)


def apply_pipelined(
    params: Dict,
    input_ids: jax.Array,
    config: GPTNeoXConfig,
    num_stages: int,
    num_microbatches: int,
    num_virtual: int = 1,
    stage_depths: Optional[Sequence[int]] = None,
) -> jax.Array:
    """Forward pass with the NeoX blocks run as a GPipe / interleaved
    pipeline over the "pipe" mesh axis (``parallel.pipeline``), the same
    formulation as ``models.llama.apply_pipelined``: embed and
    final-norm/head stay outside in the surrounding GSPMD program (the
    head spread over pipe as extra data parallelism), stages are the
    scan-stacked layer chunks. Use with the "neox_pp" rule set.

    ``stage_depths``: per-stage-chunk layer counts (visit order) for
    uneven splits; see ``pipeline.stack_stages_uneven``. Plain causal
    mode only (packed segments ride the unpipelined ``apply``).
    """
    from dlrover_tpu.parallel.pipeline import (
        dispatch_pipeline,
        masked_layer_scan,
        merge_microbatches,
        pipe_batch_constraint,
        split_microbatches,
    )

    c = config
    x = params["embed_tokens"]["embedding"][input_ids].astype(c.compute_dtype)

    def stage_fn(chunk_and_mask, x):
        layers_chunk, mask = chunk_and_mask
        block = apply_remat(_block(c), c.remat_policy)
        return masked_layer_scan(block, x, layers_chunk, mask)

    x_mb = split_microbatches(x, num_microbatches)
    out_mb = dispatch_pipeline(
        stage_fn, params["layers"], x_mb,
        num_stages, num_virtual, stage_depths,
        remat_stage=remat_enabled(c.remat_policy),
    )
    x = merge_microbatches(out_mb)

    x = pipe_batch_constraint(x)
    x = _layer_norm(x, params["final_norm"]["scale"],
                    params["final_norm"]["bias"], c.ln_eps)
    logits = x @ params["lm_head"]["kernel"].astype(c.compute_dtype)
    return logits.astype(jnp.float32)


# -- training glue ----------------------------------------------------------


def make_init_fn(config: GPTNeoXConfig):
    return partial(init, config=config)


def make_loss_fn(config: GPTNeoXConfig, z_loss_weight: float = 0.0):
    def loss_fn(params, batch, rng):
        logits = apply(params, batch["input_ids"], config, rng,
                       segment_ids=batch.get("segment_ids"))
        return masked_lm_loss(logits, batch["labels"], z_loss_weight), {}

    return loss_fn


def param_count(config: GPTNeoXConfig) -> int:
    return common_param_count(partial(init, config=config))
