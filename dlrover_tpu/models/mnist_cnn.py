"""MNIST CNN (BASELINE config #1: the elastic-agent smoke-test model,
parity with ``/root/reference/examples/pytorch/mnist``)."""

from __future__ import annotations

from functools import partial
from typing import Dict

import jax
import jax.numpy as jnp
from jax import lax


def init(rng: jax.Array, num_classes: int = 10) -> Dict:
    k1, k2, k3, k4 = jax.random.split(rng, 4)
    return {
        "conv1": {"kernel": jax.random.normal(k1, (3, 3, 1, 16)) * 0.1},
        "conv2": {"kernel": jax.random.normal(k2, (3, 3, 16, 32)) * 0.1},
        "dense1": {"kernel": jax.random.normal(k3, (7 * 7 * 32, 128)) * 0.02,
                   "bias": jnp.zeros((128,))},
        "dense2": {"kernel": jax.random.normal(k4, (128, num_classes)) * 0.02,
                   "bias": jnp.zeros((num_classes,))},
    }


def _conv(x, kernel, stride=1):
    return lax.conv_general_dilated(
        x, kernel, (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def apply(params: Dict, images: jax.Array) -> jax.Array:
    """images: [B, 28, 28, 1] -> logits [B, 10]."""
    x = jax.nn.relu(_conv(images, params["conv1"]["kernel"]))
    x = lax.reduce_window(x, -jnp.inf, lax.max, (1, 2, 2, 1), (1, 2, 2, 1),
                          "VALID")
    x = jax.nn.relu(_conv(x, params["conv2"]["kernel"]))
    x = lax.reduce_window(x, -jnp.inf, lax.max, (1, 2, 2, 1), (1, 2, 2, 1),
                          "VALID")
    x = x.reshape(x.shape[0], -1)
    x = jax.nn.relu(x @ params["dense1"]["kernel"] + params["dense1"]["bias"])
    return x @ params["dense2"]["kernel"] + params["dense2"]["bias"]


def make_init_fn():
    return partial(init)


def make_loss_fn():
    def loss_fn(params, batch, rng):
        logits = apply(params, batch["image"])
        import optax

        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["label"]
        ).mean()
        acc = (logits.argmax(-1) == batch["label"]).mean()
        return loss, {"accuracy": acc}

    return loss_fn
