"""GLM-family decoder (prefix-LM mask, 2D positional encoding).

Role parity: the reference's GLM support — Megatron-sharded GLM blocks in
the shardable-operator registry and the glm-mask FlashAttention adapter
(``atorch/modules/transformer/layers.py:1191`` ``fa2_with_glm_mask``).
GLM's two signatures, built TPU-first:

  * **prefix-LM attention**: token ``i`` attends to ``j`` iff
    ``j < prefix_len`` (bidirectional over the prompt) OR ``j <= i``
    (causal over the generation). Per-example ``prefix_len`` arrives in
    the batch; the mask is computed in-program from iota comparisons —
    static shapes, no data-dependent control flow, so one compiled
    program serves every prefix split.
  * **2D positions** (autoregressive blank-infilling): position ids run
    0..p-1 over the prefix then freeze at ``p``; block-position ids are 0
    over the prefix and 1..n over the generation. Two learned tables are
    summed into the token embedding, matching GLM's scheme.

Pure-causal batches (prefix_len == 0) route through the Pallas flash
kernel; prefix batches use the bias-capable XLA attention — the mask is a
per-batch bias, which XLA fuses into the attention einsum.

Sharding: ``parallel.sharding_rules.glm_rules``.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, replace
from functools import partial
from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name

from dlrover_tpu.models.common import (
    cast_floats,
    dense_init as _dense,
    layer_norm as _layer_norm,
    param_count as common_param_count,
)
from dlrover_tpu.models.losses import masked_lm_loss
from dlrover_tpu.ops.attention_ref import mha_reference
from dlrover_tpu.ops.flash_attention import flash_attention_auto
from dlrover_tpu.ops.remat import apply_remat, remat_enabled


@dataclass(frozen=True)
class GLMConfig:
    vocab_size: int = 50304
    hidden_size: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    intermediate_size: int = 4096
    max_seq_len: int = 1024
    ln_eps: float = 1e-5
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.bfloat16
    remat_policy: str = "dots_saveable"
    use_flash: bool = True  # used only for pure-causal batches
    flash_block_q: int = 512
    flash_block_k: int = 1024
    flash_interpret: Any = None
    # sequence parallelism (long context): seq_axis="seq" + the Mesh
    # runs ring attention inside the jitted GSPMD program — the same
    # contract as Llama/NeoX, INCLUDING prefix-LM batches: the prefix
    # mask decomposes over the ring (past shards fully visible,
    # diagonal runs the shifted prefix kernel, future shards contribute
    # only their prompt columns). Packed (segment_ids) batches ride the
    # causal packed ring.
    seq_axis: Any = None
    mesh: Any = None

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads


def glm_large(**overrides) -> GLMConfig:
    return replace(GLMConfig(), **overrides)


def glm_10b(**overrides) -> GLMConfig:
    return replace(
        GLMConfig(hidden_size=4096, num_layers=48, num_heads=64,
                  intermediate_size=16384, max_seq_len=2048),
        **overrides,
    )


def glm_tiny(**overrides) -> GLMConfig:
    return replace(
        GLMConfig(vocab_size=256, hidden_size=64, num_layers=2,
                  num_heads=4, intermediate_size=128, max_seq_len=128,
                  compute_dtype=jnp.float32, use_flash=False),
        **overrides,
    )


# -- init -------------------------------------------------------------------


def init(rng: jax.Array, config: GLMConfig) -> Dict:
    c = config
    dt = c.param_dtype
    keys = iter(jax.random.split(rng, 12))
    l, d, f = c.num_layers, c.hidden_size, c.intermediate_size
    h, hd = c.num_heads, c.head_dim

    layers = {
        "input_norm": {"scale": jnp.ones((l, d), dt),
                       "bias": jnp.zeros((l, d), dt)},
        "post_norm": {"scale": jnp.ones((l, d), dt),
                      "bias": jnp.zeros((l, d), dt)},
        "q_proj": {"kernel": _dense(next(keys), (l, d, h * hd), dt),
                   "bias": jnp.zeros((l, h * hd), dt)},
        "k_proj": {"kernel": _dense(next(keys), (l, d, h * hd), dt),
                   "bias": jnp.zeros((l, h * hd), dt)},
        "v_proj": {"kernel": _dense(next(keys), (l, d, h * hd), dt),
                   "bias": jnp.zeros((l, h * hd), dt)},
        "o_proj": {"kernel": _dense(next(keys), (l, h * hd, d), dt),
                   "bias": jnp.zeros((l, d), dt)},
        "up_proj": {"kernel": _dense(next(keys), (l, d, f), dt),
                    "bias": jnp.zeros((l, f), dt)},
        "down_proj": {"kernel": _dense(next(keys), (l, f, d), dt,
                                       scale=1.0 / math.sqrt(f)),
                      "bias": jnp.zeros((l, d), dt)},
    }
    return {
        "embed_tokens": {"embedding": jax.random.normal(
            next(keys), (c.vocab_size, d), dt) * 0.02},
        # 2D positional encoding: absolute + block tables
        "pos_embed": {"embedding": jax.random.normal(
            next(keys), (c.max_seq_len + 1, d), dt) * 0.02},
        "block_pos_embed": {"embedding": jax.random.normal(
            next(keys), (c.max_seq_len + 1, d), dt) * 0.02},
        "layers": layers,
        "final_norm": {"scale": jnp.ones((d,), dt),
                       "bias": jnp.zeros((d,), dt)},
        "lm_head": {"kernel": _dense(next(keys), (d, c.vocab_size), dt)},
    }


# -- masks / positions ------------------------------------------------------


def glm_positions(seq_len: int, prefix_len: jax.Array):
    """2D position ids from per-example prefix lengths [B].

    Returns (position_ids, block_position_ids), each [B, S]:
      prefix token i       -> (i, 0)
      generation token g_j -> (prefix_len, j + 1)
    """
    idx = jnp.arange(seq_len)[None, :]  # [1, S]
    p = prefix_len[:, None]  # [B, 1]
    position_ids = jnp.where(idx < p, idx, p)
    block_position_ids = jnp.where(idx < p, 0, idx - p + 1)
    return position_ids, block_position_ids


def prefix_lm_bias(seq_len: int, prefix_len: jax.Array,
                   dtype=jnp.float32) -> jax.Array:
    """Additive attention bias [B, 1, S, S]: 0 where attending is allowed
    (j < prefix_len OR j <= i), large-negative otherwise."""
    i = jnp.arange(seq_len)[:, None]  # queries
    j = jnp.arange(seq_len)[None, :]  # keys
    causal = j <= i  # [S, S]
    in_prefix = j[None, :, :] < prefix_len[:, None, None]  # [B, S, S]
    allowed = jnp.logical_or(causal[None], in_prefix)
    neg = jnp.asarray(jnp.finfo(jnp.float32).min, dtype)
    return jnp.where(allowed, jnp.zeros((), dtype), neg)[:, None, :, :]


# -- forward ----------------------------------------------------------------


def _attention(x, layer, c: GLMConfig, bias, prefix_len=None,
               segment_ids=None):
    b, s, d = x.shape
    h, hd = c.num_heads, c.head_dim
    q = (x @ layer["q_proj"]["kernel"] + layer["q_proj"]["bias"]
         ).reshape(b, s, h, hd)
    k = (x @ layer["k_proj"]["kernel"] + layer["k_proj"]["bias"]
         ).reshape(b, s, h, hd)
    v = (x @ layer["v_proj"]["kernel"] + layer["v_proj"]["bias"]
         ).reshape(b, s, h, hd)
    q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))
    if c.seq_axis is not None:
        # long context: ring attention over the "seq" mesh axis — all
        # three GLM modes (causal, packed, prefix-LM) decompose over
        # the ring; the bias is never materialized here
        from dlrover_tpu.ops.ring_attention import (
            ambient_ring_mesh,
            impl_from_flags,
            ring_attention,
            ring_attention_local,
        )

        impl = impl_from_flags(c.use_flash, c.flash_interpret)
        common = dict(
            axis_name=c.seq_axis, causal=True,
            block_q=c.flash_block_q, block_k=c.flash_block_k,
            segment_ids=segment_ids, prefix_len=prefix_len, impl=impl,
        )
        # explicit config mesh wins; else the ambient mesh (rebuilt by
        # every accelerate) keeps ring configs elastic-safe
        ring_mesh = (c.mesh if c.mesh is not None
                     else ambient_ring_mesh(c.seq_axis))
        if ring_mesh is not None:
            out = ring_attention(
                q, k, v, ring_mesh, batch_axes=("data", "fsdp"),
                head_axis="tensor", **common,
            )
        else:
            out = ring_attention_local(q, k, v, **common)
        out = checkpoint_name(out, "attn_out")
        out = out.transpose(0, 2, 1, 3).reshape(b, s, h * hd)
        return out @ layer["o_proj"]["kernel"] + layer["o_proj"]["bias"]
    # segment dispatch comes FIRST (the sibling families' discipline):
    # the plain-flash branch below also matches when segment_ids is set
    # (bias is None then), and taking it would silently drop the
    # per-document mask
    if segment_ids is not None:
        from dlrover_tpu.ops.flash_attention import segmented_attention

        out = segmented_attention(
            q, k, v, segment_ids, c.use_flash,
            block_q=c.flash_block_q, block_k=c.flash_block_k,
            interpret=c.flash_interpret,
        )
    elif prefix_len is not None and c.use_flash:
        # the prefix-LM mask fused into the Pallas tiles — no S x S bias
        from dlrover_tpu.ops.flash_attention import (
            flash_attention_prefix_auto,
        )

        out = flash_attention_prefix_auto(
            q, k, v, prefix_len,
            block_q=c.flash_block_q, block_k=c.flash_block_k,
            interpret=c.flash_interpret,
        )
    elif bias is None and c.use_flash:
        out = flash_attention_auto(q, k, v, True,
                                   block_q=c.flash_block_q,
                                   block_k=c.flash_block_k,
                                   interpret=c.flash_interpret)
    else:
        # prefix-LM mask rides as an additive bias; causal=False because
        # the bias already encodes the causal part
        out = mha_reference(q, k, v, bias=bias, causal=bias is None)
    # named so the "attn_saveable" remat policy keeps the attention
    # outputs for this family too
    out = checkpoint_name(out, "attn_out")
    out = out.transpose(0, 2, 1, 3).reshape(b, s, h * hd)
    return out @ layer["o_proj"]["kernel"] + layer["o_proj"]["bias"]


def _block(c: GLMConfig, bias, prefix_len=None, segment_ids=None):
    def block(x, layer):
        layer = cast_floats(layer, c.compute_dtype)
        attn_in = _layer_norm(x, layer["input_norm"]["scale"],
                              layer["input_norm"]["bias"], c.ln_eps)
        x = x + _attention(attn_in, layer, c, bias, prefix_len,
                           segment_ids)
        mlp_in = _layer_norm(x, layer["post_norm"]["scale"],
                             layer["post_norm"]["bias"], c.ln_eps)
        up = mlp_in @ layer["up_proj"]["kernel"] + layer["up_proj"]["bias"]
        mlp_out = jax.nn.gelu(up) @ layer["down_proj"]["kernel"] \
            + layer["down_proj"]["bias"]
        return x + mlp_out, None

    return block


def apply(params: Dict, input_ids: jax.Array, config: GLMConfig,
          rng: Optional[jax.Array] = None,
          prefix_len: Optional[jax.Array] = None,
          segment_ids: Optional[jax.Array] = None) -> jax.Array:
    """prefix_len: [B] int array; None means pure causal LM (flash path).
    segment_ids: [B, S] packed-document mode (causal per document,
    positions restarting per segment) — mutually exclusive with
    prefix_len."""
    c = config
    b, s = input_ids.shape
    if prefix_len is not None and segment_ids is not None:
        raise ValueError("prefix_len and segment_ids are mutually "
                         "exclusive GLM modes")
    x = params["embed_tokens"]["embedding"][input_ids]
    if prefix_len is not None:
        pos_ids, block_ids = glm_positions(s, prefix_len)
        # the flash path fuses the prefix mask into the kernel tiles,
        # and the ring path decomposes it per shard; the S x S bias is
        # only materialized for the dense reference (use_flash=False,
        # no seq_axis)
        bias = (None if (c.use_flash or c.seq_axis is not None)
                else prefix_lm_bias(s, prefix_len, c.compute_dtype))
    elif segment_ids is not None:
        from dlrover_tpu.models.common import segment_positions

        pos_ids = segment_positions(segment_ids)
        block_ids = jnp.zeros((b, s), jnp.int32)
        bias = None
    else:
        pos_ids = jnp.broadcast_to(jnp.arange(s), (b, s))
        block_ids = jnp.zeros((b, s), jnp.int32)
        bias = None
    x = x + params["pos_embed"]["embedding"][pos_ids] \
        + params["block_pos_embed"]["embedding"][block_ids]
    x = x.astype(c.compute_dtype)

    block = apply_remat(_block(c, bias, prefix_len, segment_ids),
                        c.remat_policy)
    x, _ = lax.scan(block, x, params["layers"])
    x = _layer_norm(x, params["final_norm"]["scale"],
                    params["final_norm"]["bias"], c.ln_eps)
    logits = x @ params["lm_head"]["kernel"].astype(c.compute_dtype)
    return logits.astype(jnp.float32)


def apply_pipelined(
    params: Dict,
    input_ids: jax.Array,
    config: GLMConfig,
    num_stages: int,
    num_microbatches: int,
    prefix_len: Optional[jax.Array] = None,
    num_virtual: int = 1,
    stage_depths: Optional[Sequence[int]] = None,
) -> jax.Array:
    """Forward pass with the GLM blocks as a GPipe / interleaved
    pipeline over the "pipe" mesh axis — including PREFIX-LM mode: the
    per-example ``prefix_len`` rides the pipeline state beside its
    microbatch (the mask context must travel with the microbatch around
    the stage ring), and each stage rebuilds the mask from it — fused
    into the Pallas tiles on the flash path, an additive bias on the
    dense reference path. Use with the "glm_pp" rule set.

    2D positions are applied at embed time (outside the pipeline) from
    the full-batch ``prefix_len``, exactly as ``apply`` does. Packed
    ``segment_ids`` mode rides the unpipelined ``apply``.
    """
    from dlrover_tpu.parallel.pipeline import (
        dispatch_pipeline,
        masked_layer_scan,
        merge_microbatches,
        pipe_batch_constraint,
        split_microbatches,
    )

    c = config
    b, s = input_ids.shape
    x = params["embed_tokens"]["embedding"][input_ids]
    if prefix_len is not None:
        pos_ids, block_ids = glm_positions(s, prefix_len)
    else:
        pos_ids = jnp.broadcast_to(jnp.arange(s), (b, s))
        block_ids = jnp.zeros((b, s), jnp.int32)
    x = x + params["pos_embed"]["embedding"][pos_ids] \
        + params["block_pos_embed"]["embedding"][block_ids]
    x = x.astype(c.compute_dtype)

    with_prefix = prefix_len is not None

    def run_chunk(layers_chunk, x, pfx, mask=None):
        # mirror apply()'s dispatch: the flash path fuses the prefix
        # mask into the kernel tiles and the ring path decomposes it
        # per shard (both take prefix_len); the S x S bias is only
        # materialized for the dense reference
        mask_in_kernel = c.use_flash or c.seq_axis is not None
        bias = None
        if with_prefix and not mask_in_kernel:
            bias = prefix_lm_bias(x.shape[1], pfx, c.compute_dtype)
        block = apply_remat(
            _block(c, bias, pfx if (with_prefix and mask_in_kernel)
                   else None),
            c.remat_policy,
        )
        return masked_layer_scan(block, x, layers_chunk, mask)

    if with_prefix:
        state = (x, prefix_len)

        def stage_fn(chunk_and_mask, st):
            layers_chunk, mask = chunk_and_mask
            x, pfx = st
            return (run_chunk(layers_chunk, x, pfx, mask), pfx)
    else:
        state = x

        def stage_fn(chunk_and_mask, x):
            layers_chunk, mask = chunk_and_mask
            return run_chunk(layers_chunk, x, None, mask)

    state_mb = split_microbatches(state, num_microbatches)
    out_mb = dispatch_pipeline(
        stage_fn, params["layers"], state_mb,
        num_stages, num_virtual, stage_depths,
        remat_stage=remat_enabled(c.remat_policy),
    )
    out_state = merge_microbatches(out_mb)
    x = out_state[0] if with_prefix else out_state

    x = pipe_batch_constraint(x)
    x = _layer_norm(x, params["final_norm"]["scale"],
                    params["final_norm"]["bias"], c.ln_eps)
    logits = x @ params["lm_head"]["kernel"].astype(c.compute_dtype)
    return logits.astype(jnp.float32)


# -- training glue ----------------------------------------------------------


def make_init_fn(config: GLMConfig):
    return partial(init, config=config)


def make_loss_fn(config: GLMConfig, z_loss_weight: float = 0.0):
    """Batches: {"input_ids", "labels"} (+ optional "prefix_len" [B] or
    "segment_ids" [B, S] — mutually exclusive). With prefix_len, loss is
    typically masked to the generation span via labels==-100 over the
    prefix (HF convention). With segment_ids (packed documents), labels
    at segment boundaries MUST be -100: the attention mask stops reads
    across documents, but only label masking stops the last token of one
    document being trained to predict the first of the next."""

    def loss_fn(params, batch, rng):
        logits = apply(params, batch["input_ids"], config, rng,
                       prefix_len=batch.get("prefix_len"),
                       segment_ids=batch.get("segment_ids"))
        return masked_lm_loss(logits, batch["labels"], z_loss_weight), {}

    return loss_fn


def param_count(config: GLMConfig) -> int:
    return common_param_count(partial(init, config=config))
