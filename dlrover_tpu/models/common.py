"""Shared building blocks for the model families."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax


def dense_init(rng, shape, dtype, scale=None):
    """Fan-in-scaled normal initializer (scale defaults to
    1/sqrt(fan_in), fan_in = second-to-last dim)."""
    scale = scale if scale is not None else 1.0 / math.sqrt(shape[-2])
    return jax.random.normal(rng, shape, dtype) * scale


def layer_norm(x, scale, bias, eps):
    """LayerNorm with f32 statistics regardless of compute dtype."""
    xf = x.astype(jnp.float32)
    mean = xf.mean(axis=-1, keepdims=True)
    var = ((xf - mean) ** 2).mean(axis=-1, keepdims=True)
    normed = (xf - mean) * lax.rsqrt(var + eps)
    return normed.astype(x.dtype) * scale + bias


def rms_norm(x, scale, eps):
    """RMSNorm with f32 statistics (llama-family)."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * lax.rsqrt(var + eps)).astype(x.dtype) * scale
