"""Shared building blocks for the model families."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax


def dense_init(rng, shape, dtype, scale=None):
    """Fan-in-scaled normal initializer (scale defaults to
    1/sqrt(fan_in), fan_in = second-to-last dim)."""
    scale = scale if scale is not None else 1.0 / math.sqrt(shape[-2])
    return jax.random.normal(rng, shape, dtype) * scale


def layer_norm(x, scale, bias, eps):
    """LayerNorm with f32 statistics regardless of compute dtype. The
    scale/bias params are cast to x's dtype so the output dtype is
    stable under scan even when params are f32 and compute is bf16."""
    xf = x.astype(jnp.float32)
    mean = xf.mean(axis=-1, keepdims=True)
    var = ((xf - mean) ** 2).mean(axis=-1, keepdims=True)
    normed = (xf - mean) * lax.rsqrt(var + eps)
    return normed.astype(x.dtype) * scale.astype(x.dtype) + bias.astype(
        x.dtype
    )


def rms_norm(x, scale, eps):
    """RMSNorm with f32 statistics (llama-family); output keeps x's
    dtype (see layer_norm)."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * lax.rsqrt(var + eps)).astype(x.dtype) * scale.astype(
        x.dtype
    )


def cast_floats(tree, dtype):
    """Cast floating leaves to ``dtype`` (params stored f32, computed
    bf16 — the mixed-precision pattern); non-float leaves pass through."""
    return jax.tree.map(
        lambda w: w.astype(dtype)
        if jnp.issubdtype(w.dtype, jnp.floating) else w,
        tree,
    )


def param_count(init_fn) -> int:
    """Total parameter count of ``init_fn(rng)`` via abstract eval."""
    import math

    abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    return sum(
        math.prod(int(s) for s in leaf.shape)
        for leaf in jax.tree.leaves(abstract)
    )


def segment_positions(segment_ids):
    """[B, S] segment ids -> position WITHIN each segment (positional
    encodings must restart per packed document, or later documents see
    phantom long distances). Shared by every packed-capable family."""
    b, s = segment_ids.shape
    idx = jnp.arange(s)[None, :]
    is_start = jnp.concatenate(
        [jnp.ones((b, 1), bool),
         segment_ids[:, 1:] != segment_ids[:, :-1]], axis=1,
    )
    starts = jax.lax.cummax(jnp.where(is_start, idx, 0), axis=1)
    return idx - starts
