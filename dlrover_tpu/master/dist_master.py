"""The distributed job master: node lifecycle + services + main loop.

Role parity: ``dlrover/python/master/dist_master.py``
(``DistributedJobMaster``) — composes the JobManager (node lifecycle over a
scaler/watcher pair), TaskManager (data shards), both rendezvous managers,
SpeedMonitor, JobMetricCollector, ElasticPsService, SyncService and the RPC
servicer; then runs a 30 s control loop checking completion / early stop /
hang, and starts auto-scaling once speed samples exist.

TPU-first: the same master drives local subprocesses (standalone, tests)
or k8s pods (production) purely through the Scaler/NodeWatcher seam.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from dlrover_tpu.common.config import get_context
from dlrover_tpu.common.constants import (
    DistributionStrategy,
    JobExitReason,
    PlatformType,
    RendezvousName,
)
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.master.elastic_training.elastic_ps import ElasticPsService
from dlrover_tpu.master.elastic_training.kv_store import KVStoreService
from dlrover_tpu.master.elastic_training.rdzv_manager import (
    ElasticTrainingRendezvousManager,
    NetworkCheckRendezvousManager,
    RendezvousManager,
)
from dlrover_tpu.master.elastic_training.sync_service import SyncService
from dlrover_tpu.master.monitor.speed_monitor import SpeedMonitor
from dlrover_tpu.master.node.event_callback import (
    AllReduceNodeHandlingCallback,
    TaskRescheduleCallback,
)
from dlrover_tpu.master.node.job_auto_scaler import JobAutoScaler
from dlrover_tpu.master.node.job_manager import DistributedJobManager
from dlrover_tpu.master.resource.job_optimizer import JobResourceOptimizer
from dlrover_tpu.master.scaler.base_scaler import Scaler
from dlrover_tpu.master.servicer import MasterServicer
from dlrover_tpu.master.shard.task_manager import TaskManager
from dlrover_tpu.master.stats.job_collector import JobMetricCollector
from dlrover_tpu.master.watcher.base_watcher import NodeWatcher
from dlrover_tpu.rpc.server import build_server
from dlrover_tpu.scheduler.job import JobArgs, local_job_args
from dlrover_tpu.scheduler.local import LocalProcessBackend

logger = get_logger("master.dist")


class DistributedJobMaster:
    def __init__(
        self,
        port: int = 0,
        job_name: str = "job",
        platform: str = PlatformType.LOCAL,
        node_num: int = 1,
        job_args: Optional[JobArgs] = None,
        scaler: Optional[Scaler] = None,
        watcher: Optional[NodeWatcher] = None,
    ):
        self.job_args = job_args or local_job_args(
            job_name=job_name, node_num=node_num
        )
        self.job_name = self.job_args.job_name

        # Services shared with the local master.
        self.speed_monitor = SpeedMonitor()
        self.task_manager = TaskManager(self.speed_monitor)
        self.rdzv_managers: Dict[str, RendezvousManager] = {
            RendezvousName.TRAINING: ElasticTrainingRendezvousManager(),
            RendezvousName.NETWORK_CHECK: NetworkCheckRendezvousManager(),
        }
        self.kv_store = KVStoreService()
        self.sync_service = SyncService()
        self.elastic_ps_service = ElasticPsService()
        self.metric_collector = JobMetricCollector(self.job_name)

        # Node lifecycle plumbing.
        scaler, watcher = self._build_backend(platform, scaler, watcher)
        self.job_optimizer = JobResourceOptimizer(self.job_args)
        callbacks = [
            TaskRescheduleCallback(self.task_manager),
            AllReduceNodeHandlingCallback(self),
        ]
        self.job_manager = DistributedJobManager(
            job_args=self.job_args,
            scaler=scaler,
            watcher=watcher,
            job_optimizer=self.job_optimizer,
            node_event_callbacks=callbacks,
        )
        self.job_auto_scaler = JobAutoScaler(
            self.job_manager, self.job_optimizer, self.speed_monitor
        )

        self.servicer = MasterServicer(
            task_manager=self.task_manager,
            rdzv_managers=self.rdzv_managers,
            speed_monitor=self.speed_monitor,
            kv_store=self.kv_store,
            sync_service=self.sync_service,
            elastic_ps_service=self.elastic_ps_service,
            job_manager=self.job_manager,
            metric_collector=self.metric_collector,
        )
        self._server, self.port = build_server(self.servicer, port=port)
        self.addr = f"127.0.0.1:{self.port}"
        # a RECOVERED diagnosis verdict re-evaluates the auto-scaler
        # immediately: optimize_once defers while verdicts are active,
        # so waiting out the periodic tick after the incident clears
        # would add up to a full scaler period of recovery latency
        self.servicer.straggler_detector.add_verdict_listener(
            self._on_diag_verdict)
        # the serving SLO policy loop feeds the auto-scaler (scale-out
        # on sustained violation, scale-in on sustained idle); the
        # resize itself rides the serving live-resize path
        self.servicer.serving_scale_policy.attach_auto_scaler(
            self.job_auto_scaler)
        # node-lifecycle loss signals (watcher events, failure reports,
        # heartbeat-loss relaunches) feed the replica directory, so
        # recovery plans stop pointing fetchers at dead holders
        self.job_manager.replica_directory = (
            self.servicer.replica_directory)
        self._stopped = threading.Event()
        self._exit_reason = ""
        self._ctx = get_context()

    def _on_diag_verdict(self, node_id: int, verdict: str):
        if verdict == "healthy":
            self.job_auto_scaler.request_immediate_evaluation()

    def _build_backend(self, platform, scaler, watcher):
        if scaler is not None and watcher is not None:
            return scaler, watcher
        if platform == PlatformType.LOCAL:
            from dlrover_tpu.master.scaler.process_scaler import LocalProcessScaler
            from dlrover_tpu.master.watcher.process_watcher import (
                LocalProcessWatcher,
            )

            backend = LocalProcessBackend()
            # Address isn't known before build_server; patched in prepare().
            self._local_backend = backend
            return (
                scaler or LocalProcessScaler(self.job_name, backend, ""),
                watcher or LocalProcessWatcher(backend),
            )
        if platform == PlatformType.KUBERNETES:
            from dlrover_tpu.master.scaler.pod_scaler import PodScaler
            from dlrover_tpu.master.watcher.k8s_watcher import PodWatcher
            from dlrover_tpu.scheduler.kubernetes import K8sClient

            client = K8sClient.singleton_instance(self.job_args.namespace)
            return (
                scaler or PodScaler(self.job_name, client, ""),
                watcher or PodWatcher(self.job_name, client),
            )
        if platform == PlatformType.RAY:
            from dlrover_tpu.master.scaler.actor_scaler import ActorScaler
            from dlrover_tpu.master.watcher.ray_watcher import ActorWatcher
            from dlrover_tpu.scheduler.ray import RayClient

            client = RayClient.singleton_instance(
                self.job_args.namespace, self.job_name
            )
            return (
                scaler or ActorScaler(self.job_name, client, master_addr=""),
                watcher or ActorWatcher(self.job_name, client),
            )
        raise ValueError(f"unsupported platform: {platform}")

    # -- lifecycle -----------------------------------------------------------

    def prepare(self):
        scaler = self.job_manager._scaler
        if hasattr(scaler, "_master_addr") and not scaler._master_addr:
            scaler._master_addr = self.addr
        self._server.start()
        self.task_manager.start()
        self.task_manager.set_task_timeout_callback(self.job_manager.remove_worker)
        self.job_manager.start()
        logger.info("distributed master serving at %s", self.addr)

    def request_stop(self, success: bool, reason: str = ""):
        self.servicer.job_success = success
        self.servicer.job_exit_requested = True
        self._exit_reason = reason

    def run(self) -> int:
        """Main control loop (reference: dist_master.py:165-214)."""
        try:
            while not self._stopped.is_set():
                if self.servicer.job_exit_requested:
                    ok = bool(self.servicer.job_success)
                    logger.info(
                        "job exiting: success=%s reason=%s", ok, self._exit_reason
                    )
                    return 0 if ok else 1

                if self.job_manager.all_workers_exited():
                    ok = self.job_manager.all_workers_succeeded()
                    self.request_stop(
                        success=ok,
                        reason=JobExitReason.SUCCEEDED if ok
                        else JobExitReason.NODE_ERROR,
                    )
                    continue

                if self.job_manager.should_early_stop():
                    self.request_stop(
                        success=False, reason=JobExitReason.RDZV_TIMEOUT_ERROR
                    )
                    continue

                hung = self.job_manager.detect_hung_nodes()
                if hung and self.task_manager.finished():
                    self.request_stop(
                        success=True, reason=JobExitReason.SUCCEEDED
                    )
                    continue

                if (
                    self.speed_monitor.sample_count
                    >= 3
                    and not self.job_auto_scaler.started
                ):
                    self.job_auto_scaler.start_auto_scaling()

                self.metric_collector.collect_runtime_stats(
                    self.speed_monitor, self.job_manager.get_job_nodes()
                )
                # the serving SLO plane ticks on the same clock the
                # local master uses (the engine self-paces its window);
                # guarded like the local master's stats loop — an SLO
                # evaluation failure must not tear down the job master
                try:
                    self.servicer.serve_slo.evaluate()
                    self.servicer.serving_scale_policy.tick()
                except Exception:  # noqa: BLE001
                    logger.exception("serving SLO tick failed")
                self._stopped.wait(self._ctx.seconds_interval_to_report)
            return 0
        finally:
            self.stop()

    def stop(self):
        self._stopped.set()
        self.job_auto_scaler.stop()
        self.job_manager.stop()
        self.task_manager.stop()
        self._server.stop(grace=1)
