"""Master CLI arguments (role parity: ``dlrover/python/master/args.py``)."""

from __future__ import annotations

import argparse


def build_master_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description="dlrover_tpu job master")
    parser.add_argument(
        "--platform", default="local", choices=["local", "k8s", "ray"],
        help="scheduling platform hosting the job nodes",
    )
    parser.add_argument("--job_name", default="dlrover-tpu-job")
    parser.add_argument("--namespace", default="default")
    parser.add_argument(
        "--port", type=int, default=0,
        help="RPC port (0 picks a free port, printed on stdout)",
    )
    parser.add_argument("--node_num", type=int, default=1)
    parser.add_argument(
        "--ray_conf", default="",
        help="JSON job conf for --platform ray (see scheduler.ray."
             "ray_job_args)",
    )
    parser.add_argument(
        "--timeout", type=float, default=0.0,
        help="exit with failure if the job outlives this many seconds (0=off)",
    )
    return parser


def parse_master_args(argv=None):
    return build_master_parser().parse_args(argv)
