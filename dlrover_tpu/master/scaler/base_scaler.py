"""ScalePlan and the Scaler interface.

Role parity: ``dlrover/python/master/scaler/base_scaler.py`` — a ScalePlan
is the single currency between the resource optimizer / job manager (who
decide) and the platform backend (who acts): group resource targets, plus
concrete nodes to launch/remove/migrate.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import Dict, List

from dlrover_tpu.common.node import Node, NodeGroupResource, NodeResource


@dataclass
class ScalePlan:
    # Target (count, per-node resource) per node type.
    node_group_resources: Dict[str, NodeGroupResource] = field(default_factory=dict)
    # Concrete nodes to create (relaunches carry their rank_index forward).
    launch_nodes: List[Node] = field(default_factory=list)
    # Concrete nodes to delete.
    remove_nodes: List[Node] = field(default_factory=list)
    # node name -> new resource: replace in place (hot-PS migration).
    migrate_nodes: Dict[str, "NodeResource"] = field(default_factory=dict)
    # PS addresses for the next PS cluster version (PS jobs only).
    ps_addrs: List[str] = field(default_factory=list)
    # Preferred recovery rung for the SURVIVING nodes while this plan
    # executes (failover.RecoveryDecision values): "live_reshard" marks
    # a pure world-resize plan — survivors should drain + snapshot +
    # reshard in place instead of restarting; "" leaves the workers'
    # own classification in charge. Rides to_dict() into the
    # scale_plan_applied event so the timeline records which path the
    # master asked for.
    recovery: str = ""

    def resizes_world_only(self) -> bool:
        """True when the plan concretely adds/removes nodes and changes
        nothing else — no PS topology change, no in-place migration.
        Exactly the shape a surviving SPMD worker can absorb by
        resharding. Deliberately NOT satisfied by a group-resource-only
        plan: without the previous counts a plan object cannot tell a
        count bump from a cpu/memory re-spec, and a re-spec needs a pod
        relaunch — stamping it live would be wrong, so those plans
        leave the workers' own classification in charge."""
        return bool(self.launch_nodes or self.remove_nodes) and not (
            self.ps_addrs or self.migrate_nodes
        )

    def empty(self) -> bool:
        return not (
            self.node_group_resources
            or self.launch_nodes
            or self.remove_nodes
            or self.migrate_nodes
            or self.ps_addrs
        )

    def merge(self, other: "ScalePlan"):
        self.node_group_resources.update(other.node_group_resources)
        self.launch_nodes.extend(other.launch_nodes)
        self.remove_nodes.extend(other.remove_nodes)
        self.migrate_nodes.update(other.migrate_nodes)
        if other.ps_addrs:
            self.ps_addrs = other.ps_addrs
        if other.recovery:
            self.recovery = other.recovery

    def to_dict(self) -> Dict:
        return {
            "groups": {
                t: {"count": g.count, "cpu": g.node_resource.cpu,
                    "memory": g.node_resource.memory}
                for t, g in self.node_group_resources.items()
            },
            "launch": [n.name for n in self.launch_nodes],
            "remove": [n.name for n in self.remove_nodes],
            "ps_addrs": list(self.ps_addrs),
            "recovery": self.recovery,
        }


class Scaler(ABC):
    """Executes ScalePlans against a platform (reference: Scaler)."""

    def __init__(self, job_name: str, run_id: str = ""):
        import os
        import time
        import uuid

        from dlrover_tpu.common.constants import NodeEnv

        self.job_name = job_name
        # Run identity: the checkpoint staging provenance fence
        # (NodeEnv.RUN_ID) handed to every node this scaler launches.
        # Resolution order keeps it stable per JOB INSTANCE, not per
        # master process:
        #   1. explicit arg — a durable platform identity (k8s job UID);
        #   2. the master's own env — on k8s the operator stamps the
        #      master pod with the job-UID token, so a RESTARTED master
        #      re-issues the same fence and staged mirrors stay valid;
        #   3. generated name+epoch+nonce — local/dev fallback: a master
        #      restart rotates the fence (staging falls back to primary
        #      storage), the price of fencing same-named fresh reruns.
        self.run_id = (
            run_id
            or os.environ.get(NodeEnv.RUN_ID, "")
            or f"{job_name}-{int(time.time())}-{uuid.uuid4().hex[:6]}"
        )

    @abstractmethod
    def scale(self, plan: ScalePlan) -> None:
        ...

    def start(self):
        """Hook for backends that run worker threads."""

    def stop(self):
        ...
