"""ScalePlan and the Scaler interface.

Role parity: ``dlrover/python/master/scaler/base_scaler.py`` — a ScalePlan
is the single currency between the resource optimizer / job manager (who
decide) and the platform backend (who acts): group resource targets, plus
concrete nodes to launch/remove/migrate.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import Dict, List

from dlrover_tpu.common.node import Node, NodeGroupResource, NodeResource


@dataclass
class ScalePlan:
    # Target (count, per-node resource) per node type.
    node_group_resources: Dict[str, NodeGroupResource] = field(default_factory=dict)
    # Concrete nodes to create (relaunches carry their rank_index forward).
    launch_nodes: List[Node] = field(default_factory=list)
    # Concrete nodes to delete.
    remove_nodes: List[Node] = field(default_factory=list)
    # node name -> new resource: replace in place (hot-PS migration).
    migrate_nodes: Dict[str, "NodeResource"] = field(default_factory=dict)
    # PS addresses for the next PS cluster version (PS jobs only).
    ps_addrs: List[str] = field(default_factory=list)

    def empty(self) -> bool:
        return not (
            self.node_group_resources
            or self.launch_nodes
            or self.remove_nodes
            or self.migrate_nodes
            or self.ps_addrs
        )

    def merge(self, other: "ScalePlan"):
        self.node_group_resources.update(other.node_group_resources)
        self.launch_nodes.extend(other.launch_nodes)
        self.remove_nodes.extend(other.remove_nodes)
        self.migrate_nodes.update(other.migrate_nodes)
        if other.ps_addrs:
            self.ps_addrs = other.ps_addrs

    def to_dict(self) -> Dict:
        return {
            "groups": {
                t: {"count": g.count, "cpu": g.node_resource.cpu,
                    "memory": g.node_resource.memory}
                for t, g in self.node_group_resources.items()
            },
            "launch": [n.name for n in self.launch_nodes],
            "remove": [n.name for n in self.remove_nodes],
            "ps_addrs": list(self.ps_addrs),
        }


class Scaler(ABC):
    """Executes ScalePlans against a platform (reference: Scaler)."""

    def __init__(self, job_name: str):
        self.job_name = job_name

    @abstractmethod
    def scale(self, plan: ScalePlan) -> None:
        ...

    def start(self):
        """Hook for backends that run worker threads."""

    def stop(self):
        ...
