"""Scaler that emits ScalePlan custom resources for an external operator.

Role parity: ``dlrover/python/master/scaler/elasticjob_scaler.py`` — instead
of touching pods itself, the master records its decision as a ScalePlan CR
and lets the cluster operator reconcile it. This is the mode where pod
lifecycle belongs to the operator (GKE JobSet / ElasticJob controller).
"""

from __future__ import annotations

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.master.scaler.base_scaler import ScalePlan, Scaler
from dlrover_tpu.scheduler.kubernetes import SCALEPLAN_PLURAL, build_scale_plan_cr

logger = get_logger("scaler.elasticjob")


class ElasticJobScaler(Scaler):
    def __init__(self, job_name: str, client):
        super().__init__(job_name)
        self._client = client

    def scale(self, plan: ScalePlan) -> None:
        if plan.empty():
            return
        groups = {
            t: {
                "replicas": g.count,
                "resource": {
                    "cpu": str(g.node_resource.cpu),
                    "memory": f"{g.node_resource.memory}Mi",
                    "chips": g.node_resource.accelerator.chips,
                },
            }
            for t, g in plan.node_group_resources.items()
        }
        creates = [
            {"name": n.name, "type": n.type, "id": n.id, "rankIndex": n.rank_index}
            for n in plan.launch_nodes
        ]
        removes = [n.name for n in plan.remove_nodes]
        cr = build_scale_plan_cr(
            self.job_name, groups, creates, removes, plan.ps_addrs
        )
        self._client.create_custom_resource(SCALEPLAN_PLURAL, cr)
        logger.info("submitted ScalePlan CR: %s", cr["metadata"]["name"])
