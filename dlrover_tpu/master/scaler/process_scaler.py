"""Scaler that launches nodes as local subprocesses.

Role parity: the role ``PodScaler`` plays for k8s, realized on the local
platform: every launched ``Node`` becomes an agent subprocess wired to the
master address via the ``NodeEnv`` env contract. Used by ``--standalone``
mode and by integration tests (N simulated hosts on one machine).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.node import Node
from dlrover_tpu.master.scaler.base_scaler import ScalePlan, Scaler
from dlrover_tpu.scheduler.local import LocalProcessBackend

logger = get_logger("scaler.process")


def default_command_factory(node: Node) -> List[str]:
    import sys

    return [sys.executable, "-m", "dlrover_tpu.agent.training_agent"]


class LocalProcessScaler(Scaler):
    def __init__(
        self,
        job_name: str,
        backend: LocalProcessBackend,
        master_addr: str,
        command_factory: Optional[Callable[[Node], List[str]]] = None,
        extra_env: Optional[Dict[str, str]] = None,
    ):
        super().__init__(job_name)
        self._backend = backend
        self._master_addr = master_addr
        self._command_factory = command_factory or default_command_factory
        self._extra_env = extra_env or {}
        # Sticky world size: relaunch plans carry no group resources, and a
        # relaunched agent must still see the full job's NODE_NUM.
        self._node_num = 0

    def _node_env(self, node: Node, node_num: int) -> Dict[str, str]:
        env = {
            NodeEnv.MASTER_ADDR: self._master_addr,
            NodeEnv.JOB_NAME: self.job_name,
            NodeEnv.RUN_ID: self.run_id,
            NodeEnv.NODE_ID: str(node.id),
            NodeEnv.NODE_RANK: str(node.rank_index),
            NodeEnv.NODE_NUM: str(node_num),
            NodeEnv.NODE_TYPE: node.type,
        }
        env.update(self._extra_env)
        return env

    def scale(self, plan: ScalePlan) -> None:
        for node in plan.remove_nodes:
            if self._backend.kill_process(node.name):
                logger.info("removed node %s", node.name)
        group_max = max(
            (g.count for g in plan.node_group_resources.values()), default=0
        )
        self._node_num = max(self._node_num, group_max, len(plan.launch_nodes))
        node_num = self._node_num
        for node in plan.launch_nodes:
            self._backend.start_process(
                name=node.name,
                node_type=node.type,
                node_id=node.id,
                rank_index=node.rank_index,
                command=self._command_factory(node),
                env=self._node_env(node, node_num),
            )

    def stop(self):
        self._backend.stop_all()
