"""Ray actor scaler.

Role parity: ``dlrover/python/master/scaler/ray_scaler.py:39``
(``ActorScaler`` — diffs the ScalePlan's group targets against the alive
actors and creates/kills the difference).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.node import Node
from dlrover_tpu.master.scaler.base_scaler import ScalePlan, Scaler
from dlrover_tpu.scheduler.ray import (
    ActorArgs,
    parse_type_id_from_actor_name,
)

logger = get_logger("scaler.actor")

DEFAULT_EXECUTOR = "dlrover_tpu.scheduler.ray:RayWorker"


class ActorScaler(Scaler):
    def __init__(
        self,
        job_name: str,
        ray_client,  # scheduler.ray.RayClient or a fake
        executor: str = DEFAULT_EXECUTOR,
        master_addr: str = "",
        env_factory: Optional[Callable[[Node], Dict[str, str]]] = None,
    ):
        super().__init__(job_name)
        self._client = ray_client
        self._executor = executor
        self._master_addr = master_addr
        self._env_factory = env_factory

    def _alive_by_type(self) -> Dict[str, List[str]]:
        alive: Dict[str, List[str]] = {}
        for name, state in self._client.list_actors().items():
            if state in ("DEAD",):
                continue
            node_type, _ = parse_type_id_from_actor_name(name)
            alive.setdefault(node_type, []).append(name)
        return alive

    def _actor_args(self, node: Node) -> ActorArgs:
        from dlrover_tpu.common.constants import NodeEnv

        env = {
            "DLROVER_MASTER_ADDR": self._master_addr,
            "NODE_TYPE": node.type,
            "NODE_ID": str(node.id),
            "NODE_RANK": str(node.rank_index),
            # checkpoint staging provenance fence (same contract as the
            # pod/process scalers): a same-named fresh Ray job must not
            # adopt a previous run's staged weights
            NodeEnv.JOB_NAME: self.job_name,
            NodeEnv.RUN_ID: self.run_id,
        }
        if self._env_factory is not None:
            env.update(self._env_factory(node))
        return ActorArgs(
            actor_name=node.name,
            executor=self._executor,
            num_cpus=node.config_resource.cpu or 1.0,
            memory_mb=node.config_resource.memory or 1024,
            resources=(
                {"TPU": float(node.config_resource.accelerator.chips)}
                if node.config_resource.accelerator.chips else {}
            ),
            env=env,
        )

    def scale(self, plan: ScalePlan) -> None:
        alive = self._alive_by_type()
        # concrete launches/removals first (relaunch path); the alive map
        # tracks them so the group loop below doesn't double-create the
        # same names (the initial plan carries both fields)
        for node in plan.launch_nodes:
            logger.info("create actor %s", node.name)
            self._client.create_actor(self._actor_args(node))
            alive.setdefault(node.type, []).append(node.name)
        for node in plan.remove_nodes:
            logger.info("kill actor %s", node.name)
            self._client.delete_actor(node.name)
            names = alive.get(node.type, [])
            if node.name in names:
                names.remove(node.name)

        # then group targets: grow with fresh ids, shrink from the top
        for node_type, group in plan.node_group_resources.items():
            if group.count <= 0:
                continue
            names = sorted(
                alive.get(node_type, []),
                key=lambda n: parse_type_id_from_actor_name(n)[1],
            )
            cur = len(names)
            used_ids = {
                parse_type_id_from_actor_name(n)[1] for n in names
            }
            next_id = max(used_ids) + 1 if used_ids else 0
            for _ in range(cur, group.count):
                node = Node(
                    node_type=node_type, node_id=next_id,
                    config_resource=group.node_resource,
                )
                logger.info("scale-up actor %s", node.name)
                self._client.create_actor(self._actor_args(node))
                next_id += 1
            for name in names[group.count:]:
                logger.info("scale-down actor %s", name)
                self._client.delete_actor(name)
