"""Scaler that creates/deletes pods directly through the k8s API.

Role parity: ``dlrover/python/master/scaler/pod_scaler.py`` — a creation
queue drained by a worker thread (pod creation is slow and can fail
transiently; the control loop must never block on it), env injection for
the master address + rank contract, and replica bookkeeping.

TPU-first: each worker pod requests a whole TPU host's chips and pins to
the slice topology via GKE node selectors (``scheduler/kubernetes.py``).
"""

from __future__ import annotations

import queue
import threading
from typing import Dict, List, Optional

from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.node import Node
from dlrover_tpu.master.scaler.base_scaler import ScalePlan, Scaler
from dlrover_tpu.scheduler.kubernetes import build_pod_spec

logger = get_logger("scaler.pod")


class PodScaler(Scaler):
    def __init__(
        self,
        job_name: str,
        client,  # K8sClient-compatible (create_pod/delete_pod/list_pods)
        master_addr: str,
        image: str = "dlrover-tpu:latest",
        command: Optional[List[str]] = None,
        tpu_topology: str = "",
        tpu_accelerator: str = "",
    ):
        super().__init__(job_name)
        self._client = client
        self._master_addr = master_addr
        self._image = image
        self._command = command or ["python", "-m", "dlrover_tpu.agent.training_agent"]
        self._tpu_topology = tpu_topology
        self._tpu_accelerator = tpu_accelerator
        self._create_queue: "queue.Queue[Node]" = queue.Queue()
        self._create_attempts: Dict[int, int] = {}
        self._node_num = 0
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        self._thread = threading.Thread(
            target=self._drain_create_queue, name="pod-creator", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stopped.set()

    def pod_name(self, node: Node) -> str:
        return f"{self.job_name}-{node.type}-{node.id}"

    def scale(self, plan: ScalePlan) -> None:
        for t, group in plan.node_group_resources.items():
            if t and group.count > self._node_num:
                self._node_num = group.count
        for node in plan.remove_nodes:
            self._client.delete_pod(self.pod_name(node))
        for node in plan.launch_nodes:
            self._create_queue.put(node)

    def _drain_create_queue(self):
        while not self._stopped.is_set():
            try:
                node = self._create_queue.get(timeout=0.5)
            except queue.Empty:
                continue
            self._create_pod(node)

    def _node_env(self, node: Node) -> Dict[str, str]:
        return {
            NodeEnv.MASTER_ADDR: self._master_addr,
            NodeEnv.JOB_NAME: self.job_name,
            NodeEnv.RUN_ID: self.run_id,
            NodeEnv.NODE_ID: str(node.id),
            NodeEnv.NODE_RANK: str(node.rank_index),
            NodeEnv.NODE_NUM: str(max(self._node_num, 1)),
            NodeEnv.NODE_TYPE: node.type,
        }

    def _create_pod(self, node: Node):
        res = node.config_resource
        pod = build_pod_spec(
            job_name=self.job_name,
            pod_name=self.pod_name(node),
            node_type=node.type,
            node_id=node.id,
            rank_index=node.rank_index,
            image=self._image,
            command=self._command,
            cpu=res.cpu,
            memory_mb=res.memory,
            tpu_chips=res.accelerator.chips,
            tpu_topology=self._tpu_topology or res.accelerator.topology,
            tpu_accelerator=self._tpu_accelerator,
            env=self._node_env(node),
        )
        if self._client.create_pod(pod) is None:
            attempts = self._create_attempts.get(node.id, 0) + 1
            self._create_attempts[node.id] = attempts
            if attempts >= 3:
                # Spec is likely invalid (bad topology selector, quota):
                # retrying forever only hammers the API. Surface as FAILED
                # through the node object; the watcher never will.
                logger.error(
                    "pod creation for %s failed %d times; giving up",
                    node.name, attempts,
                )
                from dlrover_tpu.common.constants import (
                    NodeExitReason,
                    NodeStatus,
                )

                node.exit_reason = NodeExitReason.FATAL_ERROR
                node.update_status(NodeStatus.FAILED)
                return
            logger.error("pod creation failed for %s; requeueing", node.name)
            # Back off without blocking the drain thread's other work: the
            # requeue itself is immediate, the retry is delayed by a timer
            # so stop() stays responsive and other pods keep creating.
            delay = min(2 ** attempts, 30)
            timer = threading.Timer(delay, self._create_queue.put, args=(node,))
            timer.daemon = True
            timer.start()
