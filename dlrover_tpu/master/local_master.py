"""In-process job master for standalone mode and tests.

Role parity: ``dlrover/python/master/local_master.py`` — the master without
any cluster scheduler: rendezvous, data sharding, speed monitoring and the
RPC server, driving training on the local host (or N simulated agents in
tests). The distributed master (``dist_master.py``) adds node lifecycle
management and auto-scaling on top.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from dlrover_tpu.common.constants import RendezvousName
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.master.elastic_training.elastic_ps import ElasticPsService
from dlrover_tpu.master.elastic_training.kv_store import KVStoreService
from dlrover_tpu.master.elastic_training.rdzv_manager import (
    ElasticTrainingRendezvousManager,
    NetworkCheckRendezvousManager,
    RendezvousManager,
)
from dlrover_tpu.master.elastic_training.sync_service import SyncService
from dlrover_tpu.master.monitor.speed_monitor import SpeedMonitor
from dlrover_tpu.master.servicer import MasterServicer
from dlrover_tpu.master.shard.task_manager import TaskManager
from dlrover_tpu.rpc.server import build_server

logger = get_logger("master.local")


class LocalJobMaster:
    def __init__(self, port: int = 0, job_name: str = "local"):
        from dlrover_tpu.master.stats.job_collector import (
            JobMetricCollector,
        )

        self.job_name = job_name
        self.speed_monitor = SpeedMonitor()
        self.task_manager = TaskManager(self.speed_monitor)
        self.rdzv_managers: Dict[str, RendezvousManager] = {
            RendezvousName.TRAINING: ElasticTrainingRendezvousManager(),
            RendezvousName.NETWORK_CHECK: NetworkCheckRendezvousManager(),
        }
        self.kv_store = KVStoreService()
        self.sync_service = SyncService()
        self.elastic_ps_service = ElasticPsService()
        # model/dataset facts + the periodic runtime series land in the
        # stats reporter — the store the local optimizer and the Brain
        # watcher read, so they consume REAL series in standalone mode
        self.metric_collector = JobMetricCollector(job_name)
        self.servicer = MasterServicer(
            task_manager=self.task_manager,
            rdzv_managers=self.rdzv_managers,
            speed_monitor=self.speed_monitor,
            kv_store=self.kv_store,
            sync_service=self.sync_service,
            elastic_ps_service=self.elastic_ps_service,
            metric_collector=self.metric_collector,
        )
        self._server, self.port = build_server(self.servicer, port=port)
        self.addr = f"127.0.0.1:{self.port}"
        self._stopped = threading.Event()
        self._stats_thread: threading.Thread = threading.Thread(
            target=self._collect_runtime_stats,
            name="runtime-stats", daemon=True,
        )
        self._exporter = None

    def prepare(self):
        from dlrover_tpu.telemetry.exporter import maybe_start_exporter

        self._server.start()
        self.task_manager.start()
        self._stats_thread.start()
        # Prometheus exposition (off unless telemetry_metrics_port /
        # DLROVER_TPU_METRICS_PORT is set)
        self._exporter = maybe_start_exporter()
        logger.info("local master serving at %s", self.addr)

    def _collect_runtime_stats(self):
        """Periodic RuntimeMetric samples (global step + speed) into the
        stats reporter — the standalone counterpart of the dist
        master's node-resource collection loop."""
        from dlrover_tpu.common.config import get_context

        interval = max(
            1.0, float(get_context().seconds_interval_to_report))
        while not self._stopped.wait(interval):
            try:
                self.metric_collector.collect_runtime_stats(
                    self.speed_monitor, {}
                )
                # a hung node stops reporting, so the hang judgement
                # must run on a clock, not only on report ingest
                self.servicer.straggler_detector.scan_hangs()
                # a stranded serve lease likewise only expires on a
                # clock — a dead worker sends nothing
                self.servicer.request_router.scan_expired_once()
                # the serving SLO plane: one rolling-window tick per
                # pass (the engine self-paces to serve_slo_window_secs)
                # plus the scale policy's idle watch
                self.servicer.serve_slo.evaluate()
                self.servicer.serving_scale_policy.tick()
                # the durability audit (self-paced to
                # readiness_sweep_secs): directory assignments vs live
                # store inventories -> coverage/staleness/budget
                # verdicts + priced blast-radius gauges
                self.servicer.readiness_auditor.sweep()
            except Exception:  # noqa: BLE001 — stats must not kill serving
                logger.exception("runtime stats collection failed")

    def run(self, poll_secs: float = 1.0) -> int:
        """Block until the job exits; returns an exit code."""
        try:
            while not self._stopped.is_set():
                if self.servicer.job_exit_requested:
                    ok = self.servicer.job_success
                    logger.info("job exit requested (success=%s)", ok)
                    return 0 if ok else 1
                time.sleep(poll_secs)
            return 0
        finally:
            self.stop()

    def stop(self):
        self._stopped.set()
        self.task_manager.stop()
        if self._exporter is not None:
            self._exporter.stop()
        self._server.stop(grace=1)


def start_local_master(port: int = 0) -> LocalJobMaster:
    """Boot a ready-to-serve local master (the tests' entry point)."""
    master = LocalJobMaster(port=port)
    master.prepare()
    return master
