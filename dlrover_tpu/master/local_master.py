"""In-process job master for standalone mode and tests.

Role parity: ``dlrover/python/master/local_master.py`` — the master without
any cluster scheduler: rendezvous, data sharding, speed monitoring and the
RPC server, driving training on the local host (or N simulated agents in
tests). The distributed master (``dist_master.py``) adds node lifecycle
management and auto-scaling on top.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from dlrover_tpu.common.constants import RendezvousName
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.master.elastic_training.elastic_ps import ElasticPsService
from dlrover_tpu.master.elastic_training.kv_store import KVStoreService
from dlrover_tpu.master.elastic_training.rdzv_manager import (
    ElasticTrainingRendezvousManager,
    NetworkCheckRendezvousManager,
    RendezvousManager,
)
from dlrover_tpu.master.elastic_training.sync_service import SyncService
from dlrover_tpu.master.monitor.speed_monitor import SpeedMonitor
from dlrover_tpu.master.servicer import MasterServicer
from dlrover_tpu.master.shard.task_manager import TaskManager
from dlrover_tpu.rpc.server import build_server

logger = get_logger("master.local")


class LocalJobMaster:
    def __init__(self, port: int = 0, job_name: str = "local"):
        self.job_name = job_name
        self.speed_monitor = SpeedMonitor()
        self.task_manager = TaskManager(self.speed_monitor)
        self.rdzv_managers: Dict[str, RendezvousManager] = {
            RendezvousName.TRAINING: ElasticTrainingRendezvousManager(),
            RendezvousName.NETWORK_CHECK: NetworkCheckRendezvousManager(),
        }
        self.kv_store = KVStoreService()
        self.sync_service = SyncService()
        self.elastic_ps_service = ElasticPsService()
        self.servicer = MasterServicer(
            task_manager=self.task_manager,
            rdzv_managers=self.rdzv_managers,
            speed_monitor=self.speed_monitor,
            kv_store=self.kv_store,
            sync_service=self.sync_service,
            elastic_ps_service=self.elastic_ps_service,
        )
        self._server, self.port = build_server(self.servicer, port=port)
        self.addr = f"127.0.0.1:{self.port}"
        self._stopped = threading.Event()

    def prepare(self):
        self._server.start()
        self.task_manager.start()
        logger.info("local master serving at %s", self.addr)

    def run(self, poll_secs: float = 1.0) -> int:
        """Block until the job exits; returns an exit code."""
        try:
            while not self._stopped.is_set():
                if self.servicer.job_exit_requested:
                    ok = self.servicer.job_success
                    logger.info("job exit requested (success=%s)", ok)
                    return 0 if ok else 1
                time.sleep(poll_secs)
            return 0
        finally:
            self.stop()

    def stop(self):
        self._stopped.set()
        self.task_manager.stop()
        self._server.stop(grace=1)


def start_local_master(port: int = 0) -> LocalJobMaster:
    """Boot a ready-to-serve local master (the tests' entry point)."""
    master = LocalJobMaster(port=port)
    master.prepare()
    return master
