"""Dataset index-space splitters.

Role parity: ``dlrover/python/master/shard/dataset_splitter.py:90-481``
(TableDatasetSplitter, TextDatasetSplitter, StreamingDatasetSplitter). A
shard is a [start, end) range of ``batch_size * num_minibatches_per_shard``
records; splitters hand the task manager one epoch of shards at a time.

On TPU the consumer is a per-host input pipeline (grain/tf.data style
index sampling): each host maps its shard range to host-local batches that
feed ``jax.device_put`` onto its chips, so the master stays off the
per-batch path exactly as in the reference.
"""

from __future__ import annotations

import random
from abc import ABC, abstractmethod
from dataclasses import dataclass, field
from typing import List, Optional

from dlrover_tpu.common.log import get_logger

logger = get_logger("master.shard")


@dataclass
class Shard:
    name: str
    start: int
    end: int
    record_indices: Optional[List[int]] = None

    @property
    def size(self) -> int:
        return self.end - self.start


class DatasetSplitter(ABC):
    def __init__(self, dataset_name: str, dataset_size: int, shard_size: int,
                 num_epochs: int):
        self.dataset_name = dataset_name
        self.dataset_size = dataset_size
        self.shard_size = max(1, shard_size)
        self.num_epochs = max(1, num_epochs)
        self.epoch = 0

    @abstractmethod
    def create_shards(self) -> List[Shard]:
        """Produce the next epoch's shards (advances the epoch counter)."""

    def epoch_finished(self) -> bool:
        return self.epoch >= self.num_epochs

    @staticmethod
    def create(
        dataset_name: str,
        dataset_size: int,
        batch_size: int,
        num_epochs: int,
        shuffle: bool = False,
        num_minibatches_per_shard: int = 2,
        storage_type: str = "table",
    ) -> "DatasetSplitter":
        shard_size = batch_size * max(1, num_minibatches_per_shard)
        if storage_type == "text":
            return TextDatasetSplitter(
                dataset_name, dataset_size, shard_size, num_epochs, shuffle
            )
        if storage_type == "stream":
            return StreamingDatasetSplitter(
                dataset_name, dataset_size, shard_size, num_epochs
            )
        return TableDatasetSplitter(
            dataset_name, dataset_size, shard_size, num_epochs, shuffle
        )


class TableDatasetSplitter(DatasetSplitter):
    """Range shards over a record-addressable table."""

    def __init__(self, dataset_name, dataset_size, shard_size, num_epochs,
                 shuffle: bool = False):
        super().__init__(dataset_name, dataset_size, shard_size, num_epochs)
        self.shuffle = shuffle

    def create_shards(self) -> List[Shard]:
        if self.epoch_finished():
            return []
        shards = [
            Shard(self.dataset_name, start, min(start + self.shard_size,
                                                self.dataset_size))
            for start in range(0, self.dataset_size, self.shard_size)
        ]
        if self.shuffle:
            random.shuffle(shards)
        self.epoch += 1
        logger.info(
            "dataset %s: epoch %d/%d, %d shards of %d records",
            self.dataset_name, self.epoch, self.num_epochs, len(shards),
            self.shard_size,
        )
        return shards


class TextDatasetSplitter(DatasetSplitter):
    """Shards carrying explicit (optionally shuffled) record indices,
    for line-addressable text files."""

    def __init__(self, dataset_name, dataset_size, shard_size, num_epochs,
                 shuffle: bool = False):
        super().__init__(dataset_name, dataset_size, shard_size, num_epochs)
        self.shuffle = shuffle

    def create_shards(self) -> List[Shard]:
        if self.epoch_finished():
            return []
        indices = list(range(self.dataset_size))
        if self.shuffle:
            random.shuffle(indices)
        shards = []
        for start in range(0, self.dataset_size, self.shard_size):
            end = min(start + self.shard_size, self.dataset_size)
            shards.append(
                Shard(self.dataset_name, start, end,
                      record_indices=indices[start:end])
            )
        self.epoch += 1
        return shards


class StreamingDatasetSplitter(DatasetSplitter):
    """Unbounded stream: grow the index space as data arrives.

    ``dataset_size`` is the currently-known frontier; ``add_records`` extends
    it (the reference's PartitionOffsets-based variant,
    ``dataset_splitter.py:359``). Epochs do not apply — the splitter is
    exhausted only when marked finished.
    """

    def __init__(self, dataset_name, dataset_size, shard_size, num_epochs=1):
        super().__init__(dataset_name, dataset_size, shard_size, 1)
        self._frontier = 0
        self._finished = False

    def add_records(self, count: int):
        self.dataset_size += count

    def mark_finished(self):
        self._finished = True

    def epoch_finished(self) -> bool:
        return self._finished and self._frontier >= self.dataset_size

    def create_shards(self) -> List[Shard]:
        shards = []
        while self._frontier < self.dataset_size:
            end = min(self._frontier + self.shard_size, self.dataset_size)
            shards.append(Shard(self.dataset_name, self._frontier, end))
            self._frontier = end
        return shards
