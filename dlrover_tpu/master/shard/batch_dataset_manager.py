"""Todo/doing shard queues for one dataset.

Role parity: ``dlrover/python/master/shard/batch_dataset_manager.py:29-203``:
pop a shard to a worker (todo -> doing), complete it by reported record
counts, recover shards of dead/slow workers back to todo, and
checkpoint/restore the whole queue state so a restarted job resumes
mid-epoch without re-reading consumed data.
"""

from __future__ import annotations

import json
import time
from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, List, Optional, Tuple

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.master.shard.dataset_splitter import DatasetSplitter, Shard

logger = get_logger("master.shard")


@dataclass
class DoingTask:
    task: "Task"
    node_id: int
    start_time: float


@dataclass
class Task:
    task_id: int
    task_type: str
    shard: Shard
    epoch: int = 0

    @classmethod
    def create_invalid(cls) -> "Task":
        return cls(-1, "", Shard("", 0, 0))


class BatchDatasetManager:
    def __init__(self, splitter: DatasetSplitter, task_type: str = "training"):
        self._splitter = splitter
        self._task_type = task_type
        self.todo: Deque[Task] = deque()
        self.doing: Dict[int, DoingTask] = {}
        self._task_id_seq = 0
        self._completed_step = 0
        self._reported_records: Dict[int, int] = {}
        self._epoch_checkpoint_restored = False

    @property
    def dataset_name(self) -> str:
        return self._splitter.dataset_name

    def get_task(self, node_id: int) -> Task:
        """Pop a task for a worker, refilling from the splitter per epoch."""
        if not self.todo and not self._splitter.epoch_finished():
            self._create_epoch_tasks()
        if not self.todo:
            return Task.create_invalid()
        task = self.todo.popleft()
        self.doing[task.task_id] = DoingTask(task, node_id, time.time())
        return task

    def _create_epoch_tasks(self):
        shards = self._splitter.create_shards()
        for shard in shards:
            self.todo.append(
                Task(self._task_id_seq, self._task_type, shard,
                     epoch=self._splitter.epoch)
            )
            self._task_id_seq += 1

    def report_task_status(self, task_id: int, success: bool) -> Tuple[bool, Task]:
        """Worker finished (or failed) a task; failure requeues the shard."""
        doing = self.doing.pop(task_id, None)
        if doing is None:
            return False, Task.create_invalid()
        if not success:
            logger.info(
                "dataset %s: task %d failed, requeueing shard [%d, %d)",
                self.dataset_name, task_id, doing.task.shard.start,
                doing.task.shard.end,
            )
            self.todo.appendleft(doing.task)
        return success, doing.task

    def report_batch_done(self, node_id: int, record_count: int,
                          task_ids: Optional[List[int]] = None) -> List[int]:
        """Credit consumed records against this worker's doing tasks;
        returns the task ids completed by this report."""
        completed = []
        candidates = task_ids or [
            tid for tid, d in self.doing.items() if d.node_id == node_id
        ]
        remaining = record_count
        for tid in sorted(candidates):
            doing = self.doing.get(tid)
            if doing is None:
                continue
            credited = self._reported_records.get(tid, 0) + remaining
            if credited >= doing.task.shard.size:
                remaining = credited - doing.task.shard.size
                self._reported_records.pop(tid, None)
                self.doing.pop(tid)
                completed.append(tid)
            else:
                self._reported_records[tid] = credited
                remaining = 0
            if remaining <= 0:
                break
        return completed

    def recover_tasks(self, node_id: int):
        """Requeue every doing task of a dead worker."""
        requeued = []
        for tid, doing in list(self.doing.items()):
            if doing.node_id == node_id:
                self.doing.pop(tid)
                self._reported_records.pop(tid, None)
                self.todo.appendleft(doing.task)
                requeued.append(tid)
        if requeued:
            logger.info(
                "dataset %s: recovered tasks %s of node %d",
                self.dataset_name, requeued, node_id,
            )

    def recover_timeout_tasks(self, timeout_secs: float) -> List[int]:
        now = time.time()
        recovered = []
        for tid, doing in list(self.doing.items()):
            if now - doing.start_time > timeout_secs:
                self.doing.pop(tid)
                self.todo.appendleft(doing.task)
                recovered.append(tid)
        return recovered

    def completed(self) -> bool:
        return (
            self._splitter.epoch_finished()
            and not self.todo
            and not self.doing
        )

    # -- checkpoint ---------------------------------------------------------

    def checkpoint(self) -> str:
        """Serialize undone work: doing shards go back in front of todo."""
        shards = [
            [d.task.shard.start, d.task.shard.end]
            for d in self.doing.values()
        ] + [[t.shard.start, t.shard.end] for t in self.todo]
        return json.dumps({
            "dataset_name": self.dataset_name,
            "todo": shards,
            "epoch": self._splitter.epoch,
        })

    def restore_checkpoint(self, content: str):
        state = json.loads(content)
        if state.get("dataset_name") != self.dataset_name:
            raise ValueError(
                f"checkpoint is for {state.get('dataset_name')}, "
                f"not {self.dataset_name}"
            )
        self._splitter.epoch = state.get("epoch", 0)
        self.todo.clear()
        self.doing.clear()
        for start, end in state.get("todo", []):
            self.todo.append(
                Task(
                    self._task_id_seq,
                    self._task_type,
                    Shard(self.dataset_name, start, end),
                    epoch=self._splitter.epoch,
                )
            )
            self._task_id_seq += 1
        logger.info(
            "dataset %s: restored %d pending shards at epoch %d",
            self.dataset_name, len(self.todo), self._splitter.epoch,
        )
