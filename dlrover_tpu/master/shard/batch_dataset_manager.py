"""Todo/doing shard queues for one dataset.

Role parity: ``dlrover/python/master/shard/batch_dataset_manager.py:29-203``:
pop a shard to a worker (todo -> doing), complete it by reported record
counts, recover shards of dead/slow workers back to todo, and
checkpoint/restore the whole queue state so a restarted job resumes
mid-epoch without re-reading consumed data.
"""

from __future__ import annotations

import json
import time
from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, List, Optional, Tuple

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.master.shard.dataset_splitter import DatasetSplitter, Shard
from dlrover_tpu.telemetry import (
    EventKind,
    emit_event,
    get_registry,
    names as tm,
)

logger = get_logger("master.shard")

# the {dataset=}-labeled lifecycle gauges this manager owns (created at
# the first dispatched shard, retracted when the dataset completes —
# the absent-not-zero rule: a scrape must never read todo=0 for a
# dataset that never dispatched, or a frozen queue for one that ended)
_LIFECYCLE_GAUGES = (
    tm.DATA_SHARDS_TODO,
    tm.DATA_SHARDS_DOING,
    tm.DATA_SHARDS_DONE,
    tm.DATA_EPOCH,
    tm.DATA_EPOCH_PROGRESS,
)


@dataclass
class DoingTask:
    task: "Task"
    node_id: int
    start_time: float


@dataclass
class Task:
    task_id: int
    task_type: str
    shard: Shard
    epoch: int = 0

    @classmethod
    def create_invalid(cls) -> "Task":
        return cls(-1, "", Shard("", 0, 0))


class BatchDatasetManager:
    def __init__(self, splitter: DatasetSplitter, task_type: str = "training"):
        self._splitter = splitter
        self._task_type = task_type
        self.todo: Deque[Task] = deque()
        self.doing: Dict[int, DoingTask] = {}
        self._task_id_seq = 0
        self._completed_step = 0
        self._reported_records: Dict[int, int] = {}
        self._epoch_checkpoint_restored = False
        # -- shard-lifecycle accounting (the tpurun data ledger) ------
        self._shards_done = 0
        self._records_done = 0
        # PER-EPOCH records done + tasks outstanding (created, not yet
        # completed), keyed by the task's own epoch: epochs OVERLAP by
        # design — get_task refills lazily while the previous epoch's
        # last shards are still doing on other workers — so a single
        # "current epoch" counter would credit a late epoch-N
        # completion to epoch N+1 and never see epoch N drain
        self._epoch_records: Dict[int, int] = {}
        self._epoch_outstanding: Dict[int, int] = {}
        self._timeout_recovered = 0
        # per-node consumption: shards/records completed + first/last
        # completion stamps (the rate denominators)
        self._node_shards: Dict[int, int] = {}
        self._node_records: Dict[int, int] = {}
        self._node_first_ts: Dict[int, float] = {}
        self._node_last_ts: Dict[int, float] = {}
        self._dispatch_started = False
        self._gauges_live = False
        # cached handles (created lazily at first dispatch so the
        # absent-not-zero rule holds; the registry is resolved once)
        self._reg = get_registry()
        self._h_latency = self._reg.histogram(
            tm.DATA_SHARD_LATENCY,
            help="shard dispatch -> completion wall seconds")
        self._gauges: Dict[str, object] = {}
        self._node_counters: Dict[int, tuple] = {}

    @property
    def dataset_name(self) -> str:
        return self._splitter.dataset_name

    def get_task(self, node_id: int) -> Task:
        """Pop a task for a worker, refilling from the splitter per epoch."""
        if not self.todo and not self._splitter.epoch_finished():
            self._create_epoch_tasks()
        if not self.todo:
            return Task.create_invalid()
        task = self.todo.popleft()
        self.doing[task.task_id] = DoingTask(task, node_id, time.time())
        self._dispatch_started = True
        self._refresh_gauges()
        return task

    def _create_epoch_tasks(self):
        shards = self._splitter.create_shards()
        for shard in shards:
            self.todo.append(
                Task(self._task_id_seq, self._task_type, shard,
                     epoch=self._splitter.epoch)
            )
            self._task_id_seq += 1
        if shards:
            epoch = self._splitter.epoch
            self._epoch_outstanding[epoch] = (
                self._epoch_outstanding.get(epoch, 0) + len(shards))

    def report_task_status(self, task_id: int, success: bool) -> Tuple[bool, Task]:
        """Worker finished (or failed) a task; failure requeues the shard."""
        doing = self.doing.pop(task_id, None)
        if doing is None:
            return False, Task.create_invalid()
        if not success:
            logger.info(
                "dataset %s: task %d failed, requeueing shard [%d, %d)",
                self.dataset_name, task_id, doing.task.shard.start,
                doing.task.shard.end,
            )
            self.todo.appendleft(doing.task)
        else:
            self._account_completion(doing)
        self._refresh_gauges()
        return success, doing.task

    def report_batch_done(self, node_id: int, record_count: int,
                          task_ids: Optional[List[int]] = None) -> List[int]:
        """Credit consumed records against this worker's doing tasks;
        returns the task ids completed by this report."""
        completed = []
        candidates = task_ids or [
            tid for tid, d in self.doing.items() if d.node_id == node_id
        ]
        remaining = record_count
        for tid in sorted(candidates):
            doing = self.doing.get(tid)
            if doing is None:
                continue
            credited = self._reported_records.get(tid, 0) + remaining
            if credited >= doing.task.shard.size:
                remaining = credited - doing.task.shard.size
                self._reported_records.pop(tid, None)
                self.doing.pop(tid)
                completed.append(tid)
                self._account_completion(doing)
            else:
                self._reported_records[tid] = credited
                remaining = 0
            if remaining <= 0:
                break
        if completed:
            self._refresh_gauges()
        return completed

    # -- shard-lifecycle accounting (lock held by the TaskManager) -----------

    def _account_completion(self, doing: DoingTask):
        """One shard left doing as COMPLETED (either path: an explicit
        task result, or record credits covering the shard). Counted at
        the pop site so the two completion paths can never double
        count. Credited to the TASK's epoch — a late epoch-N
        completion arriving after epoch N+1 started dispatching must
        close epoch N, not inflate N+1."""
        now = time.time()
        size = doing.task.shard.size
        epoch = doing.task.epoch
        self._shards_done += 1
        self._records_done += size
        self._epoch_records[epoch] = (
            self._epoch_records.get(epoch, 0) + size)
        outstanding = self._epoch_outstanding.get(epoch, 1) - 1
        self._epoch_outstanding[epoch] = outstanding
        nid = int(doing.node_id)
        self._node_shards[nid] = self._node_shards.get(nid, 0) + 1
        self._node_records[nid] = self._node_records.get(nid, 0) + size
        self._node_first_ts.setdefault(nid, doing.start_time)
        self._node_last_ts[nid] = now
        self._h_latency.observe(now - doing.start_time)
        counters = self._node_counters.get(nid)
        if counters is None:
            labels = {"node": str(nid)}
            counters = (
                self._reg.counter(
                    tm.DATA_NODE_SHARDS_COMPLETED, labels=labels,
                    help="shards completed per consuming node"),
                self._reg.counter(
                    tm.DATA_NODE_RECORDS_DONE, labels=labels,
                    help="records completed per consuming node"),
            )
            self._node_counters[nid] = counters
        counters[0].inc()
        counters[1].inc(size)
        if outstanding <= 0:
            # the epoch's every created task completed — the forensic
            # anchor `tpurun data --events` reconstructs from (fires
            # even when the NEXT epoch is already dispatching)
            self._epoch_outstanding.pop(epoch, None)
            emit_event(
                EventKind.DATA_EPOCH_END,
                dataset=self.dataset_name,
                epoch=epoch,
                shards_done=self._shards_done,
                records_done=self._records_done,
                timeout_recovered=self._timeout_recovered,
                final=self.completed(),
            )

    def epoch_progress(self) -> float:
        """Fraction of the NEWEST dispatch epoch's records completed."""
        total = max(1, int(self._splitter.dataset_size))
        done = self._epoch_records.get(self._splitter.epoch, 0)
        return min(1.0, done / total)

    def _refresh_gauges(self):
        """Mirror the queue state into {dataset=}-labeled gauges.
        Created only once a shard was dispatched; RETRACTED when the
        dataset completes (absent-not-zero — see _LIFECYCLE_GAUGES)."""
        if not self._dispatch_started:
            return
        if self.completed():
            self.retract_gauges()
            return
        labels = {"dataset": self.dataset_name}
        self._gauges_live = True
        g = self._gauges
        if not g:
            g[tm.DATA_SHARDS_TODO] = self._reg.gauge(
                tm.DATA_SHARDS_TODO, labels=labels,
                help="shards waiting for dispatch")
            g[tm.DATA_SHARDS_DOING] = self._reg.gauge(
                tm.DATA_SHARDS_DOING, labels=labels,
                help="shards dispatched and in flight")
            g[tm.DATA_SHARDS_DONE] = self._reg.gauge(
                tm.DATA_SHARDS_DONE, labels=labels,
                help="shards completed so far")
            g[tm.DATA_EPOCH] = self._reg.gauge(
                tm.DATA_EPOCH, labels=labels,
                help="current dispatch epoch")
            g[tm.DATA_EPOCH_PROGRESS] = self._reg.gauge(
                tm.DATA_EPOCH_PROGRESS, labels=labels,
                help="fraction of the newest epoch's records completed")
        g[tm.DATA_SHARDS_TODO].set(len(self.todo))
        g[tm.DATA_SHARDS_DOING].set(len(self.doing))
        g[tm.DATA_SHARDS_DONE].set(self._shards_done)
        g[tm.DATA_EPOCH].set(self._splitter.epoch)
        g[tm.DATA_EPOCH_PROGRESS].set(self.epoch_progress())

    def retract_gauges(self):
        """Drop this dataset's lifecycle gauges from the exposition
        (dataset reset/unregistration — a gone dataset must not keep
        exporting a frozen queue)."""
        if not self._gauges_live:
            return
        labels = {"dataset": self.dataset_name}
        for name in _LIFECYCLE_GAUGES:
            self._reg.remove(name, labels=labels)
        self._gauges.clear()
        self._gauges_live = False

    def node_stats(self) -> Dict[int, Dict]:
        """Per-node consumption: shard/record counts, the observed
        records/second, and the completion-window bounds the caller
        needs to aggregate rates ACROSS datasets (rates over disjoint
        windows are not additive — records over the union span are)."""
        out: Dict[int, Dict] = {}
        for nid, shards in self._node_shards.items():
            records = self._node_records.get(nid, 0)
            first = self._node_first_ts.get(nid, 0.0)
            last = self._node_last_ts.get(nid, 0.0)
            out[nid] = {
                "shards_completed": shards,
                "records_done": records,
                "records_per_s": (
                    round(records / (last - first), 1)
                    if last > first else None),
                "first_ts": first,
                "last_ts": last,
            }
        return out

    def snapshot(self) -> Dict:
        """The per-dataset row of the ``tpurun data`` ledger."""
        total = max(1, int(self._splitter.dataset_size))
        done = self._epoch_records.get(self._splitter.epoch, 0)
        remaining = total - done
        # aggregate rate over the UNION of the nodes' completion
        # windows (min first -> max last), the same rule data_report
        # applies per node: per-node spans are not interchangeable —
        # a late-joining node's short span would overstate the rate
        # and quote an ETA several times too short
        span = (
            max(self._node_last_ts.values())
            - min(self._node_first_ts.values())
        ) if self._node_first_ts else 0.0
        rate = self._records_done / span if span > 0 else None
        return {
            "todo": len(self.todo),
            "doing": len(self.doing),
            "shards_done": self._shards_done,
            "records_done": self._records_done,
            "dataset_size": int(self._splitter.dataset_size),
            "epoch": self._splitter.epoch,
            "num_epochs": int(getattr(self._splitter, "num_epochs", 1)),
            "epoch_progress": round(self.epoch_progress(), 4),
            "timeout_recovered": self._timeout_recovered,
            "completed": self.completed(),
            # remaining records of the newest epoch over the observed
            # aggregate consumption rate (None before any completion)
            "eta_s": (round(remaining / rate, 1)
                      if rate and remaining > 0 else
                      (0.0 if remaining <= 0 else None)),
        }

    def recover_tasks(self, node_id: int):
        """Requeue every doing task of a dead worker."""
        requeued = []
        for tid, doing in list(self.doing.items()):
            if doing.node_id == node_id:
                self.doing.pop(tid)
                self._reported_records.pop(tid, None)
                self.todo.appendleft(doing.task)
                requeued.append(tid)
        if requeued:
            logger.info(
                "dataset %s: recovered tasks %s of node %d",
                self.dataset_name, requeued, node_id,
            )
            self._refresh_gauges()

    def recover_timeout_tasks(self, timeout_secs: float) -> List[int]:
        now = time.time()
        recovered = []
        for tid, doing in list(self.doing.items()):
            if now - doing.start_time > timeout_secs:
                self.doing.pop(tid)
                self.todo.appendleft(doing.task)
                recovered.append(tid)
        if recovered:
            self._timeout_recovered += len(recovered)
            self._refresh_gauges()
        return recovered

    def completed(self) -> bool:
        return (
            self._splitter.epoch_finished()
            and not self.todo
            and not self.doing
        )

    # -- checkpoint ---------------------------------------------------------

    def checkpoint(self) -> str:
        """Serialize undone work: doing shards go back in front of todo.
        The shard-lifecycle accounting rides along so a restored master
        resumes the ledger (gauges, epoch progress, ``tpurun data``)
        instead of re-deriving it as zero."""
        shards = [
            [d.task.shard.start, d.task.shard.end]
            for d in self.doing.values()
        ] + [[t.shard.start, t.shard.end] for t in self.todo]
        return json.dumps({
            "dataset_name": self.dataset_name,
            "todo": shards,
            "epoch": self._splitter.epoch,
            "shards_done": self._shards_done,
            "records_done": self._records_done,
            "epoch_records_done": self._epoch_records.get(
                self._splitter.epoch, 0),
            "timeout_recovered": self._timeout_recovered,
        })

    def restore_checkpoint(self, content: str):
        state = json.loads(content)
        if state.get("dataset_name") != self.dataset_name:
            raise ValueError(
                f"checkpoint is for {state.get('dataset_name')}, "
                f"not {self.dataset_name}"
            )
        self._splitter.epoch = state.get("epoch", 0)
        self.todo.clear()
        self.doing.clear()
        restored_records = 0
        for start, end in state.get("todo", []):
            self.todo.append(
                Task(
                    self._task_id_seq,
                    self._task_type,
                    Shard(self.dataset_name, start, end),
                    epoch=self._splitter.epoch,
                )
            )
            restored_records += end - start
            self._task_id_seq += 1
        self._shards_done = int(state.get("shards_done", 0))
        self._records_done = int(state.get("records_done", 0))
        # pre-accounting checkpoints lack the field: derive the epoch
        # cursor from what is NOT pending (remaining records are the
        # ground truth the restored gauges must agree with)
        epoch_done = int(state.get(
            "epoch_records_done",
            max(0, int(self._splitter.dataset_size) - restored_records),
        ))
        self._epoch_records = {self._splitter.epoch: epoch_done}
        self._epoch_outstanding = {self._splitter.epoch: len(self.todo)}
        self._timeout_recovered = int(state.get("timeout_recovered", 0))
        if self._shards_done or epoch_done:
            # mid-epoch resume: dispatch already happened in the
            # previous life, so the lifecycle gauges come back live
            self._dispatch_started = True
        self._refresh_gauges()
        logger.info(
            "dataset %s: restored %d pending shards at epoch %d "
            "(%d records already done)",
            self.dataset_name, len(self.todo), self._splitter.epoch,
            epoch_done,
        )
