"""Dataset task dispatch across all registered datasets.

Role parity: ``dlrover/python/master/shard/task_manager.py:36-284`` — owns a
BatchDatasetManager per dataset, re-assigns shards of failed workers
(TaskRescheduleCallback path) and of timed-out workers (straggler
mitigation), and surfaces training speed to the SpeedMonitor.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from dlrover_tpu.common.config import get_context
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.master.shard.batch_dataset_manager import (
    BatchDatasetManager,
    Task,
)
from dlrover_tpu.master.shard.dataset_splitter import DatasetSplitter
from dlrover_tpu.telemetry import (
    EventKind,
    emit_event,
    get_registry,
    names as tm,
)

logger = get_logger("master.task")


class TaskManager:
    def __init__(self, speed_monitor=None):
        self._lock = threading.Lock()
        self._datasets: Dict[str, BatchDatasetManager] = {}
        self._speed_monitor = speed_monitor
        self._worker_start_task_time: Dict[int, float] = {}
        self._task_timeout_callbacks: List[Callable[[int], None]] = []
        self._stop = threading.Event()
        self._timeout_thread: Optional[threading.Thread] = None

    # -- dataset registry ---------------------------------------------------

    def new_dataset(
        self,
        dataset_name: str,
        dataset_size: int,
        batch_size: int,
        num_epochs: int = 1,
        shuffle: bool = False,
        num_minibatches_per_shard: int = 2,
        storage_type: str = "table",
        task_type: str = "training",
    ):
        with self._lock:
            if dataset_name in self._datasets:
                return
            splitter = DatasetSplitter.create(
                dataset_name, dataset_size, batch_size, num_epochs,
                shuffle, num_minibatches_per_shard, storage_type,
            )
            self._datasets[dataset_name] = BatchDatasetManager(
                splitter, task_type
            )
            logger.info(
                "registered dataset %s: size=%d batch=%d epochs=%d type=%s",
                dataset_name, dataset_size, batch_size, num_epochs,
                storage_type,
            )

    def get_dataset(self, name: str) -> Optional[BatchDatasetManager]:
        return self._datasets.get(name)

    def reset_dataset(self, name: str):
        with self._lock:
            dataset = self._datasets.pop(name, None)
            if dataset is not None:
                # a dropped dataset's lifecycle gauges must not keep
                # exporting a frozen queue forever
                dataset.retract_gauges()

    # -- dispatch -----------------------------------------------------------

    def get_dataset_task(self, node_id: int, dataset_name: str) -> Task:
        with self._lock:
            dataset = self._datasets.get(dataset_name)
            if dataset is None:
                return Task.create_invalid()
            task = dataset.get_task(node_id)
            if task.task_id >= 0:
                self._worker_start_task_time[node_id] = time.time()
            return task

    def report_dataset_task(self, dataset_name: str, task_id: int,
                            success: bool):
        with self._lock:
            dataset = self._datasets.get(dataset_name)
            if dataset is None:
                return
            ok, task = dataset.report_task_status(task_id, success)
            if ok and self._speed_monitor is not None and \
                    task.task_type == "training":
                self._speed_monitor.mark_task_completed(task.shard.size)

    def report_batch_done(self, dataset_name: str, node_id: int,
                          record_count: int) -> List[int]:
        with self._lock:
            dataset = self._datasets.get(dataset_name)
            if dataset is None:
                return []
            return dataset.report_batch_done(node_id, record_count)

    def finished(self) -> bool:
        """All registered training datasets consumed."""
        with self._lock:
            training = [
                d for d in self._datasets.values()
                if d._task_type == "training"
            ]
            return bool(training) and all(d.completed() for d in training)

    # -- failure/straggler recovery ----------------------------------------

    def recover_tasks(self, node_id: int):
        with self._lock:
            for dataset in self._datasets.values():
                dataset.recover_tasks(node_id)

    def set_task_timeout_callback(self, cb: Callable[[int], None]):
        self._task_timeout_callbacks.append(cb)

    def start(self):
        if self._timeout_thread is None:
            self._timeout_thread = threading.Thread(
                target=self._monitor_timeout_tasks,
                name="task-timeout-monitor",
                daemon=True,
            )
            self._timeout_thread.start()

    def stop(self):
        self._stop.set()

    def _monitor_timeout_tasks(self):
        while True:
            # the scan cadence FOLLOWS the configured timeout (re-read
            # each cycle): a test — or an operator chasing a straggler
            # — that shrinks seconds_to_timeout_task to sub-second must
            # not wait out a hardcoded 30 s sleep before the first scan
            timeout_s = float(get_context().seconds_to_timeout_task)
            cadence = max(0.5, min(30.0, timeout_s / 4.0))
            if self._stop.wait(cadence):
                return
            self.scan_timeout_tasks_once(timeout_s)

    def scan_timeout_tasks_once(self,
                                timeout_secs: Optional[float] = None):
        """One timeout sweep (the monitor thread's body, callable
        directly from tests): requeue overdue doing shards, count them,
        and put the recovery on the event timeline — re-dispatch means
        the shard's records will be read twice, which operators must
        be able to see, not infer from a log grep."""
        if timeout_secs is None:
            timeout_secs = float(get_context().seconds_to_timeout_task)
        with self._lock:
            for dataset in self._datasets.values():
                recovered = dataset.recover_timeout_tasks(timeout_secs)
                if not recovered:
                    continue
                get_registry().counter(
                    tm.DATA_SHARDS_TIMEOUT_RECOVERED,
                    help="doing shards requeued by the timeout monitor "
                         "(each recovery re-reads the shard's records)",
                ).inc(len(recovered))
                emit_event(
                    EventKind.DATA_SHARD_TIMEOUT,
                    error_code="DATA_SHARD_TIMEOUT",
                    dataset=dataset.dataset_name,
                    count=len(recovered),
                    task_ids=recovered[:8],
                    timeout_secs=timeout_secs,
                )
                logger.warning(
                    "dataset %s: tasks %s timed out and were "
                    "requeued", dataset.dataset_name, recovered,
                )

    # -- the shard-dispatch ledger (tpurun data / DataShardRequest) ----------

    def data_report(self, dataset_name: str = "") -> Dict:
        """Per-dataset queue/epoch accounting plus per-node consumption
        — the live ``tpurun data --addr`` payload."""
        with self._lock:
            names = ([dataset_name] if dataset_name
                     else sorted(self._datasets))
            datasets: Dict[str, Dict] = {}
            nodes: Dict[int, Dict] = {}
            for name in names:
                dataset = self._datasets.get(name)
                if dataset is None:
                    continue
                datasets[name] = dataset.snapshot()
                for nid, stats in dataset.node_stats().items():
                    agg = nodes.setdefault(nid, {
                        "shards_completed": 0, "records_done": 0,
                        "first_ts": stats["first_ts"],
                        "last_ts": stats["last_ts"],
                    })
                    agg["shards_completed"] += stats["shards_completed"]
                    agg["records_done"] += stats["records_done"]
                    agg["first_ts"] = min(agg["first_ts"],
                                          stats["first_ts"])
                    agg["last_ts"] = max(agg["last_ts"],
                                         stats["last_ts"])
        for agg in nodes.values():
            # rate over the UNION of the node's completion windows:
            # per-dataset rates over disjoint windows are not additive
            # (a node doing 100 rec/s on A then 100 rec/s on B never
            # ran at 200/s)
            span = agg.pop("last_ts") - agg.pop("first_ts")
            agg["records_per_s"] = (
                round(agg["records_done"] / span, 1)
                if span > 0 else None)
        return {"datasets": datasets,
                "nodes": {str(n): v for n, v in sorted(nodes.items())}}

    # -- shard checkpoint ---------------------------------------------------

    def get_shard_checkpoint(self, dataset_name: str) -> str:
        with self._lock:
            dataset = self._datasets.get(dataset_name)
            return dataset.checkpoint() if dataset else ""

    def restore_shard_checkpoint(self, dataset_name: str, content: str):
        with self._lock:
            dataset = self._datasets.get(dataset_name)
            if dataset and content:
                dataset.restore_checkpoint(content)
