"""Dataset task dispatch across all registered datasets.

Role parity: ``dlrover/python/master/shard/task_manager.py:36-284`` — owns a
BatchDatasetManager per dataset, re-assigns shards of failed workers
(TaskRescheduleCallback path) and of timed-out workers (straggler
mitigation), and surfaces training speed to the SpeedMonitor.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from dlrover_tpu.common.config import get_context
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.master.shard.batch_dataset_manager import (
    BatchDatasetManager,
    Task,
)
from dlrover_tpu.master.shard.dataset_splitter import DatasetSplitter

logger = get_logger("master.task")


class TaskManager:
    def __init__(self, speed_monitor=None):
        self._lock = threading.Lock()
        self._datasets: Dict[str, BatchDatasetManager] = {}
        self._speed_monitor = speed_monitor
        self._worker_start_task_time: Dict[int, float] = {}
        self._task_timeout_callbacks: List[Callable[[int], None]] = []
        self._stop = threading.Event()
        self._timeout_thread: Optional[threading.Thread] = None

    # -- dataset registry ---------------------------------------------------

    def new_dataset(
        self,
        dataset_name: str,
        dataset_size: int,
        batch_size: int,
        num_epochs: int = 1,
        shuffle: bool = False,
        num_minibatches_per_shard: int = 2,
        storage_type: str = "table",
        task_type: str = "training",
    ):
        with self._lock:
            if dataset_name in self._datasets:
                return
            splitter = DatasetSplitter.create(
                dataset_name, dataset_size, batch_size, num_epochs,
                shuffle, num_minibatches_per_shard, storage_type,
            )
            self._datasets[dataset_name] = BatchDatasetManager(
                splitter, task_type
            )
            logger.info(
                "registered dataset %s: size=%d batch=%d epochs=%d type=%s",
                dataset_name, dataset_size, batch_size, num_epochs,
                storage_type,
            )

    def get_dataset(self, name: str) -> Optional[BatchDatasetManager]:
        return self._datasets.get(name)

    def reset_dataset(self, name: str):
        with self._lock:
            self._datasets.pop(name, None)

    # -- dispatch -----------------------------------------------------------

    def get_dataset_task(self, node_id: int, dataset_name: str) -> Task:
        with self._lock:
            dataset = self._datasets.get(dataset_name)
            if dataset is None:
                return Task.create_invalid()
            task = dataset.get_task(node_id)
            if task.task_id >= 0:
                self._worker_start_task_time[node_id] = time.time()
            return task

    def report_dataset_task(self, dataset_name: str, task_id: int,
                            success: bool):
        with self._lock:
            dataset = self._datasets.get(dataset_name)
            if dataset is None:
                return
            ok, task = dataset.report_task_status(task_id, success)
            if ok and self._speed_monitor is not None and \
                    task.task_type == "training":
                self._speed_monitor.mark_task_completed(task.shard.size)

    def report_batch_done(self, dataset_name: str, node_id: int,
                          record_count: int) -> List[int]:
        with self._lock:
            dataset = self._datasets.get(dataset_name)
            if dataset is None:
                return []
            return dataset.report_batch_done(node_id, record_count)

    def finished(self) -> bool:
        """All registered training datasets consumed."""
        with self._lock:
            training = [
                d for d in self._datasets.values()
                if d._task_type == "training"
            ]
            return bool(training) and all(d.completed() for d in training)

    # -- failure/straggler recovery ----------------------------------------

    def recover_tasks(self, node_id: int):
        with self._lock:
            for dataset in self._datasets.values():
                dataset.recover_tasks(node_id)

    def set_task_timeout_callback(self, cb: Callable[[int], None]):
        self._task_timeout_callbacks.append(cb)

    def start(self):
        if self._timeout_thread is None:
            self._timeout_thread = threading.Thread(
                target=self._monitor_timeout_tasks,
                name="task-timeout-monitor",
                daemon=True,
            )
            self._timeout_thread.start()

    def stop(self):
        self._stop.set()

    def _monitor_timeout_tasks(self):
        ctx = get_context()
        while not self._stop.wait(30):
            with self._lock:
                for dataset in self._datasets.values():
                    recovered = dataset.recover_timeout_tasks(
                        ctx.seconds_to_timeout_task
                    )
                    if recovered:
                        logger.warning(
                            "dataset %s: tasks %s timed out and were "
                            "requeued", dataset.dataset_name, recovered,
                        )

    # -- shard checkpoint ---------------------------------------------------

    def get_shard_checkpoint(self, dataset_name: str) -> str:
        with self._lock:
            dataset = self._datasets.get(dataset_name)
            return dataset.checkpoint() if dataset else ""

    def restore_shard_checkpoint(self, dataset_name: str, content: str):
        with self._lock:
            dataset = self._datasets.get(dataset_name)
            if dataset and content:
                dataset.restore_checkpoint(content)
