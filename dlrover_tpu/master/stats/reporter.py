"""Stats storage backends.

Role parity: ``dlrover/python/master/stats/reporter.py``
(``LocalStatsReporter`` and the Brain-backed reporter) — where the metric
collector writes and the local optimizer reads. The local backend is
in-memory per job; the brain backend forwards to a cluster-level service
over RPC (``dlrover_tpu/brain``).
"""

from __future__ import annotations

import threading
from typing import ClassVar, Dict, List, Optional

from dlrover_tpu.master.stats.training_metrics import (
    DatasetMetric,
    ModelMetric,
    RuntimeMetric,
)
from dlrover_tpu.telemetry import get_registry, names as tm


class StatsReporter:
    """Interface; also the registry keyed by job name."""

    _instances: ClassVar[Dict[str, "StatsReporter"]] = {}
    _lock = threading.Lock()

    def report_dataset_metric(self, metric: DatasetMetric):
        ...

    def report_model_metric(self, metric: ModelMetric):
        ...

    def report_runtime_stats(self, metric: RuntimeMetric):
        ...

    @classmethod
    def new_stats_reporter(cls, job_name: str, backend: str = "local"):
        with cls._lock:
            if job_name not in cls._instances:
                cls._instances[job_name] = LocalStatsReporter()
            return cls._instances[job_name]


class LocalStatsReporter(StatsReporter):
    """In-memory store the PSLocalOptimizer reads (reference :100)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.dataset_metric: Optional[DatasetMetric] = None
        self.model_metric: Optional[ModelMetric] = None
        self.runtime_stats: List[RuntimeMetric] = []
        self._c_samples = get_registry().counter(
            tm.MASTER_RUNTIME_SAMPLES,
            help="RuntimeMetric samples ingested by the stats store")

    def report_dataset_metric(self, metric: DatasetMetric):
        with self._lock:
            self.dataset_metric = metric

    def report_model_metric(self, metric: ModelMetric):
        with self._lock:
            self.model_metric = metric

    def report_runtime_stats(self, metric: RuntimeMetric):
        self._c_samples.inc()
        with self._lock:
            self.runtime_stats.append(metric)
            # Bound memory: optimizers only look at recent windows.
            if len(self.runtime_stats) > 500:
                del self.runtime_stats[:-500]
