"""Aggregates job metrics into the stats backend.

Role parity: ``dlrover/python/master/stats/job_collector.py``
(``JobMetricCollector``) — the one place that assembles RuntimeMetric
samples (speed + per-node usage) and forwards dataset/model facts reported
by agents.
"""

from __future__ import annotations

import time
from typing import Dict

from dlrover_tpu.common.constants import NodeStatus, NodeType
from dlrover_tpu.master.stats.reporter import StatsReporter
from dlrover_tpu.master.stats.training_metrics import (
    DatasetMetric,
    ModelMetric,
    RuntimeMetric,
)


class JobMetricCollector:
    def __init__(self, job_name: str, backend: str = "local"):
        self._reporter = StatsReporter.new_stats_reporter(job_name, backend)

    @property
    def reporter(self):
        return self._reporter

    def collect_dataset_metric(self, name: str, size: int, storage_size: int = 0):
        self._reporter.report_dataset_metric(
            DatasetMetric(name=name, size=size, storage_size=storage_size)
        )

    def collect_model_info(self, info):
        """Servicer-facing adapter: a ``comm.ModelInfo`` report becomes
        a ModelMetric (the servicer hands the raw message through)."""
        self.collect_model_metric(
            param_count=int(getattr(info, "num_params", 0) or 0),
            flops_per_step=float(
                getattr(info, "flops_per_step", 0.0) or 0.0),
            activation_bytes=int(
                getattr(info, "activation_bytes", 0) or 0),
        )

    def collect_model_metric(
        self, param_count: int, flops_per_step: float,
        activation_bytes: int = 0, extra: Dict[str, float] = None,
    ):
        self._reporter.report_model_metric(
            ModelMetric(
                param_count=param_count,
                flops_per_step=flops_per_step,
                activation_bytes=activation_bytes,
                extra=extra or {},
            )
        )

    def collect_runtime_stats(self, speed_monitor, job_nodes: Dict):
        """Snapshot speed + per-node usage (called from the master loop)."""
        metric = RuntimeMetric(
            timestamp=time.time(),
            global_step=speed_monitor.completed_global_step,
            speed=speed_monitor.running_speed(),
        )
        for node_type, nodes in job_nodes.items():
            entries = []
            for node in nodes.values():
                if node.status != NodeStatus.RUNNING or node.is_released:
                    continue
                entries.append(
                    {
                        "id": node.id,
                        "name": node.name,
                        "cpu": node.config_resource.cpu,
                        "memory": node.config_resource.memory,
                        "used_cpu": node.used_resource.cpu,
                        "used_memory": node.used_resource.memory,
                    }
                )
            if entries:
                metric.running_nodes[node_type] = entries
        self._reporter.report_runtime_stats(metric)
        return metric
