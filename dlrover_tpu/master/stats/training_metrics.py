"""Typed runtime/dataset/model metric records.

Role parity: ``dlrover/python/master/stats/training_metrics.py`` — the
records the stats reporter stores and optimizers consume.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List


@dataclass
class DatasetMetric:
    name: str = ""
    size: int = 0  # total records
    storage_size: int = 0  # bytes


@dataclass
class ModelMetric:
    """Static model facts (reference: ModelInfo/TensorStats/OpStats)."""

    param_count: int = 0
    flops_per_step: float = 0.0
    activation_bytes: int = 0
    extra: Dict[str, float] = field(default_factory=dict)


@dataclass
class RuntimeMetric:
    """One sample of the job's runtime state: speed + per-node usage."""

    timestamp: float = 0.0
    global_step: int = 0
    speed: float = 0.0  # steps/s
    running_nodes: Dict[str, List[Dict]] = field(default_factory=dict)
    # node dicts: {"id", "cpu", "memory", "cpu_percent"}
