"""The master's RPC surface.

Role parity: ``dlrover/python/master/servicer.py:62-525`` — one servicer
implementing every master rpc (task dispatch, shard params, rendezvous,
kv-store, failure reports, network check, resource reports, global step, PS
queries). Here requests are typed dataclass messages dispatched by type to
the owning manager; ``get`` answers queries, ``report`` ingests state.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from dlrover_tpu.common import comm
from dlrover_tpu.common.constants import (
    RendezvousName,
    TrainingExceptionLevel,
)
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.master.elastic_training.elastic_ps import ElasticPsService
from dlrover_tpu.master.elastic_training.kv_store import KVStoreService
from dlrover_tpu.master.elastic_training.rdzv_manager import RendezvousManager
from dlrover_tpu.master.elastic_training.sync_service import SyncService
from dlrover_tpu.master.monitor.speed_monitor import SpeedMonitor
from dlrover_tpu.master.shard.task_manager import TaskManager

logger = get_logger("master.servicer")


class MasterServicer:
    def __init__(
        self,
        task_manager: Optional[TaskManager] = None,
        rdzv_managers: Optional[Dict[str, RendezvousManager]] = None,
        speed_monitor: Optional[SpeedMonitor] = None,
        kv_store: Optional[KVStoreService] = None,
        sync_service: Optional[SyncService] = None,
        elastic_ps_service: Optional[ElasticPsService] = None,
        job_manager=None,
        metric_collector=None,
        node_runtime_store=None,
        straggler_detector=None,
        runtime_optimizer=None,
        request_router=None,
        serve_slo=None,
        serving_scale_policy=None,
    ):
        from dlrover_tpu.master.monitor.node_series import NodeRuntimeStore
        from dlrover_tpu.master.monitor.straggler import StragglerDetector
        from dlrover_tpu.master.optimizer import RuntimeOptimizer

        self._task_manager = task_manager
        self._rdzv_managers = rdzv_managers or {}
        self._speed_monitor = speed_monitor
        self._kv_store = kv_store or KVStoreService()
        self._sync_service = sync_service or SyncService()
        self._elastic_ps_service = elastic_ps_service
        self._job_manager = job_manager
        self._metric_collector = metric_collector
        # the cluster diagnosis plane: every master ingests per-node
        # runtime series and judges stragglers/hangs over them
        self.node_runtime_store = (
            node_runtime_store or NodeRuntimeStore()
        )
        self.straggler_detector = straggler_detector or StragglerDetector(
            self.node_runtime_store, speed_monitor=speed_monitor
        )
        self._parallel_configs: Dict[int, comm.ParallelConfig] = {}
        # the runtime optimization loop (telemetry -> planner -> live
        # reshard): verdict changes trigger re-plans; chosen plans are
        # published through the ParallelConfig broadcast slot workers
        # already poll (get_parallel_config)
        self.runtime_optimizer = runtime_optimizer or RuntimeOptimizer(
            self.node_runtime_store,
            publish=lambda cfg: self._parallel_configs.__setitem__(
                -1, cfg),
            # a worker's apply ack retracts the consumed plan so a
            # later-restarted worker cannot replay it from the slot —
            # but only while the slot still holds THAT plan: an
            # operator/brain config pushed meanwhile must not be
            # deleted by a late ack
            retract=self._retract_plan,
        )
        self.straggler_detector.add_verdict_listener(
            self.runtime_optimizer.on_verdict)
        # the peer-redundancy plane: replica endpoint directory + the
        # rendezvous-stable assignment / budget admission / recovery
        # mapping (checkpoint-free pod-scale recovery). Diagnosis hang
        # verdicts are its node-loss signal.
        from dlrover_tpu.master.replication import ReplicaDirectory

        self.replica_directory = ReplicaDirectory()
        self.straggler_detector.add_verdict_listener(
            self.replica_directory.on_verdict)
        # the recovery-readiness plane: continuous durability audit of
        # the directory against live store inventories, blast-radius
        # verdicts with predicted-MTTR-per-rung pricing. Its durability
        # verdicts feed the SAME optimizer listener path the straggler
        # detector uses, so a coverage loss triggers a replica-aware
        # re-plan under the verdict's incident trace id.
        from dlrover_tpu.master.monitor.readiness import ReadinessAuditor

        self.readiness_auditor = ReadinessAuditor(
            self.replica_directory,
            cadence_fn=self._replica_cadence_steps,
            replicas_fn=self._configured_replicas,
        )
        self.readiness_auditor.add_verdict_listener(
            self.runtime_optimizer.on_verdict)
        self.runtime_optimizer.set_durability_evidence_fn(
            lambda node_id: (
                v.to_dict()
                if (v := self.readiness_auditor.verdicts().get(node_id))
                else None))
        # the serving request plane: the PR 9 dispatch ledger
        # generalized into a request router (enqueue/lease/complete,
        # dead-worker re-lease, per-request latency accounting)
        from dlrover_tpu.serving.router import RequestRouter

        self.request_router = request_router or RequestRouter()
        # the serving SLO plane: declared targets evaluated over
        # rolling windows on the router's live state (the master's
        # stats loop ticks it), with the scale-policy loop turning
        # confirmed violations / sustained idle into proposals for the
        # auto-scaler (attached by the dist master when one exists)
        from dlrover_tpu.master.monitor.serve_slo import (
            ServeSLOEngine,
            ServingScalePolicy,
        )

        self.serve_slo = serve_slo or ServeSLOEngine(
            self.request_router, store=self.node_runtime_store)
        self.serving_scale_policy = (
            serving_scale_policy or ServingScalePolicy(
                self.serve_slo, store=self.node_runtime_store))
        # one failure record store: the job manager's when present (its
        # handle_training_failure records there), else our own so the
        # local master can still answer failed-node queries
        from dlrover_tpu.diagnosis.error_monitor import ErrorLogMonitor
        from dlrover_tpu.telemetry import get_registry, names as tm

        self.error_monitor = getattr(
            job_manager, "error_monitor", None
        ) or ErrorLogMonitor()
        self._c_failure_reports = get_registry().counter(
            tm.MASTER_FAILURE_REPORTS,
            help="NodeFailure reports ingested by the master")
        self.job_exit_requested = False
        self.job_success: Optional[bool] = None

        self._get_handlers = {
            comm.TaskRequest: self._get_task,
            comm.ShardCheckpointRequest: self._get_shard_checkpoint,
            comm.CommWorldRequest: self._get_comm_world,
            comm.WaitingNodeNumRequest: self._num_nodes_waiting,
            comm.NetworkReadyRequest: self._network_ready,
            comm.StragglerExistRequest: self._straggler_exist,
            comm.AbnormalNodesRequest: self._abnormal_nodes,
            comm.FailedNodesRequest: self._failed_nodes,
            comm.KVStoreGetRequest: self._kv_get,
            comm.KVStoreAddRequest: self._kv_add,
            comm.BarrierRequest: self._barrier_query,
            comm.SyncJoinRequest: self._sync_query,
            comm.ClusterVersionRequest: self._get_cluster_version,
            comm.QueryPsNodesRequest: self._query_ps_nodes,
            comm.ParallelConfigRequest: self._get_parallel_config,
            comm.DiagnosisRequest: self._get_diagnosis,
            comm.PlanRequest: self._get_plan,
            comm.AttributionRequest: self._get_attribution,
            comm.DataShardRequest: self._get_data_report,
            comm.ReplicaPlanRequest: self._get_replica_plan,
            comm.RecoveryPlanRequest: self._get_recovery_plan,
            comm.ReadinessRequest: self._get_readiness,
            comm.ServeLeaseRequest: self._serve_lease,
            comm.ServeReportRequest: self._get_serve_report,
            comm.ServeSLORequest: self._get_serve_slo,
        }
        self._report_handlers = {
            comm.DatasetShardParams: self._new_dataset,
            comm.TaskResult: self._report_task_result,
            comm.BatchDoneReport: self._report_batch_done,
            comm.RendezvousParams: self._set_rdzv_params,
            comm.JoinRendezvousRequest: self._join_rendezvous,
            comm.NetworkCheckResult: self._report_network_result,
            comm.KVStoreSetRequest: self._kv_set,
            comm.SyncJoinRequest: self._sync_join,
            comm.SyncFinishRequest: self._sync_finish,
            comm.BarrierRequest: self._barrier_notify,
            comm.NodeFailure: self._report_failure,
            comm.ResourceStats: self._report_resource,
            comm.GlobalStep: self._report_global_step,
            comm.NodeRuntimeReport: self._report_node_runtime,
            comm.ShardCheckpoint: self._restore_shard_checkpoint,
            comm.NodeHeartbeat: self._report_heartbeat,
            comm.NodeStatusReport: self._report_node_status,
            comm.ClusterVersionUpdate: self._update_cluster_version,
            comm.DatasetMetric: self._collect_dataset_metric,
            comm.ModelInfo: self._collect_model_info,
            comm.JobExitRequest: self._request_job_exit,
            comm.ParallelConfig: self._set_parallel_config,
            comm.TrainerConfigReport: self._report_trainer_config,
            comm.ReplicaEndpointReport: self._report_replica_endpoint,
            comm.ServeSubmit: self._serve_submit,
            comm.ServeResult: self._serve_complete,
            comm.ServeTouch: self._serve_touch,
            comm.ServeConfigReport: self._report_serve_config,
        }

    # -- entry points (bound to the two-method gRPC service) ----------------

    def get(self, request, context=None):
        handler = self._get_handlers.get(type(request))
        if handler is None:
            return comm.Response(
                success=False, reason=f"no get handler: {type(request).__name__}"
            )
        return handler(request)

    def report(self, request, context=None):
        handler = self._report_handlers.get(type(request))
        if handler is None:
            return comm.Response(
                success=False,
                reason=f"no report handler: {type(request).__name__}",
            )
        return handler(request)

    # -- data sharding ------------------------------------------------------

    def _new_dataset(self, req: comm.DatasetShardParams):
        if self._task_manager is None:
            return comm.Response(success=False, reason="no task manager")
        self._task_manager.new_dataset(
            req.dataset_name, req.dataset_size, req.batch_size,
            req.num_epochs, req.shuffle, req.num_minibatches_per_shard,
            req.storage_type, req.task_type,
        )
        if self._metric_collector is not None:
            self._metric_collector.collect_dataset_metric(
                req.dataset_name, req.dataset_size, req.storage_type
            )
        return comm.Response(success=True)

    def _get_task(self, req: comm.TaskRequest):
        if self._task_manager is None:
            return comm.Task(task_id=-1)
        task = self._task_manager.get_dataset_task(
            req.node_id, req.dataset_name
        )
        if task.task_id < 0:
            return comm.Task(task_id=-1)
        return comm.Task(
            task_id=task.task_id,
            task_type=task.task_type,
            shard=comm.Shard(
                name=task.shard.name, start=task.shard.start,
                end=task.shard.end, record_indices=task.shard.record_indices,
            ),
            epoch=task.epoch,
        )

    def _report_task_result(self, req: comm.TaskResult):
        self._task_manager.report_dataset_task(
            req.dataset_name, req.task_id, success=not req.err_message
        )
        return comm.Response(success=True)

    def _report_batch_done(self, req: comm.BatchDoneReport):
        completed = self._task_manager.report_batch_done(
            req.dataset_name, req.node_id, req.record_count
        )
        for tid in completed:
            self._task_manager.report_dataset_task(
                req.dataset_name, tid, success=True
            )
        return comm.Response(success=True)

    def _get_shard_checkpoint(self, req: comm.ShardCheckpointRequest):
        content = self._task_manager.get_shard_checkpoint(req.dataset_name)
        return comm.ShardCheckpoint(
            dataset_name=req.dataset_name, content=content
        )

    def _restore_shard_checkpoint(self, req: comm.ShardCheckpoint):
        self._task_manager.restore_shard_checkpoint(
            req.dataset_name, req.content
        )
        return comm.Response(success=True)

    def _get_data_report(self, req: comm.DataShardRequest):
        """The shard-dispatch ledger: per-dataset todo/doing/done
        queues, epoch progress + ETA, timeout recoveries and per-node
        consumption rates — the ``tpurun data --addr`` payload."""
        import json as _json

        if self._task_manager is None:
            report = {"datasets": {}, "nodes": {}}
        else:
            report = self._task_manager.data_report(
                dataset_name=req.dataset_name or "")
        return comm.DiagnosisReport(report_json=_json.dumps(report))

    # -- serving request plane ----------------------------------------------

    def _serve_submit(self, req: comm.ServeSubmit):
        rid = self.request_router.submit(
            prompt=list(req.prompt or []),
            max_new_tokens=req.max_new_tokens,
            request_id=req.request_id, eos_id=req.eos_id,
        )
        return comm.Response(success=True, data=rid)

    def _serve_lease(self, req: comm.ServeLeaseRequest):
        return comm.ServeLeases(requests=self.request_router.lease(
            req.node_id, req.max_requests))

    def _serve_complete(self, req: comm.ServeResult):
        ok = self.request_router.complete(
            req.node_id, req.request_id, list(req.tokens or []),
            ttft_s=req.ttft_s, e2e_s=req.e2e_s,
            error_code=req.error_code,
            prefix_hit_tokens=int(getattr(req, "prefix_hit_tokens", 0)
                                  or 0),
            spec_drafted_tokens=int(
                getattr(req, "spec_drafted_tokens", 0) or 0),
            spec_accepted_tokens=int(
                getattr(req, "spec_accepted_tokens", 0) or 0),
        )
        return comm.Response(success=ok)

    def _serve_touch(self, req: comm.ServeTouch):
        self.request_router.touch(req.node_id)
        return comm.Response(success=True)

    def _report_serve_config(self, req: comm.ServeConfigReport):
        """A serve worker reported its actual running serving config —
        the runtime optimizer's serve-knob family input and plan ack."""
        self.runtime_optimizer.update_serving_config(req)
        return comm.Response(success=True)

    def _get_serve_report(self, req: comm.ServeReportRequest):
        import json as _json

        self.request_router.scan_expired_once()
        return comm.DiagnosisReport(
            report_json=_json.dumps(self.request_router.report()))

    def _get_serve_slo(self, req: comm.ServeSLORequest):
        """The serving SLO plane (``tpurun serve slo --addr``):
        declared targets, burn rates, active violation verdicts and
        the scale proposals the policy loop issued."""
        import json as _json

        report = self.serve_slo.report()
        report.update(self.serving_scale_policy.to_report())
        # the prefix-hit ledger rides the SLO view: hit rate and saved
        # prefill tokens are capacity signals the same operators read
        report["prefix"] = self.request_router.prefix_summary()
        return comm.DiagnosisReport(report_json=_json.dumps(report))

    # -- rendezvous ---------------------------------------------------------

    def _manager(self, name: str) -> Optional[RendezvousManager]:
        return self._rdzv_managers.get(name)

    def _set_rdzv_params(self, req: comm.RendezvousParams):
        targets = (
            [req.rdzv_name] if req.rdzv_name else list(self._rdzv_managers)
        )
        for name in targets:
            mgr = self._manager(name)
            if mgr is not None:
                mgr.update_rdzv_params(
                    req.min_nodes, req.max_nodes, req.waiting_timeout,
                    req.node_unit,
                )
        return comm.Response(success=True)

    def _join_rendezvous(self, req: comm.JoinRendezvousRequest):
        mgr = self._manager(req.rdzv_name or RendezvousName.TRAINING)
        if mgr is None:
            return comm.Response(success=False, reason="unknown rendezvous")
        rdzv_round = mgr.join_rendezvous(
            req.node_rank, req.local_world_size, node_id=req.node_id,
            addr=req.addr, slice_index=req.slice_index,
        )
        return comm.Response(
            success=True, data=comm.RendezvousState(round=rdzv_round)
        )

    def _get_comm_world(self, req: comm.CommWorldRequest):
        mgr = self._manager(req.rdzv_name or RendezvousName.TRAINING)
        if mgr is None:
            return comm.CommWorld(rdzv_name=req.rdzv_name)
        rdzv_round, group, world, coord = mgr.get_comm_world(req.node_rank)
        return comm.CommWorld(
            rdzv_name=req.rdzv_name, round=rdzv_round, group=group,
            world=world, coordinator_addr=coord,
        )

    def _num_nodes_waiting(self, req: comm.WaitingNodeNumRequest):
        mgr = self._manager(req.rdzv_name or RendezvousName.TRAINING)
        waiting = mgr.num_nodes_waiting() if mgr else 0
        return comm.RendezvousState(
            round=mgr.rdzv_round if mgr else 0, waiting_num=waiting
        )

    def _report_network_result(self, req: comm.NetworkCheckResult):
        mgr = self._manager(RendezvousName.NETWORK_CHECK)
        if mgr is not None:
            mgr.report_network_check_result(
                req.node_rank, req.normal, req.elapsed_time
            )
        return comm.Response(success=True)

    def _network_ready(self, req: comm.NetworkReadyRequest):
        mgr = self._manager(RendezvousName.NETWORK_CHECK)
        if mgr is None:
            return comm.Response(success=True)
        success, reason = mgr.network_check_success()
        return comm.Response(success=success, reason=reason)

    def _abnormal_nodes(self, req: comm.AbnormalNodesRequest):
        mgr = self._manager(RendezvousName.NETWORK_CHECK)
        ranks = mgr.abnormal_nodes() if mgr else []
        return comm.NodeRankList(ranks=ranks)

    def _straggler_exist(self, req: comm.StragglerExistRequest):
        # union of the pre-training network-check diagnosis and the
        # RUNTIME verdicts from the node-series detector
        mgr = self._manager(RendezvousName.NETWORK_CHECK)
        stragglers = set(mgr.straggler_nodes() if mgr else [])
        stragglers.update(self.straggler_detector.stragglers())
        return comm.Response(
            success=bool(stragglers),
            reason=",".join(str(s) for s in sorted(stragglers)),
        )

    # -- kv store / sync ----------------------------------------------------

    def _kv_set(self, req: comm.KVStoreSetRequest):
        self._kv_store.set(req.key, req.value)
        return comm.Response(success=True)

    def _kv_get(self, req: comm.KVStoreGetRequest):
        value = self._kv_store.get(req.key)
        return comm.KVStoreValue(
            key=req.key, value=value or "", found=value is not None
        )

    def _kv_add(self, req: comm.KVStoreAddRequest):
        value = self._kv_store.add(req.key, req.amount)
        return comm.KVStoreValue(key=req.key, value=str(value), found=True)

    def _sync_join(self, req: comm.SyncJoinRequest):
        done = self._sync_service.join_sync(req.sync_name, req.node_rank)
        return comm.Response(success=done)

    def _sync_query(self, req: comm.SyncJoinRequest):
        return comm.Response(
            success=self._sync_service.sync_finished(req.sync_name)
        )

    def _sync_finish(self, req: comm.SyncFinishRequest):
        self._sync_service.force_finish(req.sync_name)
        return comm.Response(success=True)

    def _barrier_notify(self, req: comm.BarrierRequest):
        self._sync_service.notify_barrier(req.barrier_name)
        return comm.Response(success=True)

    def _barrier_query(self, req: comm.BarrierRequest):
        return comm.Response(
            success=self._sync_service.barrier_reached(req.barrier_name)
        )

    # -- failures / monitoring ---------------------------------------------

    # -- peer-redundant host snapshots ---------------------------------------

    def _report_replica_endpoint(self, req: comm.ReplicaEndpointReport):
        self.replica_directory.register(
            req.node_id, req.addr, req.budget_mb, req.snapshot_mb,
            req.step, ts=req.timestamp or time.time(),
            push_seconds=float(getattr(req, "push_seconds", 0.0) or 0.0),
            push_bytes=float(getattr(req, "push_bytes", 0.0) or 0.0),
        )
        return comm.Response(success=True)

    @staticmethod
    def _configured_replicas() -> int:
        from dlrover_tpu.common.config import get_context

        return int(getattr(get_context(), "snapshot_replicas", 0))

    def _replica_cadence_steps(self) -> int:
        """The cluster-wide effective replication cadence: the base
        step cadence, stretched so one cycle spans at least the wall
        floor at the cluster's MEDIAN step time. Computed HERE — one
        value for every node — because per-node wall floors drift
        push schedules apart (a node that barely misses its floor
        skips to the next multiple) and a rebuild needs ONE step with
        full owner coverage. The multiplier is quantized to a power of
        two so small drifts of the measured median cannot hand two
        nodes different cadences. 0 = no step-time series yet (workers
        fall back to their local knob + wall floor)."""
        import math

        from dlrover_tpu.common.config import get_context

        ctx = get_context()
        base = max(1, int(getattr(ctx, "replica_cadence_steps", 16)))
        floor_s = float(getattr(
            ctx, "replica_min_interval_secs", 15.0))
        if floor_s <= 0:
            return base
        p50s = []
        for sample in self.node_runtime_store.summary().values():
            if not sample or not sample.get("step_p50"):
                continue
            if sample.get("node_type") == "serve":
                # serving samples carry DECODE-step percentiles (ms
                # scale): letting them anchor the median would inflate
                # the cadence multiplier by orders of magnitude on a
                # colocated train+serve master
                continue
            p50s.append(float(sample["step_p50"]))
        if not p50s:
            return 0
        med = sorted(p50s)[len(p50s) // 2]
        mult = max(1, math.ceil(floor_s / max(1e-9, base * med)))
        mult = 1 << (mult - 1).bit_length()
        return base * mult

    def _get_replica_plan(self, req: comm.ReplicaPlanRequest):
        plan = self.replica_directory.plan_for(
            req.node_id, self._configured_replicas())
        return comm.ReplicaPlan(
            owner=plan["owner"], peers=plan["peers"],
            replicas=plan["replicas"], requested=plan["requested"],
            group=list(plan["group"]),
            cadence_steps=self._replica_cadence_steps(),
            degraded=plan["degraded"],
            reason=plan["reason"],
        )

    def _get_recovery_plan(self, req: comm.RecoveryPlanRequest):
        import json as _json

        plan = self.replica_directory.recovery_plan(
            self._configured_replicas(), for_node=req.node_id)
        # attach the priced ladder for the requesting node so the rung
        # it walks is the predicted-MTTR choice, not a fixed order
        plan["predicted_mttr"] = (
            self.readiness_auditor.predicted_mttr_table(req.node_id))
        return comm.DiagnosisReport(report_json=_json.dumps(plan))

    def _get_readiness(self, req: comm.ReadinessRequest):
        import json as _json

        report = self.readiness_auditor.report()
        if req.node_id >= 0:
            report["nodes"] = {
                k: v for k, v in report.get("nodes", {}).items()
                if k == str(req.node_id)
            }
        return comm.DiagnosisReport(report_json=_json.dumps(report))

    def _report_failure(self, req: comm.NodeFailure):
        self._c_failure_reports.inc()
        # a hard node/process failure is the replica plane's node-loss
        # signal too: recovery plans must stop pointing fetchers at the
        # dead node's store
        if req.level in (
            TrainingExceptionLevel.NODE_ERROR,
            TrainingExceptionLevel.PROCESS_ERROR,
        ):
            self.replica_directory.mark_failed(req.node_id)
        logger.warning(
            "node %d (rank %d) failure level=%s restart=%d: %s",
            req.node_id, req.node_rank, req.level, req.restart_count,
            req.error_data[:512],
        )
        if self._job_manager is not None:
            # records into the shared error monitor via the job manager
            self._job_manager.handle_training_failure(
                req.node_id, req.restart_count, req.error_data, req.level
            )
        else:
            # local master: record at the ingress so failed-node queries
            # still work without a job manager
            self.error_monitor.process_error(
                req.node_id, req.restart_count, req.error_data, req.level
            )
        return comm.Response(success=True)

    def _failed_nodes(self, req: comm.FailedNodesRequest):
        import time as _time

        if req.since_timestamp < 0:
            # baseline probe: hand out the master clock only, no history
            return comm.NodeRankList(ranks=[], server_time=_time.time())
        return comm.NodeRankList(
            ranks=self.error_monitor.failed_node_ids(req.since_timestamp),
            server_time=_time.time(),
        )

    def _report_resource(self, req: comm.ResourceStats):
        if self._job_manager is not None:
            self._job_manager.update_node_resource_usage(
                req.node_type, req.node_id, req.cpu_percent, req.memory_mb
            )
        return comm.Response(success=True)

    def _report_global_step(self, req: comm.GlobalStep):
        if self._speed_monitor is not None:
            if getattr(req, "reset", False):
                # the true step REWOUND (rollback / live reshard): the
                # monotone max() path would pin the gauge stale-high
                self._speed_monitor.reset_step(
                    req.step, req.timestamp or time.time()
                )
            else:
                self._speed_monitor.collect_global_step(
                    req.step, req.timestamp or time.time()
                )
        return comm.Response(success=True)

    def _report_node_runtime(self, req: comm.NodeRuntimeReport):
        """Ingest a worker's node-tagged runtime snapshot and run the
        straggler/hang judgement over the refreshed series."""
        self.node_runtime_store.ingest(req)
        self.straggler_detector.observe(req.node_id)
        return comm.Response(success=True)

    def _get_diagnosis(self, req: comm.DiagnosisRequest):
        import json as _json

        summary = self.node_runtime_store.summary()
        if req.node_id >= 0:
            summary = {req.node_id: summary.get(req.node_id)}
        report = {
            "nodes": {str(k): v for k, v in summary.items()},
            "verdicts": {
                str(k): v
                for k, v in self.straggler_detector.verdicts().items()
            },
            "stragglers": self.straggler_detector.stragglers(),
            "hung": self.straggler_detector.hung_nodes(),
        }
        return comm.DiagnosisReport(report_json=_json.dumps(report))

    def _get_attribution(self, req: comm.AttributionRequest):
        """The performance-attribution view: per-node derived MFU /
        exposed-comm / HBM gauges (from the node series) plus the
        optimizer's memory-feasibility rejections — the ``tpurun
        attribution --addr`` payload."""
        import json as _json

        summary = self.node_runtime_store.summary()
        if req.node_id >= 0:
            summary = {req.node_id: summary.get(req.node_id)}
        keys = ("step", "steps_total", "step_p50", "mfu",
                "exposed_comm_frac", "flops_per_step", "peak_hbm_mb",
                "device_mem_mb", "hbm_headroom_mb", "report_age_s")
        report = {
            "nodes": {
                str(node_id): {k: sample.get(k) for k in keys}
                for node_id, sample in summary.items()
                if sample is not None
            },
            "memory_rejected": self.runtime_optimizer.memory_rejections(
                limit=req.limit or 0),
            # predicted (overlap-aware planner) vs measured (PR 8
            # gauge) exposed-comm fraction for the running config —
            # did the overlap the planner paid for materialize?
            "exposed_comm": self.runtime_optimizer.exposed_comm_view(),
        }
        return comm.DiagnosisReport(report_json=_json.dumps(report))

    def _report_heartbeat(self, req: comm.NodeHeartbeat):
        if self._job_manager is not None:
            self._job_manager.collect_node_heartbeat(
                req.node_id, req.timestamp or time.time()
            )
        return comm.Response(success=True)

    def _report_node_status(self, req: comm.NodeStatusReport):
        if self._job_manager is not None:
            self._job_manager.update_node_reported_status(
                req.node_type, req.node_id, req.status
            )
        return comm.Response(success=True)

    # -- PS parity ----------------------------------------------------------

    def _get_cluster_version(self, req: comm.ClusterVersionRequest):
        if self._elastic_ps_service is None:
            return comm.ClusterVersion(version=0)
        version = self._elastic_ps_service.get_cluster_version(
            req.version_type, req.task_type, req.task_id
        )
        return comm.ClusterVersion(version=version)

    def _update_cluster_version(self, req: comm.ClusterVersionUpdate):
        applied = True
        if self._elastic_ps_service is not None:
            applied = self._elastic_ps_service.update_cluster_version(
                req.version_type, req.version, req.task_type, req.task_id,
                expected=req.expected,
            )
        return comm.Response(success=applied)

    def _query_ps_nodes(self, req: comm.QueryPsNodesRequest):
        if self._job_manager is None or not hasattr(
            self._job_manager, "get_ps_addrs"
        ):
            return comm.PsNodes(addrs=[], ready=False)
        addrs = self._job_manager.get_ps_addrs()
        return comm.PsNodes(addrs=addrs, ready=bool(addrs))

    # -- stats / parallel config / job control ------------------------------

    def _collect_dataset_metric(self, req: comm.DatasetMetric):
        if self._metric_collector is not None:
            self._metric_collector.collect_dataset_metric(
                req.dataset_name, req.dataset_size, req.storage_type
            )
        return comm.Response(success=True)

    def _collect_model_info(self, req: comm.ModelInfo):
        if self._metric_collector is not None:
            self._metric_collector.collect_model_info(req)
        self.runtime_optimizer.update_model_info(req)
        return comm.Response(success=True)

    def _report_trainer_config(self, req: comm.TrainerConfigReport):
        """A worker reported its ACTUAL running config (train start /
        post-reshard / plan ack) — the optimizer's running-config input
        and its world-change re-plan trigger."""
        self.runtime_optimizer.update_running_config(req)
        return comm.Response(success=True)

    def _get_plan(self, req: comm.PlanRequest):
        import json as _json

        report = self.runtime_optimizer.to_report(limit=req.limit)
        return comm.DiagnosisReport(report_json=_json.dumps(report))

    def _retract_plan(self, plan_id: str):
        cur = self._parallel_configs.get(-1)
        if cur is not None and getattr(cur, "plan_id", "") == plan_id:
            self._parallel_configs.pop(-1, None)

    def _set_parallel_config(self, req: comm.ParallelConfig):
        # master-pushed config applies to all nodes (node_id -1 = broadcast)
        self._parallel_configs[-1] = req
        return comm.Response(success=True)

    def _get_parallel_config(self, req: comm.ParallelConfigRequest):
        cfg = self._parallel_configs.get(req.node_id) or \
            self._parallel_configs.get(-1)
        return cfg or comm.ParallelConfig()

    def _request_job_exit(self, req: comm.JobExitRequest):
        self.job_exit_requested = True
        self.job_success = req.success
        logger.info(
            "job exit requested by node %d: success=%s reason=%s",
            req.node_id, req.success, req.reason,
        )
        return comm.Response(success=True)
