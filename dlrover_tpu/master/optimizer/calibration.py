"""Calibrate the planner's cost model against measured runtime series.

``parallel.planner.estimate`` prices a mesh from first principles
(datasheet FLOPs, link bandwidths, a bench-fitted efficiency). That is
the right prior before anything has run — but the running job KNOWS its
real step time: the per-node series the diagnosis plane collects
(``master/monitor/node_series.py``) carries windowed step-time,
dispatch and host-sync percentiles. This module fits per-term
correction factors (predicted vs observed) so the optimizer's candidate
pricing is anchored to reality while keeping the analytic model's
RELATIVE structure (how cost scales with mesh shape, ``steps_per_call``,
dispatch mode) — the part measurement alone cannot provide.

Three factor families (``TermCorrections``):

  dispatch  measured per-call host dispatch time over the model's
            ``HOST_DISPATCH_OVERHEAD_S`` constant. The cleanest
            attribution: the executor's dispatch histogram times
            exactly this term, once per compiled call.
  compute   measured device-bound per-step time over the predicted
            compute seconds. Observable only when the job is NOT
            dispatch-bound (otherwise the device time hides under the
            floor and the previous factor is kept).
  comm      collective seconds scale; defaults to tracking the compute
            factor (the two are not separable from step time alone —
            a future HLO-profile feed can split them).

Recombination uses the SAME formula as ``estimate`` itself
(``planner.combine_step_time``), so a calibrated prediction for the
*current* config reproduces the measured p50 by construction — the
property the acceptance test pins.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.parallel.planner import (
    COMM_BREAKDOWN_KEYS,
    HOST_DISPATCH_OVERHEAD_S,
    DeviceSpec,
    ModelSpec,
    PlanScore,
    combine_step_time,
    estimate,
)

logger = get_logger("master.optimizer.calibration")

# factors are clamped into this band: a single garbage window (clock
# hiccup, empty histogram) must not blow the model up by 10^6
_FACTOR_MIN = 0.02
_FACTOR_MAX = 1e4

# the measured step p50 must exceed the dispatch share by this margin
# before the residual is trusted as a DEVICE time observation
_DEVICE_VISIBLE_MARGIN = 1.25

# analytic-memory fit headroom (the planner's own 0.8: allocator
# fragmentation, collective buffers, hoisted gathers)
_FIT_HEADROOM = 0.8


class MemoryInfeasibleError(ValueError):
    """A candidate plan's predicted peak HBM exceeds the device budget:
    it must be REJECTED BEFORE PRICING (a cheap-looking plan the
    devices cannot hold would win the ranking and then OOM the apply).
    Carries the evidence the decision trail records."""

    def __init__(self, mesh, memory_bytes: float, budget_bytes: float):
        super().__init__(
            f"plan {mesh} memory-infeasible: predicted peak "
            f"{memory_bytes / 1e9:.2f} GB > budget "
            f"{budget_bytes / 1e9:.2f} GB"
        )
        self.mesh = mesh
        self.memory_bytes = float(memory_bytes)
        self.budget_bytes = float(budget_bytes)


@dataclass
class TermCorrections:
    """Multiplicative predicted->observed factors per cost-term family
    (1.0 = the analytic model was right)."""

    compute: float = 1.0
    comm: float = 1.0
    dispatch: float = 1.0
    samples: int = 0
    updated_ts: float = 0.0

    def to_dict(self) -> Dict[str, float]:
        return {
            "compute": round(self.compute, 4),
            "comm": round(self.comm, 4),
            "dispatch": round(self.dispatch, 4),
            "samples": self.samples,
            "updated_ts": self.updated_ts,
        }


def _clamp(x: float) -> float:
    return min(max(float(x), _FACTOR_MIN), _FACTOR_MAX)


def calibrated_step_time(
    score: PlanScore,
    corrections: TermCorrections,
    steps_per_call: int = 1,
    overlapped: bool = True,
) -> float:
    """Re-price one ``estimate`` result under the fitted corrections,
    through the planner's own combining formula."""
    bd = score.breakdown
    compute_s = bd.get("compute_s", 0.0) * corrections.compute
    comm_s = sum(bd.get(k, 0.0) for k in COMM_BREAKDOWN_KEYS)
    comm_s *= corrections.comm
    dispatch_s = (
        HOST_DISPATCH_OVERHEAD_S * corrections.dispatch
        / max(1, steps_per_call)
    )
    return combine_step_time(compute_s, comm_s, dispatch_s,
                             overlapped=overlapped)


@dataclass
class CostCalibrator:
    """Fits ``TermCorrections`` from measured (step p50, dispatch p50)
    points for the CURRENT config, one observation at a time (EMA over
    windows, so one noisy window cannot whipsaw the model)."""

    model: ModelSpec
    device: DeviceSpec = field(default_factory=DeviceSpec)
    remat_policy: str = ""
    # per-device HBM budget (bytes) for the memory-feasibility gate;
    # 0 = the device spec's capacity under the planner's fit headroom
    hbm_budget_bytes: float = 0.0
    ema: float = 0.5  # weight of the NEWEST observation
    corrections: TermCorrections = field(default_factory=TermCorrections)
    # factor families that have absorbed at least one real observation:
    # the FIRST observation of a family is adopted outright (blending
    # it with the 1.0 prior would halve a true 10x correction right
    # when the first replan decision is being made), later ones EMA in.
    # Keyed per family — a dispatch-only first pass must not make the
    # compute family think it has been observed.
    _seen: set = field(default_factory=set)

    def base_estimate(self, mesh, steps_per_call: int = 1) -> PlanScore:
        return estimate(
            mesh, self.model, self.device,
            remat_policy=self.remat_policy,
            steps_per_call=steps_per_call,
        )

    def observe(
        self,
        mesh,
        steps_per_call: int,
        measured_step_p50: Optional[float],
        measured_dispatch_p50: Optional[float] = None,
        now: Optional[float] = None,
    ) -> TermCorrections:
        """One calibration pass against the running config's window.

        ``measured_dispatch_p50`` is PER COMPILED CALL (what the
        executor's dispatch histogram observes); ``measured_step_p50``
        is per optimizer step (the node-series step histogram)."""
        if measured_step_p50 is None and measured_dispatch_p50 is None:
            return self.corrections
        k = max(1, int(steps_per_call))
        base = self.base_estimate(mesh, steps_per_call=k)
        cur = self.corrections

        def blend(family: str, old: float, new: float) -> float:
            if family not in self._seen:
                self._seen.add(family)
                return _clamp(new)
            return _clamp(old * (1.0 - self.ema) + new * self.ema)

        dispatch_per_step = None
        if measured_dispatch_p50 is not None and measured_dispatch_p50 > 0:
            cur.dispatch = blend(
                "dispatch", cur.dispatch,
                measured_dispatch_p50 / HOST_DISPATCH_OVERHEAD_S,
            )
            dispatch_per_step = measured_dispatch_p50 / k
        if measured_step_p50 is not None and measured_step_p50 > 0:
            bd = base.breakdown
            pred_device = combine_step_time(
                bd.get("compute_s", 0.0),
                sum(bd.get(key, 0.0) for key in COMM_BREAKDOWN_KEYS),
                dispatch_s=0.0,
            )
            if dispatch_per_step is None:
                dispatch_per_step = (
                    HOST_DISPATCH_OVERHEAD_S * cur.dispatch / k
                )
            if (
                pred_device > 0
                and measured_step_p50
                > _DEVICE_VISIBLE_MARGIN * dispatch_per_step
            ):
                # device-visible regime: the step time IS the device
                # time (dispatch hides under the overlap floor)
                factor = measured_step_p50 / pred_device
                cur.compute = blend("compute", cur.compute, factor)
                # comm is not separable from step time alone; keep it
                # tracking the compute scale so mesh-relative structure
                # from the analytic model survives
                cur.comm = cur.compute
            elif dispatch_per_step and measured_dispatch_p50 is None:
                # dispatch-bound and no direct dispatch measurement:
                # the step p50 IS the per-step dispatch cost
                cur.dispatch = blend(
                    "dispatch", cur.dispatch,
                    measured_step_p50 * k / HOST_DISPATCH_OVERHEAD_S,
                )
        cur.samples += 1
        cur.updated_ts = float(now if now is not None else time.time())
        logger.info(
            "calibration pass %d: compute=%.3g comm=%.3g dispatch=%.3g "
            "(measured step p50=%s dispatch p50=%s, K=%d)",
            cur.samples, cur.compute, cur.comm, cur.dispatch,
            measured_step_p50, measured_dispatch_p50, k,
        )
        return cur

    def price(self, mesh, steps_per_call: int = 1,
              train_window: int = 1,
              moe_dispatch: str = "",
              dispatch_chunks: int = 0,
              moe_precision: str = "",
              fsdp_precision: str = "",
              require_fit: bool = True) -> float:
        """Calibrated predicted per-step seconds for one candidate.

        ``require_fit`` (the candidate-enumeration default) rejects
        plans ``estimate`` judges infeasible BEFORE pricing: an
        unbuildable sharding (``step_s=inf``) raises ``ValueError``; a
        memory overflow — predicted peak HBM above ``hbm_budget_bytes``
        (or the device capacity under the planner's 0.8 fit headroom)
        — raises ``MemoryInfeasibleError`` carrying the evidence, so
        the optimizer can record a ``PLAN_REJECTED`` memory reason in
        the decision trail. The corrections rescale the breakdown
        TERMS, which stay finite even for plans the planner refused,
        and a cheap-looking infeasible mesh must never win the
        candidate ranking. Pass ``require_fit=False`` only for the
        CURRENT config, which is observably running regardless of what
        the analytic memory model thinks of it."""
        import dataclasses as _dc

        model = self.model
        if moe_dispatch and moe_dispatch != model.moe_dispatch:
            model = _dc.replace(model, moe_dispatch=moe_dispatch)
        if (dispatch_chunks
                and dispatch_chunks != model.moe_dispatch_chunks):
            # the chunk knob reshapes only the EXPOSED share of the
            # dispatch comm (overlap_exposed_comm); bytes are invariant
            model = _dc.replace(model,
                                moe_dispatch_chunks=int(dispatch_chunks))
        if moe_precision and moe_precision != model.moe_precision:
            # the precision knob reshapes the BYTES (the fp8 wire's
            # values + scale side-band, ModelSpec.moe_wire_bytes_per_elem)
            # — the dual of the chunk knob, priced through the same
            # estimate
            model = _dc.replace(model, moe_precision=moe_precision)
        if fsdp_precision and fsdp_precision != model.fsdp_precision:
            # the dense-wire knob reshapes the fsdp GATHER bytes
            # (ModelSpec.fsdp_wire_bytes_per_elem; the grad
            # reduce-scatter leg stays at the param dtype)
            model = _dc.replace(model, fsdp_precision=fsdp_precision)
        k = max(1, int(steps_per_call))
        base = estimate(
            mesh, model, self.device, remat_policy=self.remat_policy,
            steps_per_call=k,
        )
        if require_fit:
            if base.step_time_s == float("inf"):
                raise ValueError(
                    f"plan {mesh} unbuildable (fits={base.fits}, "
                    f"step_s={base.step_time_s})"
                )
            # an explicit operator budget GOVERNS (it already encodes
            # whatever headroom the operator wants); otherwise the
            # planner's own fit judgement (capacity x 0.8) applies
            if self.hbm_budget_bytes:
                budget = self.hbm_budget_bytes
                over = base.memory_bytes > budget
            else:
                budget = self.device.hbm_bytes * _FIT_HEADROOM
                over = not base.fits
            if over:
                raise MemoryInfeasibleError(
                    mesh, base.memory_bytes, budget)
        return calibrated_step_time(
            base, self.corrections, steps_per_call=k,
            overlapped=train_window > 0,
        )
