"""Master-side runtime optimizer: the closed control loop.

Triggers (straggler/hang verdicts from ``master/monitor/straggler.py``,
``DIAG_RECOVERED``, world changes reported by resharded workers) run a
re-plan pass: calibrate the planner's cost model against the measured
node series (``calibration``), enumerate candidate configs — mesh shape
for the current world, ``train_window``, ``steps_per_call``, MoE
dispatch mode — price every one through the calibrated estimate, and
publish the winner as a ``ParallelConfig`` plan the workers apply LIVE
(``OptimizerPlanHook`` → executor retune → program cache / live
reshard; no process restart).

Guard rails so the loop cannot oscillate:

  hysteresis   a plan must predict ≥ ``replan_min_speedup`` over the
               calibrated estimate of the CURRENT config;
  cooldown     the identical candidate proposed twice within
               ``replan_cooldown_secs`` is suppressed
               (``parallel.search.ProposalCooldown``);
  tie-break    equal-price candidates sort by distance from the current
               knobs, so "no change" always beats gratuitous churn.

Every decision (candidates priced, plan chosen/rejected, calibration
factors, predicted-vs-realized speedup) lands in the event timeline as
``OPTIMIZER_*`` records under one incident trace id; ``tpurun plan``
renders the live table (``PlanRequest`` RPC) and the forensic trail
(``decision_trail_from_events``).
"""

from __future__ import annotations

import collections
import statistics
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from dlrover_tpu.common import comm
from dlrover_tpu.common.config import get_context
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.master.monitor.straggler import BOUND_PEER_DELTA
from dlrover_tpu.master.optimizer.calibration import (
    CostCalibrator,
    MemoryInfeasibleError,
)
from dlrover_tpu.parallel.mesh import (
    MeshPlan,
    candidate_plans,
    mesh_axes_key,
)
from dlrover_tpu.parallel.planner import DeviceSpec, ModelSpec
from dlrover_tpu.parallel.search import ProposalCooldown
from dlrover_tpu.telemetry import (
    EventKind,
    emit_event,
    get_registry,
    names as tm,
)
from dlrover_tpu.telemetry.trace_context import (
    current_trace_id,
    trace_scope,
)

logger = get_logger("master.optimizer")

# how many top-priced candidates ride along in events / the plan report
_TABLE_ROWS = 8
# bound on the retained decision trail
_MAX_DECISIONS = 64
# a node's latest sample older than this does not anchor calibration
_CALIBRATION_FRESHNESS_S = 600.0
# the input-bound replan gate's absolute backstop: uniform cluster-wide
# starvation (a shared slow filesystem — the most common input-bound
# mode) has no peer excess to show, so a MEDIAN input-wait fraction at
# an absolute majority of the window also marks the job data-starved.
# The peer-relative leg shares BOUND_PEER_DELTA with the straggler
# verdict's bound label (one constant, never desynchronized).
_INPUT_BOUND_ABS = 0.5

STEPS_PER_CALL_OPTIONS = (1, 2, 4, 8)
# grouped_ep chunked-dispatch degrees the optimizer prices (the
# comm/compute-overlap knob, ops.moe dispatch_chunks). Enumerated only
# when the worker REPORTS it runs moe_dispatch="grouped_ep" — on any
# other dispatch the knob is inert and would only widen the candidate
# product. Applied live through the same prewarmed program-cache swap
# as steps_per_call (ElasticTrainer.retune(dispatch_chunks=...)).
DISPATCH_CHUNKS_OPTIONS = (1, 2, 4, 8)
# grouped_ep wire precisions the optimizer prices (ops.moe precision /
# ops.quantize): the fp8 wire halves the dispatch-comm bytes the
# planner prices, so on a comm-bound MoE job the family wins honestly.
# Enumerated under the same parked-knob discipline as dispatch_chunks
# (only when the worker REPORTS moe_dispatch="grouped_ep" — on any
# other dispatch the knob is inert and would only widen the candidate
# product), and applied live through the same prewarmed program-cache
# swap (ElasticTrainer.retune(moe_precision=...)). "fp8_qdq" (the
# reference oracle) is deliberately absent: it prices as bf16 and
# exists to test against, never to run.
MOE_PRECISION_OPTIONS = ("bf16", "fp8")
# dense FSDP gather wire precisions the optimizer prices (models/llama
# fsdp_precision / ops.quantize): the fp8 wire cuts the per-layer param
# gather bytes the planner's fsdp_gather term prices (~0.28x of an f32
# gather), so on a gather-bound dense job the family wins honestly.
# Enumerated under the parked-knob discipline: only when the worker
# REPORTS a dense-wire precision (TrainerConfigReport.fsdp_precision —
# a trainer-managed llama job always does) AND the running mesh
# actually has an fsdp axis > 1; otherwise the knob is inert and would
# only widen the candidate product. Applied live through the same
# prewarmed program-cache swap (ElasticTrainer.retune(fsdp_precision=))
# with the probe-failure negative-ack contract. "fp8_qdq" (the
# dequant-exact oracle) is deliberately absent: it prices at the full-
# precision wire and exists to test against, never to run. The
# GRADIENT-path precision (grad_precision) is NOT a family at all: its
# error-feedback residual is TrainState structure, which no live
# retune can swap.
FSDP_PRECISION_OPTIONS = ("bf16", "fp8")
# priced by the cost model, but NOT yet live-appliable: a dispatch-mode
# change rebuilds the model, and enumeration is gated on the calibrator
# seeing num_experts > 0 — which comm.ModelInfo does not carry yet, so
# today every candidate keeps the running mode. Wire ModelInfo experts
# + a model-rebuild apply path before enabling this knob for real.
MOE_DISPATCH_OPTIONS = ("gather", "einsum", "grouped", "grouped_ep")


def _mesh_dict(mesh: MeshPlan) -> Dict[str, int]:
    return {k: int(v) for k, v in mesh.axis_sizes().items()}


@dataclass
class RunningConfig:
    """What the workers report they are actually running."""

    mesh: MeshPlan
    world: int
    train_window: int = 4
    steps_per_call: int = 1
    moe_dispatch: str = ""
    dispatch_chunks: int = 1
    moe_precision: str = "bf16"
    # "" = the worker did not report a dense-wire precision (the
    # family stays parked); a trainer-managed job reports "bf16"/"fp8"
    fsdp_precision: str = ""
    global_batch: int = 0

    @classmethod
    def from_report(cls, report: comm.TrainerConfigReport
                    ) -> "RunningConfig":
        shape = dict(report.mesh_shape or {})
        mesh = MeshPlan(**{
            k: int(v) for k, v in shape.items()
            if k in ("pipe", "data", "fsdp", "seq", "tensor")
        }) if shape else MeshPlan(data=max(1, report.world))
        return cls(
            mesh=mesh,
            world=int(report.world or 0),
            train_window=int(report.train_window),
            steps_per_call=max(1, int(report.steps_per_call)),
            moe_dispatch=report.moe_dispatch or "",
            dispatch_chunks=max(
                1, int(getattr(report, "dispatch_chunks", 0) or 1)),
            moe_precision=str(
                getattr(report, "moe_precision", "") or "bf16"),
            fsdp_precision=str(
                getattr(report, "fsdp_precision", "") or ""),
            global_batch=int(report.global_batch or 0),
        )

    def to_dict(self) -> Dict:
        return {
            "mesh": _mesh_dict(self.mesh),
            "world": self.world,
            "train_window": self.train_window,
            "steps_per_call": self.steps_per_call,
            "moe_dispatch": self.moe_dispatch,
            "dispatch_chunks": self.dispatch_chunks,
            "moe_precision": self.moe_precision,
            "fsdp_precision": self.fsdp_precision,
            "global_batch": self.global_batch,
        }


@dataclass
class CandidateScore:
    """One priced candidate config."""

    mesh: MeshPlan
    steps_per_call: int
    train_window: int
    moe_dispatch: str
    dispatch_chunks: int = 1
    moe_precision: str = "bf16"
    fsdp_precision: str = "bf16"
    predicted_step_s: float = 0.0
    speedup: float = 0.0  # current predicted / this predicted

    @property
    def key(self) -> str:
        return (
            f"mesh={mesh_axes_key(self.mesh)}"
            f"|k={self.steps_per_call}|w={self.train_window}"
            f"|moe={self.moe_dispatch}|c={self.dispatch_chunks}"
            f"|p={self.moe_precision}|fp={self.fsdp_precision}"
        )

    def to_dict(self) -> Dict:
        return {
            "mesh": _mesh_dict(self.mesh),
            "steps_per_call": self.steps_per_call,
            "train_window": self.train_window,
            "moe_dispatch": self.moe_dispatch,
            "dispatch_chunks": self.dispatch_chunks,
            "moe_precision": self.moe_precision,
            "fsdp_precision": self.fsdp_precision,
            "predicted_step_s": round(self.predicted_step_s, 6),
            "speedup": round(self.speedup, 3),
        }


@dataclass
class Decision:
    """One re-plan pass: what was priced, what was decided, and — once
    the worker's post-apply window lands — what it actually bought."""

    trigger: str
    trace_id: str
    ts: float
    outcome: str = "rejected"  # "chosen" | "rejected"
    reason: str = ""
    plan_id: str = ""
    current: Dict = field(default_factory=dict)
    current_predicted_s: float = 0.0
    candidates: List[Dict] = field(default_factory=list)
    chosen: Optional[Dict] = None
    predicted_speedup: float = 0.0
    corrections: Dict = field(default_factory=dict)
    applied: bool = False
    apply_failed: bool = False
    realized_speedup: Optional[float] = None
    # candidate meshes the MEMORY-FEASIBILITY gate rejected BEFORE
    # pricing (predicted peak HBM above the device budget) — the
    # evidence `tpurun plan` / `tpurun attribution` surface
    memory_rejected: List[Dict] = field(default_factory=list)
    # the INPUT-BOUND gate's evidence when it rejected this pass's
    # program plan (which node is starved, by how much over peers)
    input_bound: Optional[Dict] = None
    # the readiness auditor's verdict evidence when a ``durability:``
    # trigger fired this pass (which owner is at risk, which coverage /
    # staleness / budget dimension failed) — `tpurun plan` shows WHY a
    # placement replan was asked for, not just that one was
    durability: Optional[Dict] = None
    # the chosen candidate's knob-tuple key (blacklist identity on a
    # failed apply); not part of the reported dict
    chosen_key: str = ""

    def to_dict(self) -> Dict:
        return {
            "trigger": self.trigger,
            "trace_id": self.trace_id,
            "ts": self.ts,
            "outcome": self.outcome,
            "reason": self.reason,
            "plan_id": self.plan_id,
            "current": dict(self.current),
            "current_predicted_s": round(self.current_predicted_s, 6),
            "candidates": list(self.candidates),
            "chosen": dict(self.chosen) if self.chosen else None,
            "predicted_speedup": round(self.predicted_speedup, 3),
            "corrections": dict(self.corrections),
            "applied": self.applied,
            "apply_failed": self.apply_failed,
            "realized_speedup": self.realized_speedup,
            "memory_rejected": list(self.memory_rejected),
            "input_bound": (dict(self.input_bound)
                            if self.input_bound else None),
            "durability": (dict(self.durability)
                           if self.durability else None),
        }


class RuntimeOptimizer:
    """The loop brain. Thread-safe: triggers arrive from RPC handler
    threads and the master's periodic stats loop."""

    def __init__(
        self,
        store,
        publish: Optional[Callable[[comm.ParallelConfig], None]] = None,
        retract: Optional[Callable[[str], None]] = None,
        device: Optional[DeviceSpec] = None,
        min_speedup: Optional[float] = None,
        cooldown_secs: Optional[float] = None,
        enabled: Optional[bool] = None,
        mesh_candidates: bool = True,
    ):
        ctx = get_context()
        self._store = store
        self._publish = publish
        self._retract = retract
        self._device = device or DeviceSpec()
        self._min_speedup = float(
            min_speedup if min_speedup is not None
            else getattr(ctx, "replan_min_speedup", 1.2))
        self._cooldown = ProposalCooldown(float(
            cooldown_secs if cooldown_secs is not None
            else getattr(ctx, "replan_cooldown_secs", 60.0)))
        self._enabled = bool(
            enabled if enabled is not None
            else getattr(ctx, "runtime_optimizer_enabled", True))
        # the input-bound gate (mirror of PR 8's memory gate): a
        # data-starved job must not pay a drain for a program replan
        # that cannot feed it — docs/operations.md names the knob
        self._input_bound_gate = bool(
            getattr(ctx, "replan_input_bound_gate", True))
        self._mesh_candidates = mesh_candidates
        # supplies the readiness auditor's verdict evidence for a node
        # (wired by the servicer) so durability-triggered decisions
        # carry WHY placement must change, not just the trigger string
        self._durability_evidence_fn: Optional[
            Callable[[int], Optional[Dict]]] = None
        self._lock = threading.RLock()
        self._running: Optional[RunningConfig] = None
        # last reported world PER NODE (the world-change trigger input)
        self._node_worlds: Dict[int, int] = {}
        # knob tuples a worker negative-acked (rebuild failed /
        # unsupported): excluded from the candidate ranking for this
        # optimizer's lifetime — the model priced them feasible once
        # and reality disagreed, so re-proposing every cooldown window
        # would stall the job with a failed rebuild each cycle
        self._failed_keys: set = set()
        self._model_info: Optional[comm.ModelInfo] = None
        # the serving workload's running view (ServeConfigReport) —
        # the serve-knob family's input, None until a serve worker
        # reports; worlds tracked PER NODE so a laggard's stale report
        # cannot rewind the view (the _node_worlds discipline)
        self._serving: Optional[Dict] = None
        self._serve_node_worlds: Dict[int, int] = {}
        self._calibrator: Optional[CostCalibrator] = None
        self._decisions: "collections.deque[Decision]" = (
            collections.deque(maxlen=_MAX_DECISIONS)
        )
        self._pending: Optional[comm.ParallelConfig] = None
        self._plan_seq = 0
        reg = get_registry()
        self._c_replans = reg.counter(
            tm.OPTIMIZER_REPLANS, help="re-plan passes evaluated")
        self._c_chosen = reg.counter(
            tm.OPTIMIZER_PLANS_CHOSEN, help="plans published to workers")
        self._c_rejected = reg.counter(
            tm.OPTIMIZER_PLANS_REJECTED,
            help="plans suppressed (hysteresis / cooldown / optimal)")
        self._c_calibrations = reg.counter(
            tm.OPTIMIZER_CALIBRATIONS,
            help="cost-model calibration passes")
        self._c_memory_rejected = reg.counter(
            tm.OPTIMIZER_PLANS_MEMORY_REJECTED,
            help="candidate plans rejected by the memory-feasibility "
                 "gate before pricing")

    # -- inputs --------------------------------------------------------------

    def update_model_info(self, info: comm.ModelInfo) -> None:
        with self._lock:
            self._model_info = info
            self._calibrator = None  # respec; corrections re-fit fast

    def update_running_config(self, report: comm.TrainerConfigReport
                              ) -> None:
        """A worker reported the config it actually runs (train start,
        post-reshard, post-retune, or a plan-apply ack)."""
        with self._lock:
            cfg = RunningConfig.from_report(report)
            # the world-change trigger compares a node against ITS OWN
            # previous report: during a reshard the survivors re-report
            # at different times, and judging consecutive reports from
            # DIFFERENT nodes against one global slot would fire
            # spurious 8->4->8->4 replans off a laggard's stale view
            nid = int(report.node_id)
            prev_world = self._node_worlds.get(nid)
            self._node_worlds[nid] = cfg.world
            world_changed = (
                prev_world is not None and prev_world != cfg.world
                and cfg.world > 0
            )
            # adopt the report as the running view unless it is a
            # laggard's STALE minority world: after an 8->4 shrink a
            # queued pre-shrink report (world=8, no per-node change)
            # must not rewind _running — the next replan would price
            # and publish candidates for a world that no longer exists
            if (
                self._running is None or world_changed
                or cfg.world == self._running.world
            ):
                self._running = cfg
            if report.plan_id:
                self._record_applied(report)
        if world_changed:
            # ScalePlan / live-reshard world change: the knobs tuned for
            # the old world may be wrong for the survivor one
            self.replan(f"world_change:{prev_world}->{cfg.world}")

    def _record_applied(self, report: comm.TrainerConfigReport) -> None:
        failed = bool(getattr(report, "apply_failed", False))
        for d in reversed(self._decisions):
            if d.plan_id == report.plan_id:
                if failed:
                    d.apply_failed = True
                    if d.chosen_key:
                        self._failed_keys.add(d.chosen_key)
                        logger.warning(
                            "plan %s (%s) failed to apply on node %d; "
                            "knob tuple blacklisted",
                            report.plan_id, d.chosen_key, report.node_id,
                        )
                else:
                    d.applied = True
                    realized = getattr(report, "realized_speedup", 0.0)
                    if realized:
                        d.realized_speedup = round(float(realized), 3)
                break
        # a consumed plan is RETRACTED from the broadcast slot: a worker
        # restarted later (fresh _seen_plan) must not replay a plan the
        # running job already absorbed — it would retune the job off a
        # judgment the optimizer no longer stands behind and corrupt
        # the decision trail with a second apply/measurement cycle
        if (
            self._pending is not None
            and self._pending.plan_id == report.plan_id
        ):
            self._pending = None
            if self._retract is not None:
                try:
                    self._retract(report.plan_id)
                except Exception:  # noqa: BLE001 — ack path must not die
                    logger.exception("failed to retract consumed plan")

    def update_serving_config(self, report: comm.ServeConfigReport
                              ) -> None:
        """A SERVE worker reported its running config (serve start,
        post-resize, or a serve-plan ack) — the serving twin of
        ``update_running_config``. A config change (fresh worker,
        resized world) triggers a serve-knob re-plan."""
        with self._lock:
            cfg = {
                "node_id": int(report.node_id),
                "world": int(report.world),
                "serve_slots": int(report.serve_slots),
                "prefill_chunk": int(report.prefill_chunk),
                "kv_precision": report.kv_precision or "f32",
                "max_seq": int(report.max_seq),
                "num_layers": int(getattr(report, "num_layers", 0)),
                "kv_heads": int(getattr(report, "kv_heads", 0)),
                "head_dim": int(getattr(report, "head_dim", 0)),
                "prefix_pool_pages": int(getattr(
                    report, "prefix_pool_pages", 0)),
                "page_size": int(getattr(report, "page_size", 0)),
                # observed hit rate rides the report but is NOT a
                # replan trigger (it drifts every request) — it only
                # feeds the pricing when >= 0
                "prefix_hit_rate": float(getattr(
                    report, "prefix_hit_rate", -1.0)),
                "spec_draft_len": int(getattr(
                    report, "spec_draft_len", 0) or 0),
                # the observed acceptance rate: pricing evidence only
                # (like the hit rate, it drifts — never a trigger)
                "spec_accept_rate": float(getattr(
                    report, "spec_accept_rate", -1.0)),
            }
            if report.plan_id:
                self._record_applied(report)
            # per-node world tracking + stale-minority rejection, the
            # update_running_config discipline: around a resize, a
            # laggard peer's queued pre-resize report must neither
            # rewind the serving view to a dead world nor fire a
            # replan priced for it
            nid = int(report.node_id)
            prev_world = self._serve_node_worlds.get(nid)
            self._serve_node_worlds[nid] = cfg["world"]
            world_changed = (prev_world is not None
                             and prev_world != cfg["world"]
                             and cfg["world"] > 0)
            prev = self._serving
            adopted = (prev is None or world_changed
                       or cfg["world"] == prev.get("world"))
            if adopted:
                self._serving = cfg
            changed = adopted and (prev is None or any(
                prev.get(k) != cfg[k]
                for k in ("world", "serve_slots", "prefill_chunk",
                          "kv_precision", "prefix_pool_pages",
                          "spec_draft_len")))
        if changed and not report.plan_id:
            # an ack's config echo is the plan we just published —
            # re-planning on it would chase our own tail
            self.replan_serving("serve_config")

    # -- the serving knob family ---------------------------------------------

    def serving_config(self) -> Optional[Dict]:
        with self._lock:
            cfg = getattr(self, "_serving", None)
            return dict(cfg) if cfg else None

    def _serve_candidates(self, cfg: Dict) -> List[Dict]:
        slots = max(1, cfg["serve_slots"])
        chunk = max(1, cfg["prefill_chunk"])
        max_seq = max(1, cfg["max_seq"])
        slot_opts = sorted({
            s for s in (slots // 2, slots, slots * 2, slots * 4)
            if 1 <= s <= 256})
        # only chunks the worker can honor EXACTLY: the reported
        # max_seq is the page-aligned pool depth, and the engine fits
        # chunks to its divisors (a non-divisor plan would be
        # negative-acked — don't enumerate guaranteed nacks)
        chunk_opts = sorted({
            c for c in (chunk // 2, chunk, chunk * 2)
            if 1 <= c <= max_seq and max_seq % c == 0})
        if not chunk_opts:
            chunk_opts = [chunk]
        # prefix-pool widths: 0 (off), current, and pool depths sized
        # to hold whole prompts (max_seq / page_size pages each). Only
        # enumerable when the worker reported its page geometry — an
        # old worker without page_size keeps its pool untouched.
        ppp = max(0, int(cfg.get("prefix_pool_pages", 0) or 0))
        pg = int(cfg.get("page_size", 0) or 0)
        if pg > 0:
            per_prompt = max(1, max_seq // pg)
            pool_opts = sorted({
                p for p in (0, ppp, per_prompt * 4, per_prompt * 8)
                if 0 <= p <= 4096})
        else:
            pool_opts = [ppp]
        # speculative draft lengths: 0 (off), current, and the small
        # powers of two the verify step's compute trade favors — but
        # ONLY under the serve_spec_enabled master switch (disabled =
        # the current K alone, so a hand-set K is left untouched but
        # never enumerated away from)
        sk = max(0, int(cfg.get("spec_draft_len", 0) or 0))
        if bool(getattr(get_context(), "serve_spec_enabled", True)):
            spec_opts = sorted({0, sk, 2, 4, 8})
        else:
            spec_opts = [sk]
        return [{"serve_slots": s, "prefill_chunk": c,
                 "prefix_pool_pages": p, "spec_draft_len": k}
                for s in slot_opts for c in chunk_opts
                for p in pool_opts for k in spec_opts]

    def _serve_spec(self, cfg: Optional[Dict] = None):
        """A ModelSpec for the decode pricing. The KV-pool geometry
        (layers, kv heads, head_dim) comes from the SERVE WORKER's
        report when it carries it — the worker knows its KVCacheSpec
        exactly, and guessing heads from hidden_size would price a
        GQA model's pool up to heads/kv_heads too large and memory-
        reject slot widths that actually fit. ModelInfo fills the
        param count (the weight-read term); a placeholder otherwise
        (the RANKING is shape-driven either way)."""
        from dlrover_tpu.parallel.planner import ModelSpec

        cfg = cfg or getattr(self, "_serving", None) or {}
        info = self._model_info
        kv_heads = int(cfg.get("kv_heads") or 0)
        head_dim = int(cfg.get("head_dim") or 0)
        layers = int(cfg.get("num_layers") or 0)
        if kv_heads and head_dim:
            # encode the reported geometry exactly: hidden/heads is
            # how the planner re-derives head_dim, so set heads such
            # that hidden_size // heads == head_dim
            hidden = (int(info.hidden_size) if info is not None
                      and info.hidden_size else kv_heads * head_dim)
            heads = max(1, hidden // head_dim)
            return ModelSpec(
                param_count=int(info.num_params) if info is not None
                and info.num_params > 0 else 1e6,
                num_layers=max(1, layers or (
                    int(info.num_layers) if info is not None else 1)),
                hidden_size=hidden,
                seq_len=max(1, int(getattr(info, "seq_len", 0) or 128)
                            if info is not None else 128),
                global_batch=1,
                num_heads=heads, kv_heads=kv_heads,
            )
        if info is not None and info.num_params > 0:
            heads = max(1, (info.hidden_size or 64) // 64)
            return ModelSpec(
                param_count=int(info.num_params),
                num_layers=max(1, int(info.num_layers or 1)),
                hidden_size=max(1, int(info.hidden_size or 64)),
                seq_len=max(1, int(info.seq_len or 128)),
                global_batch=1,
                num_heads=heads, kv_heads=heads,
            )
        return ModelSpec(param_count=1e6, num_layers=2, hidden_size=64,
                         seq_len=128, global_batch=1, num_heads=4,
                         kv_heads=2)

    def _serve_budget_bytes(self) -> float:
        budget = float(getattr(
            get_context(), "device_hbm_budget_bytes", 0.0) or 0.0)
        if budget > 0:
            return budget
        return float(self._device.hbm_bytes) * 0.8

    def replan_serving(self, trigger: str) -> Optional[Decision]:
        """Enumerate and price ``serve_slots`` / ``prefill_chunk``
        under live traffic — the serving mirror of ``replan``: the
        planner's decode term (KV-read bytes, the memory-bound regime)
        prices candidates, the HBM feasibility gate (PR 8) refuses
        pools that cannot fit, hysteresis/cooldown/blacklist guard the
        churn, and winners publish through the SAME ParallelConfig
        broadcast the training knobs ride."""
        if not self._enabled:
            return None
        from dlrover_tpu.parallel.planner import (
            estimate_decode,
            serve_cache_bytes,
            serve_prefix_pool_bytes,
        )

        with self._lock:
            cfg = getattr(self, "_serving", None)
            if cfg is None:
                return None
            with trace_scope(current_trace_id() or None) as tid:
                self._c_replans.inc()
                spec = self._serve_spec(cfg)
                world = max(1, cfg["world"])
                kvp = cfg["kv_precision"]
                max_seq = max(1, cfg["max_seq"])
                budget = self._serve_budget_bytes()
                page_size = int(cfg.get("page_size", 0) or 0)
                # the hit-rate driving the prefill discount: observed
                # (from the worker's ledger) once traffic has spoken,
                # else the operator's prior — 0 without either, which
                # prices every pool width as pure cost and keeps the
                # knob off until there is evidence it pays
                observed_hr = float(cfg.get("prefix_hit_rate", -1.0))
                hit_rate = (observed_hr if observed_hr >= 0.0
                            else float(getattr(
                                get_context(),
                                "serve_prefix_expected_hit_rate",
                                0.0) or 0.0))
                # the acceptance rate has NO prior knob: with no
                # observation every K>0 prices at exactly 1.0x inside
                # estimate_decode, so spec stays off until traffic
                # proves drafts land — evidence-only, stricter than
                # the prefix discount (a wrong prior here would cost
                # real compute every step, not just idle pool HBM)
                accept_rate = float(cfg.get("spec_accept_rate", -1.0))
                current = estimate_decode(
                    spec, world, cfg["serve_slots"],
                    cfg["prefill_chunk"], max_seq, kvp,
                    device=self._device,
                    prefix_pool_pages=max(
                        0, cfg.get("prefix_pool_pages", 0)),
                    page_size=page_size or 16,
                    prefix_hit_rate=hit_rate,
                    spec_draft_len=max(
                        0, cfg.get("spec_draft_len", 0)),
                    spec_accept_rate=accept_rate)
                priced, memory_rejected = [], []
                for cand in self._serve_candidates(cfg):
                    pool = serve_cache_bytes(
                        spec, cand["serve_slots"], max_seq, kvp)
                    # the prefix pool is sharded only on heads and
                    # charged UNDIVIDED per device (conservative: the
                    # page dim is replicated) on top of this node's
                    # slot-pool share
                    prefix_bytes = serve_prefix_pool_bytes(
                        spec, cand["prefix_pool_pages"],
                        page_size or 16, kvp)
                    per_device = pool / world + prefix_bytes
                    if per_device > budget:
                        memory_rejected.append({
                            "serve_slots": cand["serve_slots"],
                            "prefix_pool_pages":
                                cand["prefix_pool_pages"],
                            "predicted_hbm_bytes": per_device,
                            "budget_bytes": budget,
                        })
                        self._c_memory_rejected.inc()
                        continue
                    est = estimate_decode(
                        spec, world, cand["serve_slots"],
                        cand["prefill_chunk"], max_seq, kvp,
                        device=self._device,
                        prefix_pool_pages=cand["prefix_pool_pages"],
                        page_size=page_size or 16,
                        prefix_hit_rate=hit_rate,
                        spec_draft_len=cand["spec_draft_len"],
                        spec_accept_rate=accept_rate)
                    key = (f"serve|slots={cand['serve_slots']}"
                           f"|pc={cand['prefill_chunk']}"
                           f"|ppp={cand['prefix_pool_pages']}"
                           f"|spec={cand['spec_draft_len']}")
                    if key in self._failed_keys:
                        continue
                    priced.append({
                        **cand, "key": key,
                        "tokens_per_s": est["tokens_per_s"],
                        "step_s": est["step_s"],
                        "speedup": (est["tokens_per_s"]
                                    / max(current["tokens_per_s"],
                                          1e-12)),
                    })
                memory_rejected.sort(
                    key=lambda r: -r["predicted_hbm_bytes"])
                decision = Decision(
                    trigger=f"serve:{trigger}", trace_id=tid,
                    ts=time.time(), current=dict(cfg),
                    current_predicted_s=current["step_s"],
                    memory_rejected=memory_rejected[:8],
                )
                if not priced:
                    self._reject(decision, "serve:no_feasible_candidate")
                    self._decisions.append(decision)
                    return decision
                def churn(c):
                    # equal throughput prefers the fewest knob flips
                    # (the training ranking's churn tie-break): a tied
                    # prefill_chunk change must not ride along free
                    return ((c["serve_slots"] != cfg["serve_slots"])
                            + (c["prefill_chunk"]
                               != cfg["prefill_chunk"])
                            + (c["prefix_pool_pages"]
                               != cfg.get("prefix_pool_pages", 0))
                            + (c["spec_draft_len"]
                               != cfg.get("spec_draft_len", 0)))

                priced.sort(key=lambda c: (-c["tokens_per_s"],
                                           churn(c), c["serve_slots"]))
                decision.candidates = [
                    {k: (round(v, 6) if isinstance(v, float) else v)
                     for k, v in c.items()} for c in priced[:8]]
                best = priced[0]
                decision.predicted_speedup = round(best["speedup"], 3)
                unchanged = (
                    best["serve_slots"] == cfg["serve_slots"]
                    and best["prefill_chunk"] == cfg["prefill_chunk"]
                    and best["prefix_pool_pages"]
                    == cfg.get("prefix_pool_pages", 0)
                    and best["spec_draft_len"]
                    == cfg.get("spec_draft_len", 0))
                pending_training = (
                    self._pending is not None
                    and not getattr(self._pending, "serve_slots", 0)
                    and not getattr(self._pending,
                                    "serve_prefill_chunk", 0)
                    and getattr(self._pending,
                                "serve_prefix_pool_pages", -1) < 0
                    and getattr(self._pending,
                                "serve_spec_draft_len", -1) < 0)
                if unchanged:
                    self._reject(decision, "already_optimal")
                elif pending_training:
                    # ONE broadcast slot serves both planes today (the
                    # colocation split is ROADMAP item 3): publishing
                    # now would silently clobber an unconsumed TRAINING
                    # plan. Defer — the next serve-config report
                    # re-triggers this pass. (The trainer's plan hook
                    # symmetrically ignores serve-only plans, so the
                    # reverse clobber is an overwrite, not a bad ack.)
                    self._reject(decision, "pending_training_plan")
                elif best["speedup"] < self._min_speedup:
                    self._reject(
                        decision,
                        f"hysteresis:{best['speedup']:.2f}"
                        f"<{self._min_speedup:.2f}")
                elif not self._cooldown.check(best["key"]):
                    self._reject(
                        decision, "cooldown:%.0fs"
                        % self._cooldown.seconds_remaining(best["key"]))
                else:
                    self._choose_serving(decision, best, cfg)
                self._decisions.append(decision)
                return decision

    def _choose_serving(self, decision: Decision, best: Dict,
                        cfg: Dict) -> None:
        self._plan_seq += 1
        plan_id = f"plan-{self._plan_seq}"
        decision.outcome = "chosen"
        decision.plan_id = plan_id
        decision.chosen = dict(best)
        decision.chosen_key = best["key"]
        self._c_chosen.inc()
        published = comm.ParallelConfig(
            serve_slots=(best["serve_slots"]
                         if best["serve_slots"] != cfg["serve_slots"]
                         else 0),
            serve_prefill_chunk=(
                best["prefill_chunk"]
                if best["prefill_chunk"] != cfg["prefill_chunk"]
                else 0),
            serve_prefix_pool_pages=(
                best["prefix_pool_pages"]
                if best["prefix_pool_pages"]
                != cfg.get("prefix_pool_pages", 0)
                else -1),
            serve_spec_draft_len=(
                best["spec_draft_len"]
                if best["spec_draft_len"]
                != cfg.get("spec_draft_len", 0)
                else -1),
            plan_id=plan_id,
            trace_id=decision.trace_id,
            predicted_speedup=round(best["speedup"], 3),
            prewarm=True,
        )
        self._pending = published
        emit_event(
            EventKind.OPTIMIZER_PLAN_CHOSEN,
            plan_id=plan_id, trigger=decision.trigger,
            predicted_speedup=round(best["speedup"], 3),
            knob_serve_slots=best["serve_slots"],
            knob_serve_prefill_chunk=best["prefill_chunk"],
            knob_serve_prefix_pool_pages=best["prefix_pool_pages"],
            knob_serve_spec_draft_len=best["spec_draft_len"],
        )
        logger.info("replan(%s): chose %s (predicted %.2fx tokens/s, "
                    "plan %s)", decision.trigger, best["key"],
                    best["speedup"], plan_id)
        if self._publish is not None:
            self._publish(published)

    def on_verdict(self, node_id: int, verdict: str) -> None:
        """Straggler-detector listener: a flagged verdict (and its
        recovery) is a re-plan trigger. Recovery replans IMMEDIATELY —
        the degraded-config workaround should not outlive the incident
        by a scaler period (ISSUE 7 satellite; the auto-scaler gets the
        same kick through its own listener)."""
        if verdict == "healthy":
            self.replan(f"recovered:{node_id}")
        else:
            self.replan(f"{verdict}:{node_id}")

    def set_durability_evidence_fn(
            self, fn: Callable[[int], Optional[Dict]]) -> None:
        """Wire the readiness auditor's per-node verdict lookup in."""
        self._durability_evidence_fn = fn

    def _durability_evidence(self, trigger: str) -> Optional[Dict]:
        """The at-risk owner's audit evidence for a ``durability:N``
        trigger (None for every other trigger, or when the verdict
        already cleared by the time the pass runs)."""
        if (self._durability_evidence_fn is None
                or not trigger.startswith("durability:")):
            return None
        try:
            node_id = int(trigger.split(":", 1)[1])
        except (TypeError, ValueError):
            return None
        try:
            return self._durability_evidence_fn(node_id)
        except Exception:  # noqa: BLE001 — evidence is garnish, the
            # replan itself must still run
            logger.exception("durability evidence lookup failed")
            return None

    # -- calibration ---------------------------------------------------------

    def _ensure_calibrator(self) -> Optional[CostCalibrator]:
        if self._running is None:
            return None
        if self._calibrator is not None:
            return self._calibrator
        info = self._model_info
        batch = self._running.global_batch or 8
        if info is not None and info.num_params > 0:
            moe_kwargs = {}
            if int(getattr(info, "num_experts", 0) or 0) > 0:
                # the worker runs an MoE model: the spec must carry the
                # expert shape (and the RUNNING dispatch mode) or the
                # dispatch-comm terms price as zero and the
                # dispatch_chunks family collapses into ties
                moe_kwargs = dict(
                    num_experts=int(info.num_experts),
                    moe_top_k=max(1, int(
                        getattr(info, "moe_top_k", 1) or 1)),
                    moe_dispatch=(self._running.moe_dispatch
                                  or "grouped_ep"),
                    moe_dispatch_chunks=max(
                        1, self._running.dispatch_chunks),
                    moe_precision=(self._running.moe_precision
                                   or "bf16"),
                )
                if float(getattr(info, "ffn_mult", 0.0) or 0.0) > 0:
                    moe_kwargs["ffn_mult"] = float(info.ffn_mult)
            spec = ModelSpec(
                param_count=int(info.num_params),
                num_layers=max(1, int(info.num_layers or 2)),
                hidden_size=max(8, int(info.hidden_size or 256)),
                seq_len=max(1, int(info.seq_len or 128)),
                global_batch=batch,
                fsdp_precision=(self._running.fsdp_precision or "bf16"),
                **moe_kwargs,
            )
        else:
            # no ModelInfo reported: a minimal placeholder spec — the
            # corrections anchor absolute scale, the analytic model only
            # contributes relative structure across knobs
            spec = ModelSpec(
                param_count=1_000_000, num_layers=2, hidden_size=256,
                seq_len=128, global_batch=batch,
            )
        ctx = get_context()
        self._calibrator = CostCalibrator(
            model=spec, device=self._device,
            # operator HBM budget for the memory-feasibility gate
            # (0 = the device spec's capacity under the fit headroom)
            hbm_budget_bytes=float(
                getattr(ctx, "device_hbm_budget_bytes", 0.0)),
        )
        return self._calibrator

    def _measured_anchor(self) -> Dict[str, Optional[float]]:
        """The step/dispatch p50 the JOB actually paces at: the MAX
        over fresh nodes. A synchronous SPMD job runs at its slowest
        member, so a degraded-but-alive straggler IS the job's step
        time — the HSDP-at-100k position (PAPERS.md 2602.00277): treat
        it as a config-search input, not just a restart trigger. Each
        node's windowed p50 already rides out single-sample noise."""
        now = time.time()
        steps: List[float] = []
        dispatches: List[float] = []
        for nid in self._store.node_ids():
            s = self._store.latest(nid)
            if s is None or now - s.ts > _CALIBRATION_FRESHNESS_S:
                continue
            if s.step_p50 is not None:
                steps.append(s.step_p50)
            if s.dispatch_p50 is not None:
                dispatches.append(s.dispatch_p50)
        return {
            "step_p50": max(steps) if steps else None,
            "dispatch_p50": max(dispatches) if dispatches else None,
        }

    def calibrate(self) -> Optional[Dict]:
        """One predicted-vs-observed fit for the current config;
        returns the correction factors (None without a running config
        or any fresh measurement)."""
        with self._lock:
            cal = self._ensure_calibrator()
            if cal is None:
                return None
            measured = self._measured_anchor()
            if (measured["step_p50"] is None
                    and measured["dispatch_p50"] is None):
                return None
            run = self._running
            corr = cal.observe(
                run.mesh, run.steps_per_call,
                measured_step_p50=measured["step_p50"],
                measured_dispatch_p50=measured["dispatch_p50"],
            )
            self._c_calibrations.inc()
            out = corr.to_dict()
            emit_event(
                EventKind.OPTIMIZER_CALIBRATED,
                measured_step_p50_s=measured["step_p50"],
                measured_dispatch_p50_s=measured["dispatch_p50"],
                steps_per_call=run.steps_per_call,
                **{f"factor_{k}": v for k, v in out.items()
                   if k in ("compute", "comm", "dispatch")},
            )
            return out

    # -- candidate enumeration / pricing -------------------------------------

    def _knob_options(self, run: RunningConfig):
        meshes: List[MeshPlan] = [run.mesh]
        if self._mesh_candidates and run.world > 1:
            seen = {mesh_axes_key(run.mesh)}
            for m in candidate_plans(run.world):
                k = mesh_axes_key(m)
                if k not in seen:
                    seen.add(k)
                    meshes.append(m)
        ks = sorted({run.steps_per_call, *STEPS_PER_CALL_OPTIONS})
        windows = [run.train_window]
        if run.train_window == 0:
            windows.append(4)  # enable dispatch/compute overlap
        cal = self._ensure_calibrator()
        # the moe-dispatch family stays PARKED at the running mode even
        # now that ModelInfo carries num_experts: a dispatch-mode
        # change rebuilds the MODEL, and the worker's plan hook ignores
        # the knob while acking the rest of the plan — enumerating it
        # would let a fiction win the ranking and mark itself applied.
        # (MOE_DISPATCH_OPTIONS waits on a model-rebuild apply path.)
        moes = [run.moe_dispatch]
        # the chunked-dispatch family: only live-appliable on the
        # dispatch the worker reports running (grouped_ep) — on every
        # other mode the knob is a no-op the worker would ack but the
        # program would ignore
        chunk_opts = [max(1, run.dispatch_chunks)]
        # the wire-precision family rides the same gate: a precision
        # the running dispatch would silently ignore must not compete
        precision_opts = [run.moe_precision or "bf16"]
        if (cal is not None and cal.model.num_experts > 0
                and run.moe_dispatch == "grouped_ep"):
            chunk_opts = sorted(
                {max(1, run.dispatch_chunks), *DISPATCH_CHUNKS_OPTIONS})
            precision_opts = sorted(
                {run.moe_precision or "bf16", *MOE_PRECISION_OPTIONS})
        # the dense-wire family: parked unless the worker REPORTS a
        # dense-wire precision (i.e. the trainer manages the knob and a
        # live apply exists); per-MESH gating — only factorizations
        # that actually pay fsdp gathers differentiate the options —
        # happens in _price_candidates, the chunks_for_moe pattern
        fsdp_opts = [run.fsdp_precision or "bf16"]
        if run.fsdp_precision:
            fsdp_opts = sorted(
                {run.fsdp_precision or "bf16", *FSDP_PRECISION_OPTIONS})
        return (meshes, ks, windows, moes, chunk_opts, precision_opts,
                fsdp_opts)

    def _price_candidates(self, run: RunningConfig
                          ) -> Tuple[List[CandidateScore], List[Dict]]:
        """Price every knob combination; returns (priced candidates,
        memory-rejected evidence). The memory-feasibility gate fires
        BEFORE pricing: a plan whose predicted peak HBM exceeds the
        device budget is recorded (once per mesh — the memory estimate
        is knob-invariant) instead of silently skipped, so the
        decision trail shows WHY a cheap-looking mesh never competed."""
        cal = self._ensure_calibrator()
        if cal is None:
            return [], []
        (meshes, ks, windows, moes, chunk_opts,
         precision_opts, fsdp_opts) = self._knob_options(run)
        out: List[CandidateScore] = []
        memory_rejected: List[Dict] = []
        mem_seen: set = set()
        for mesh in meshes:
            # the dense-wire family only differentiates meshes that pay
            # fsdp gathers; elsewhere it would add identical-priced rows
            fsdp_for_mesh = (
                fsdp_opts
                if max(1, mesh.axis_sizes().get("fsdp", 1)) > 1
                else [run.fsdp_precision or "bf16"]
            )
            for k in ks:
                for w in windows:
                    for moe in moes:
                        # the chunk family only differentiates the
                        # grouped_ep dispatch; pricing other modes at
                        # every C would add identical-priced rows
                        chunks_for_moe = (
                            chunk_opts if moe == "grouped_ep"
                            else [max(1, run.dispatch_chunks)]
                        )
                        precisions_for_moe = (
                            precision_opts if moe == "grouped_ep"
                            else [run.moe_precision or "bf16"]
                        )
                        combos = [
                            (ch, prec, fp)
                            for ch in chunks_for_moe
                            for prec in precisions_for_moe
                            for fp in fsdp_for_mesh
                        ]
                        for ch, prec, fp in combos:
                            try:
                                s = cal.price(
                                    mesh, steps_per_call=k,
                                    train_window=w,
                                    moe_dispatch=moe,
                                    dispatch_chunks=ch,
                                    moe_precision=prec,
                                    fsdp_precision=fp)
                            except MemoryInfeasibleError as e:
                                mkey = mesh_axes_key(mesh)
                                if mkey not in mem_seen:
                                    mem_seen.add(mkey)
                                    self._c_memory_rejected.inc()
                                    memory_rejected.append({
                                        "mesh": _mesh_dict(mesh),
                                        "predicted_hbm_bytes":
                                            round(e.memory_bytes),
                                        "budget_bytes": round(
                                            e.budget_bytes),
                                    })
                                break
                            except (ValueError, KeyError) as e:
                                logger.debug(
                                    "candidate %s unpriceable: %s",
                                    mesh, e)
                                break
                            out.append(CandidateScore(
                                mesh=mesh, steps_per_call=k,
                                train_window=w, moe_dispatch=moe,
                                dispatch_chunks=ch,
                                moe_precision=prec,
                                fsdp_precision=fp,
                                predicted_step_s=s,
                            ))
        # worst offender first: the trimmed decision evidence and the
        # PLAN_REJECTED event must name the true worst, not whichever
        # mesh enumeration happened to visit early
        memory_rejected.sort(key=lambda m: -m["predicted_hbm_bytes"])
        return out, memory_rejected

    def _input_bound_evidence(self) -> Optional[Dict]:
        """The input-bound judgement over the fresh node samples, on
        two legs: (a) peer-relative — the worst node's
        ``input_wait_frac`` at least ``BOUND_PEER_DELTA`` above the
        peer median (the straggler verdict's bound-label pattern,
        catching ONE starved node); (b) absolute — the cluster MEDIAN
        fraction at ``_INPUT_BOUND_ABS`` or above (uniform starvation
        from a shared slow source shows no peer excess at all).
        Returns the evidence dict when the job is input-bound, else
        None. A mesh/steps_per_call replan reshapes device work; it
        cannot make the host produce batches faster, so a program plan
        chosen while this holds is rejected as ``input_bound``."""
        if not self._input_bound_gate:
            return None
        now = time.time()
        fracs: Dict[int, float] = {}
        for nid in self._store.node_ids():
            s = self._store.latest(nid)
            if s is None or now - getattr(s, "ts", now) > \
                    _CALIBRATION_FRESHNESS_S:
                continue
            frac = getattr(s, "input_wait_frac", None)
            if frac is not None:
                fracs[int(nid)] = float(frac)
        if not fracs:
            return None
        worst = max(fracs, key=fracs.get)
        peers = [f for n, f in fracs.items() if n != worst]
        # "input_bound_node", not "node": the evidence rides emit_event
        # kwargs, where a "node" field would clobber the record's own
        # node-identity stamp
        if peers:
            peer_median = statistics.median(peers)
            if fracs[worst] - peer_median >= BOUND_PEER_DELTA:
                return {
                    "input_bound_node": worst,
                    "input_wait_frac": round(fracs[worst], 4),
                    "peer_median_input_wait_frac": round(peer_median, 4),
                }
        median = statistics.median(fracs.values())
        if median >= _INPUT_BOUND_ABS:
            return {
                "input_bound_node": worst,
                "input_wait_frac": round(fracs[worst], 4),
                "median_input_wait_frac": round(median, 4),
            }
        return None

    @staticmethod
    def _wants_program(c: CandidateScore, run: RunningConfig) -> bool:
        """Whether the candidate changes the COMPILED program (mesh,
        fused-step degree, or dispatch chunking) — the knobs whose
        apply pays a drain. A host-knob-only plan (train_window) stays
        appliable even on a data-starved job."""
        return (
            _mesh_dict(c.mesh) != _mesh_dict(run.mesh)
            or c.steps_per_call != run.steps_per_call
            or max(1, c.dispatch_chunks) != max(1, run.dispatch_chunks)
            or (c.moe_precision or "bf16")
            != (run.moe_precision or "bf16")
            or (c.fsdp_precision or "bf16")
            != (run.fsdp_precision or "bf16")
        )

    @staticmethod
    def _churn(c: CandidateScore, run: RunningConfig) -> int:
        """Tie-break distance from the current knobs: equal-price plans
        must prefer NOT changing anything."""
        cur = _mesh_dict(run.mesh)
        cand = _mesh_dict(c.mesh)
        return (
            int(cand != cur)
            + int(c.steps_per_call != run.steps_per_call)
            + int(c.train_window != run.train_window)
            + int((c.moe_dispatch or "") != (run.moe_dispatch or ""))
            + int(max(1, c.dispatch_chunks)
                  != max(1, run.dispatch_chunks))
            + int((c.moe_precision or "bf16")
                  != (run.moe_precision or "bf16"))
            + int((c.fsdp_precision or "bf16")
                  != (run.fsdp_precision or "bf16"))
        )

    # -- the re-plan pass ----------------------------------------------------

    def replan(self, trigger: str) -> Optional[Decision]:
        """Calibrate, enumerate, price, decide, publish. Returns the
        recorded Decision (None when disabled or nothing is known yet
        about the running job)."""
        if not self._enabled:
            return None
        with self._lock:
            run = self._running
            if run is None:
                logger.info("replan(%s) skipped: no running config "
                            "reported yet", trigger)
                return None
            # adopt the ambient incident id when one is open (the
            # verdict listener fires inside the verdict's trace scope,
            # an RPC-triggered replan inside the caller's) so the
            # DIAG_* verdict and the OPTIMIZER_* decision trail merge
            # into ONE incident in `tpurun trace`
            with trace_scope(current_trace_id() or None) as tid:
                return self._replan_locked(trigger, run, tid)

    def _replan_locked(self, trigger: str, run: RunningConfig,
                       tid: str) -> Optional[Decision]:
        self._c_replans.inc()
        durability_ev = self._durability_evidence(trigger)
        corrections = self.calibrate() or (
            self._calibrator.corrections.to_dict()
            if self._calibrator is not None else {}
        )
        cal = self._ensure_calibrator()
        if cal is None:
            return None
        # require_fit=False: the current config is OBSERVABLY running,
        # whatever the analytic memory model thinks of it
        current_s = cal.price(
            run.mesh, steps_per_call=run.steps_per_call,
            train_window=run.train_window,
            moe_dispatch=run.moe_dispatch,
            dispatch_chunks=run.dispatch_chunks,
            moe_precision=run.moe_precision,
            fsdp_precision=run.fsdp_precision, require_fit=False,
        )
        priced, memory_rejected = self._price_candidates(run)
        candidates = [c for c in priced
                      if c.key not in self._failed_keys]
        if memory_rejected:
            # the memory-feasibility gate fired: one PLAN_REJECTED
            # record per pass carrying the evidence (which meshes, how
            # far over budget) — visible in `tpurun plan` and
            # `tpurun attribution`. Decision evidence keeps the 8
            # worst; the event carries the full count.
            worst = memory_rejected[0]
            total_rejected = len(memory_rejected)
            memory_rejected = memory_rejected[:8]
            emit_event(
                EventKind.OPTIMIZER_PLAN_REJECTED,
                trigger=trigger,
                reason="memory_infeasible",
                rejected_meshes=total_rejected,
                mesh=worst["mesh"],
                predicted_hbm_mb=round(
                    worst["predicted_hbm_bytes"] / 1e6, 1),
                budget_mb=round(worst["budget_bytes"] / 1e6, 1),
            )
            logger.info(
                "replan(%s): %d candidate mesh(es) memory-infeasible "
                "(worst %s needs %.1f MB > %.1f MB budget)",
                trigger, total_rejected, worst["mesh"],
                worst["predicted_hbm_bytes"] / 1e6,
                worst["budget_bytes"] / 1e6,
            )
        if not candidates:
            if memory_rejected:
                # every candidate died at the gate: the pass itself is
                # a recorded rejection, not a silent no-op
                decision = Decision(
                    trigger=trigger, trace_id=tid, ts=time.time(),
                    current=run.to_dict(),
                    current_predicted_s=current_s,
                    corrections=corrections,
                    memory_rejected=memory_rejected,
                    durability=durability_ev,
                )
                self._reject(decision, "memory_infeasible:all")
                self._decisions.append(decision)
                return decision
            return None
        for c in candidates:
            c.speedup = current_s / max(c.predicted_step_s, 1e-12)
        candidates.sort(
            key=lambda c: (c.predicted_step_s, self._churn(c, run)))
        table = [c.to_dict() for c in candidates[:_TABLE_ROWS]]
        decision = Decision(
            trigger=trigger, trace_id=tid, ts=time.time(),
            current=run.to_dict(), current_predicted_s=current_s,
            candidates=table, corrections=corrections,
            memory_rejected=memory_rejected,
            durability=durability_ev,
        )
        best = candidates[0]
        decision.predicted_speedup = best.speedup
        emit_event(
            EventKind.OPTIMIZER_REPLAN, trigger=trigger,
            candidates_priced=len(candidates),
            current_predicted_s=round(current_s, 6),
            best_predicted_s=round(best.predicted_step_s, 6),
            best_speedup=round(best.speedup, 3),
        )
        input_ev = self._input_bound_evidence()
        if input_ev is not None and (
            self._churn(best, run) == 0
            or self._wants_program(best, run)
        ):
            # the INPUT-BOUND gate, checked before every other verdict
            # on the pass: a starved input pipeline poisons the
            # calibration in BOTH directions (the anchor p50 includes
            # host wait the cost model books as device work), so
            # "already optimal" and "8x from K=8" are equally fictional
            # — and a mesh/steps_per_call drain cannot make the host
            # produce batches faster. The pass is rejected with the
            # starvation evidence instead; only a host-knob-only plan
            # (train_window) passes through. The gate does not consume
            # the cooldown, so the same plan is immediately proposable
            # once the starvation clears.
            decision.input_bound = dict(input_ev)
            self._reject(decision, "input_bound", **input_ev)
        elif self._churn(best, run) == 0:
            self._reject(decision, "already_optimal")
        elif best.speedup < self._min_speedup:
            self._reject(
                decision,
                f"hysteresis:{best.speedup:.2f}<{self._min_speedup:.2f}",
            )
        elif not self._cooldown.check(best.key):
            self._reject(
                decision,
                "cooldown:%.0fs" % self._cooldown.seconds_remaining(
                    best.key),
            )
        else:
            self._choose(decision, best)
        self._decisions.append(decision)
        return decision

    def _reject(self, decision: Decision, reason: str,
                **evidence) -> None:
        decision.outcome = "rejected"
        decision.reason = reason
        self._c_rejected.inc()
        emit_event(
            EventKind.OPTIMIZER_PLAN_REJECTED,
            trigger=decision.trigger, reason=reason,
            predicted_speedup=round(decision.predicted_speedup, 3),
            **evidence,
        )
        logger.info("replan(%s): no plan published (%s)",
                    decision.trigger, reason)

    def _choose(self, decision: Decision, best: CandidateScore) -> None:
        self._plan_seq += 1
        plan_id = f"plan-{self._plan_seq}"
        decision.outcome = "chosen"
        decision.plan_id = plan_id
        decision.chosen = best.to_dict()
        decision.chosen_key = best.key
        self._c_chosen.inc()
        # UNCHANGED knobs are published as their "leave it alone"
        # sentinels (None / -1 / 0 / ""), so the worker can tell a
        # host-knob-only plan from a compiled-program change (the
        # multi-host guard keys off exactly that)
        cur = decision.current
        mesh_changed = _mesh_dict(best.mesh) != cur.get("mesh")
        cfg = comm.ParallelConfig(
            mesh_shape=_mesh_dict(best.mesh) if mesh_changed else None,
            train_window=(best.train_window
                          if best.train_window != cur.get("train_window")
                          else -1),
            steps_per_call=(
                best.steps_per_call
                if best.steps_per_call != cur.get("steps_per_call")
                else 0),
            moe_dispatch=(best.moe_dispatch
                          if (best.moe_dispatch or "")
                          != (cur.get("moe_dispatch") or "") else ""),
            dispatch_chunks=(
                best.dispatch_chunks
                if max(1, best.dispatch_chunks)
                != max(1, cur.get("dispatch_chunks") or 1) else 0),
            moe_precision=(
                best.moe_precision
                if (best.moe_precision or "bf16")
                != (cur.get("moe_precision") or "bf16") else ""),
            fsdp_precision=(
                best.fsdp_precision
                if (best.fsdp_precision or "bf16")
                != (cur.get("fsdp_precision") or "bf16") else ""),
            plan_id=plan_id,
            trace_id=decision.trace_id,
            predicted_speedup=round(best.speedup, 3),
            prewarm=True,
        )
        self._pending = cfg
        emit_event(
            EventKind.OPTIMIZER_PLAN_CHOSEN,
            plan_id=plan_id, trigger=decision.trigger,
            predicted_speedup=round(best.speedup, 3),
            predicted_step_s=round(best.predicted_step_s, 6),
            **{f"knob_{k}": v for k, v in best.to_dict().items()
               if k in ("steps_per_call", "train_window",
                        "moe_dispatch", "dispatch_chunks",
                        "moe_precision", "fsdp_precision")},
            mesh=_mesh_dict(best.mesh),
        )
        logger.info(
            "replan(%s): chose %s (predicted %.2fx, plan %s)",
            decision.trigger, best.key, best.speedup, plan_id,
        )
        if self._publish is not None:
            self._publish(cfg)

    # -- queries -------------------------------------------------------------

    def exposed_comm_view(self) -> Optional[Dict]:
        """Predicted vs measured exposed-comm fraction for the RUNNING
        config, side by side — the operator's check that the overlap
        the planner paid for actually materialized. Predicted comes
        from the overlap-aware ``estimate`` breakdown at the running
        knobs; measured is the median of the fresh nodes'
        ``exposed_comm_frac`` gauges (PR 8's attribution plane — an
        UPPER bound, so measured modestly above predicted is healthy;
        measured near the C=1 serial prediction means the overlap never
        happened). None when nothing is running yet."""
        import dataclasses as _dc

        from dlrover_tpu.parallel.planner import estimate

        with self._lock:
            cal = self._ensure_calibrator()
            run = self._running
            if cal is None or run is None:
                return None
            model = cal.model
            if run.moe_dispatch and run.moe_dispatch != model.moe_dispatch:
                model = _dc.replace(model, moe_dispatch=run.moe_dispatch)
            if max(1, run.dispatch_chunks) != model.moe_dispatch_chunks:
                model = _dc.replace(
                    model,
                    moe_dispatch_chunks=max(1, run.dispatch_chunks))
            if (run.moe_precision or "bf16") != model.moe_precision:
                model = _dc.replace(
                    model, moe_precision=run.moe_precision or "bf16")
            if (run.fsdp_precision or "bf16") != model.fsdp_precision:
                model = _dc.replace(
                    model, fsdp_precision=run.fsdp_precision or "bf16")
            score = estimate(run.mesh, model, self._device,
                             steps_per_call=run.steps_per_call)
            predicted = score.breakdown.get("exposed_comm_frac")
            now = time.time()
            fracs: List[float] = []
            for nid in self._store.node_ids():
                s = self._store.latest(nid)
                if s is None or now - getattr(s, "ts", now) > \
                        _CALIBRATION_FRESHNESS_S:
                    continue
                f = getattr(s, "exposed_comm_frac", None)
                if f is not None:
                    fracs.append(float(f))
        return {
            "predicted": (round(float(predicted), 4)
                          if predicted is not None else None),
            "measured": (round(statistics.median(fracs), 4)
                         if fracs else None),
            "nodes_measured": len(fracs),
            "dispatch_chunks": max(1, run.dispatch_chunks),
        }

    def pending_plan(self) -> Optional[comm.ParallelConfig]:
        with self._lock:
            return self._pending

    def decisions(self, limit: int = 0) -> List[Dict]:
        with self._lock:
            out = [d.to_dict() for d in self._decisions]
        return out[-limit:] if limit else out

    def memory_rejections(self, limit: int = 0) -> List[Dict]:
        """Every memory-feasibility rejection in the retained decision
        trail, newest last — the ``tpurun attribution`` evidence of
        which candidate plans the devices could not hold."""
        with self._lock:
            out = [
                {"ts": d.ts, "trigger": d.trigger,
                 "trace_id": d.trace_id, **m}
                for d in self._decisions for m in d.memory_rejected
            ]
        return out[-limit:] if limit else out

    def to_report(self, limit: int = 0) -> Dict:
        """The ``tpurun plan --addr`` payload."""
        with self._lock:
            running = self._running.to_dict() if self._running else None
            serving = dict(self._serving) if self._serving else None
            corr = (self._calibrator.corrections.to_dict()
                    if self._calibrator is not None else None)
            pending = self._pending
        return {
            "enabled": self._enabled,
            "running": running,
            "serving": serving,
            "corrections": corr,
            "min_speedup": self._min_speedup,
            "cooldown_secs": self._cooldown.cooldown_secs,
            "exposed_comm": self.exposed_comm_view(),
            "pending_plan": {
                "plan_id": pending.plan_id,
                "mesh": dict(pending.mesh_shape or {}),
                "train_window": pending.train_window,
                "steps_per_call": pending.steps_per_call,
                "moe_dispatch": pending.moe_dispatch,
                "dispatch_chunks": getattr(
                    pending, "dispatch_chunks", 0),
                "moe_precision": getattr(
                    pending, "moe_precision", ""),
                "predicted_speedup": pending.predicted_speedup,
                "trace_id": pending.trace_id,
            } if pending is not None else None,
            "decisions": self.decisions(limit),
        }


# -- forensic decision trail (tpurun plan --events) ---------------------------

_OPTIMIZER_KINDS = (
    EventKind.OPTIMIZER_REPLAN,
    EventKind.OPTIMIZER_CALIBRATED,
    EventKind.OPTIMIZER_PLAN_CHOSEN,
    EventKind.OPTIMIZER_PLAN_REJECTED,
    EventKind.OPTIMIZER_APPLY_BEGIN,
    EventKind.OPTIMIZER_APPLY_DONE,
    EventKind.OPTIMIZER_APPLIED,
)


def decision_trail_from_events(records: List[Dict]) -> Dict:
    """Reconstruct the decision trail from a (merged, multi-process)
    event timeline: master-side decisions joined to worker-side applies
    by plan id / trace id — the forensic ``tpurun plan --events`` view.
    """
    trail = [r for r in records if r.get("kind") in _OPTIMIZER_KINDS]
    plans: Dict[str, Dict] = {}
    for rec in trail:
        kind = rec.get("kind")
        pid = rec.get("plan_id", "")
        if not pid:
            continue
        p = plans.setdefault(pid, {"plan_id": pid})
        if kind == EventKind.OPTIMIZER_PLAN_CHOSEN:
            p.update(
                chosen_ts=rec.get("ts"),
                trigger=rec.get("trigger", ""),
                trace_id=rec.get("trace_id", ""),
                predicted_speedup=rec.get("predicted_speedup"),
                mesh=rec.get("mesh"),
                steps_per_call=rec.get("knob_steps_per_call"),
                train_window=rec.get("knob_train_window"),
            )
        elif kind == EventKind.OPTIMIZER_APPLY_BEGIN:
            p["apply_begin_ts"] = rec.get("ts")
        elif kind == EventKind.OPTIMIZER_APPLY_DONE:
            p["apply_done_ts"] = rec.get("ts")
            p["apply_seconds"] = rec.get("seconds")
            p["recompiled"] = rec.get("recompiled")
            if rec.get("error_code"):
                p["apply_error"] = rec.get("error_code")
        elif kind == EventKind.OPTIMIZER_APPLIED:
            p["realized_speedup"] = rec.get("realized_speedup")
            p["applied_predicted_speedup"] = rec.get("predicted_speedup")
    return {
        "events": len(trail),
        "plans": [plans[k] for k in sorted(
            plans, key=lambda k: plans[k].get("chosen_ts") or 0.0)],
        "trail": trail,
    }
