"""Runtime optimization loop: telemetry → planner → live reshard.

The fourth DLRover pillar (automatic resource optimization, PAPER.md
§pillars) closed as a master-side control loop:

  * ``calibration``        — fit the analytic planner's cost terms to
    the MEASURED per-node runtime series (predicted-vs-observed
    correction factors per term), so candidate pricing reflects the
    job actually running, not the datasheet.
  * ``runtime_optimizer``  — consume the node series and diagnosis
    verdicts, enumerate and price candidate configs (mesh shape,
    ``train_window``, ``steps_per_call``, MoE dispatch) through the
    calibrated cost model, and publish winning plans to workers —
    applied WITHOUT a restart through the live-reshard/retune path.

The remote case fronts ``brain/`` (``optimize_mode="cluster"``) for
cross-job initial plans; this loop owns the within-job re-planning.
"""

from dlrover_tpu.master.optimizer.calibration import (  # noqa: F401
    CostCalibrator,
    TermCorrections,
    calibrated_step_time,
)
from dlrover_tpu.master.optimizer.runtime_optimizer import (  # noqa: F401
    CandidateScore,
    Decision,
    RunningConfig,
    RuntimeOptimizer,
    decision_trail_from_events,
)
