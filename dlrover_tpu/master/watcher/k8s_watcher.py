"""Watcher over k8s pods and ScalePlan CRs.

Role parity: ``dlrover/python/master/watcher/k8s_watcher.py``
(``PodWatcher`` — list/watch pods → NodeEvents, with exit-reason parsing:
OOMKilled / Killed / fatal exit codes; ``K8sScalePlanWatcher`` — pick up
user-submitted ScalePlan CRs for manual scaling).

The watcher consumes plain pod dicts so tests feed canned API objects
through a fake client, as the reference's tests do.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from dlrover_tpu.common.constants import (
    NodeEventType,
    NodeExitReason,
    NodeStatus,
    NodeType,
)
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.node import Node, NodeGroupResource, NodeResource
from dlrover_tpu.master.scaler.base_scaler import ScalePlan
from dlrover_tpu.master.watcher.base_watcher import NodeEvent, NodeWatcher

logger = get_logger("watcher.k8s")

_PHASE_TO_STATUS = {
    "Pending": NodeStatus.PENDING,
    "Running": NodeStatus.RUNNING,
    "Succeeded": NodeStatus.SUCCEEDED,
    "Failed": NodeStatus.FAILED,
    "Unknown": NodeStatus.UNKNOWN,
}

# Exit codes the reference treats as unrecoverable user-code errors
# (k8s_watcher.py:49 _get_pod_exit_reason).
_FATAL_EXIT_CODES = {1, 2, 126, 127, 128}


def parse_memory_mb(quantity) -> int:
    """Parse a k8s memory quantity ('8192Mi', '2Gi', '512M', bytes-int)
    to MiB. Delegates to the ONE shared parser
    (``scheduler.kubernetes.parse_memory_mib``) — per the k8s grammar a
    plain number is BYTES.

    Semantics break vs pre-0.1 revisions, which returned a plain
    numeric input verbatim as MiB: callers that passed raw MiB ints
    now get ~0 and must send '<n>Mi' (or bytes) instead."""
    from dlrover_tpu.scheduler.kubernetes import parse_memory_mib

    return parse_memory_mib(quantity)


def _dig(d: Dict, *keys, default=None):
    cur: Any = d
    for k in keys:
        if not isinstance(cur, dict) or k not in cur:
            return default
        cur = cur[k]
    return cur


def get_pod_exit_reason(pod: Dict[str, Any]) -> str:
    """Classify why a pod's main container died."""
    statuses = _dig(pod, "status", "containerStatuses", default=[]) or []
    for cs in statuses:
        term = _dig(cs, "state", "terminated") or _dig(cs, "lastState", "terminated")
        if not term:
            continue
        reason = term.get("reason", "")
        code = term.get("exitCode", 0)
        if reason == "OOMKilled":
            return NodeExitReason.OOM
        if reason == "Killed" or code in (-9, 137):
            return NodeExitReason.KILLED
        if code in _FATAL_EXIT_CODES:
            return NodeExitReason.FATAL_ERROR
        if code != 0:
            return NodeExitReason.UNKNOWN_ERROR
    return ""


def pod_to_node(pod: Dict[str, Any]) -> Optional[Node]:
    labels = _dig(pod, "metadata", "labels", default={}) or {}
    node_type = labels.get("replica-type")
    if node_type is None:
        return None
    rank = int(labels.get("rank-index", 0))
    node_id = int(_dig(pod, "metadata", "annotations", "node-id", default=rank))
    phase = _dig(pod, "status", "phase", default="Unknown")
    node = Node(
        node_type=node_type,
        node_id=node_id,
        rank_index=rank,
        name=_dig(pod, "metadata", "name", default=f"{node_type}-{node_id}"),
        status=_PHASE_TO_STATUS.get(phase, NodeStatus.UNKNOWN),
    )
    node.exit_reason = get_pod_exit_reason(pod)
    return node


class PodWatcher(NodeWatcher):
    """List/watch pods of one job via a (real or fake) k8s client."""

    def __init__(self, job_name: str, client, poll_secs: float = 1.0):
        self._job_name = job_name
        self._client = client
        self._poll_secs = poll_secs
        self._stopped = threading.Event()
        self._selector = f"elasticjob-name={job_name}"

    def list(self) -> List[Node]:
        pods = self._client.list_pods(label_selector=self._selector) or []
        nodes = [pod_to_node(p) for p in pods]
        return [n for n in nodes if n is not None]

    def watch(self) -> Iterator[NodeEvent]:
        # Poll-based list+diff: equivalent behavior to the reference's
        # list+watch without holding a server-side watch connection.
        last: Dict[str, Node] = {}
        while not self._stopped.is_set():
            seen = set()
            for node in self.list():
                seen.add(node.name)
                prev = last.get(node.name)
                if prev is None:
                    last[node.name] = node
                    yield NodeEvent(NodeEventType.ADDED, node)
                elif prev.status != node.status:
                    last[node.name] = node
                    yield NodeEvent(NodeEventType.MODIFIED, node)
            for name in list(last):
                if name not in seen:
                    gone = last.pop(name)
                    gone.status = NodeStatus.DELETED
                    yield NodeEvent(NodeEventType.DELETED, gone)
            time.sleep(self._poll_secs)

    def stop(self):
        self._stopped.set()


class ScalePlanWatcher:
    """Watch user-submitted ScalePlan CRs → manual ScalePlans.

    Role parity: ``K8sScalePlanWatcher`` — a human (or external controller)
    writes a ScalePlan CR; the master applies it like any optimizer plan.
    """

    def __init__(self, job_name: str, client, poll_secs: float = 2.0):
        self._job_name = job_name
        self._client = client
        self._poll_secs = poll_secs
        self._stopped = threading.Event()
        self._seen: set = set()

    def watch(self) -> Iterator[ScalePlan]:
        while not self._stopped.is_set():
            crs = self._client.list_scale_plans(self._job_name) or []
            for cr in crs:
                name = _dig(cr, "metadata", "name", default="")
                if not name or name in self._seen:
                    continue
                self._seen.add(name)
                yield self.to_scale_plan(cr)
            time.sleep(self._poll_secs)

    @staticmethod
    def to_scale_plan(cr: Dict[str, Any]) -> ScalePlan:
        plan = ScalePlan()
        specs = _dig(cr, "spec", "replicaResourceSpecs", default={}) or {}
        for node_type, spec in specs.items():
            res = spec.get("resource", {})
            plan.node_group_resources[node_type] = NodeGroupResource(
                count=int(spec.get("replicas", 0)),
                node_resource=NodeResource(
                    cpu=float(res.get("cpu", 0) or 0),
                    memory=parse_memory_mb(res.get("memory", 0)),
                ),
            )
        plan.ps_addrs = _dig(cr, "spec", "psHosts", default=[]) or []
        return plan

    def stop(self):
        self._stopped.set()
