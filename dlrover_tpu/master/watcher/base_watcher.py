"""NodeEvent and the NodeWatcher interface.

Role parity: ``dlrover/python/master/watcher/base_watcher.py`` — watchers
turn platform state changes (pod phases, subprocess exits) into a stream of
``NodeEvent``s the job manager's monitor thread consumes.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from dataclasses import dataclass
from typing import Iterator, List

from dlrover_tpu.common.node import Node


@dataclass
class NodeEvent:
    event_type: str  # NodeEventType.{ADDED,MODIFIED,DELETED}
    node: Node


class NodeWatcher(ABC):
    @abstractmethod
    def watch(self) -> Iterator[NodeEvent]:
        """Yield events until the watcher is stopped."""

    @abstractmethod
    def list(self) -> List[Node]:
        """Snapshot of all currently-known nodes."""

    def stop(self):
        ...
