"""Ray actor watcher.

Role parity: ``dlrover/python/master/watcher/ray_watcher.py:80``
(``ActorWatcher`` — polls actor states and emits NodeEvents). Ray has no
list+watch API like k8s, so watching is polling with a state cache:
transitions produce MODIFIED/DELETED events, new names produce ADDED.
"""

from __future__ import annotations

import time
from typing import Dict, Iterator, List

from dlrover_tpu.common.constants import NodeEventType, NodeStatus
from dlrover_tpu.common.node import Node
from dlrover_tpu.master.watcher.base_watcher import NodeEvent, NodeWatcher
from dlrover_tpu.scheduler.ray import parse_type_id_from_actor_name

_STATE_MAP = {
    "DEPENDENCIES_UNREADY": NodeStatus.PENDING,
    "PENDING_CREATION": NodeStatus.PENDING,
    "ALIVE": NodeStatus.RUNNING,
    "RESTARTING": NodeStatus.PENDING,
    "DEAD": NodeStatus.FAILED,
}


def actor_state_to_status(state: str) -> str:
    return _STATE_MAP.get(state, NodeStatus.UNKNOWN)


class ActorWatcher(NodeWatcher):
    def __init__(self, job_name: str, ray_client, poll_interval: float = 2.0):
        self._job_name = job_name
        self._client = ray_client
        self._interval = poll_interval
        self._stopped = False
        self._known: Dict[str, str] = {}  # name -> last status

    def list(self) -> List[Node]:
        nodes = []
        for name, state in sorted(self._client.list_actors().items()):
            node_type, node_id = parse_type_id_from_actor_name(name)
            nodes.append(Node(
                node_type=node_type, node_id=node_id, name=name,
                status=actor_state_to_status(state),
            ))
        return nodes

    def watch(self) -> Iterator[NodeEvent]:
        while not self._stopped:
            current = {n.name: n for n in self.list()}
            for name, node in current.items():
                last = self._known.get(name)
                if last is None:
                    yield NodeEvent(NodeEventType.ADDED, node)
                elif last != node.status:
                    yield NodeEvent(NodeEventType.MODIFIED, node)
                self._known[name] = node.status
            for name in list(self._known):
                if name not in current:
                    node_type, node_id = parse_type_id_from_actor_name(name)
                    del self._known[name]
                    yield NodeEvent(
                        NodeEventType.DELETED,
                        Node(node_type=node_type, node_id=node_id,
                             name=name, status=NodeStatus.DELETED),
                    )
            time.sleep(self._interval)

    def stop(self):
        self._stopped = True
