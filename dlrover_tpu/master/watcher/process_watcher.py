"""Watcher over the local subprocess backend.

Role parity: the ``PodWatcher`` role on the local platform — polls the
``LocalProcessBackend`` process table and emits ADDED/MODIFIED/DELETED
``NodeEvent``s on state changes.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterator, List

from dlrover_tpu.common.constants import NodeEventType, NodeExitReason, NodeStatus
from dlrover_tpu.common.node import Node
from dlrover_tpu.master.watcher.base_watcher import NodeEvent, NodeWatcher
from dlrover_tpu.scheduler.local import LocalProcessBackend


class LocalProcessWatcher(NodeWatcher):
    def __init__(self, backend: LocalProcessBackend, poll_secs: float = 0.2):
        self._backend = backend
        self._poll_secs = poll_secs
        self._stopped = threading.Event()

    def _to_node(self, proc) -> Node:
        node = Node(
            node_type=proc.node_type,
            node_id=proc.node_id,
            rank_index=proc.rank_index,
            name=proc.name,
            status=proc.status(),
        )
        rc = proc.exit_code()
        if proc.exit_reason:
            node.exit_reason = proc.exit_reason
        elif rc is not None and rc != 0:
            # SIGKILL from the OS OOM-killer surfaces as -9.
            node.exit_reason = (
                NodeExitReason.OOM if rc == -9 else NodeExitReason.UNKNOWN_ERROR
            )
        return node

    def list(self) -> List[Node]:
        return [self._to_node(p) for p in self._backend.list_processes()]

    def watch(self) -> Iterator[NodeEvent]:
        last_status: Dict[str, str] = {}
        while not self._stopped.is_set():
            seen = set()
            for proc in self._backend.list_processes():
                node = self._to_node(proc)
                seen.add(node.name)
                prev = last_status.get(node.name)
                if prev is None:
                    last_status[node.name] = node.status
                    yield NodeEvent(NodeEventType.ADDED, node)
                elif prev != node.status:
                    last_status[node.name] = node.status
                    yield NodeEvent(NodeEventType.MODIFIED, node)
            for name in list(last_status):
                if name not in seen:
                    del last_status[name]
            time.sleep(self._poll_secs)

    def stop(self):
        self._stopped.set()
