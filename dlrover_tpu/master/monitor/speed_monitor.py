"""Training speed tracking on the master.

Role parity: ``dlrover/python/master/monitor/speed_monitor.py:43-193`` —
global-step reports become a steps/s series; the auto-scaler asks it whether
the current worker membership has run long enough to be judged
(``worker_adjustment_finished``).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, List, Optional, Set, Tuple

from dlrover_tpu.common.config import get_context
from dlrover_tpu.telemetry import get_registry, names as tm


class SpeedMonitor:
    def __init__(self):
        self._lock = threading.Lock()
        ctx = get_context()
        self._max_records = ctx.train_speed_record_num
        reg = get_registry()
        self._g_step = reg.gauge(
            tm.MASTER_GLOBAL_STEP,
            help="newest global step reported by any worker")
        self._g_speed = reg.gauge(
            tm.MASTER_TRAIN_SPEED,
            help="steps/s over the master's report window")
        # (timestamp, global_step) samples
        self._global_step_records: Deque[Tuple[float, int]] = deque(
            maxlen=self._max_records
        )
        self._global_step = 0
        self._init_time = time.time()
        self._start_training_time: Optional[float] = None
        self._sample_count = 0
        self._completed_records = 0
        self._running_workers: Set[int] = set()
        self._worker_adjust_time = time.time()
        self._max_worker_num = 0

    # -- step reports -------------------------------------------------------

    def collect_global_step(self, step: int, timestamp: Optional[float] = None):
        with self._lock:
            if self._start_training_time is None:
                self._start_training_time = time.time()
            ts = timestamp or time.time()
            self._global_step = max(self._global_step, step)
            self._global_step_records.append((ts, step))
            self._sample_count += 1
            self._g_step.set(self._global_step)
        self._g_speed.set(self.running_speed())

    def mark_task_completed(self, record_count: int):
        with self._lock:
            self._completed_records += record_count

    @property
    def completed_global_step(self) -> int:
        return self._global_step

    @property
    def sample_count(self) -> int:
        return self._sample_count

    def running_speed(self) -> float:
        """steps/s over the recorded window (0 if not enough samples)."""
        with self._lock:
            if len(self._global_step_records) < 2:
                return 0.0
            (t0, s0) = self._global_step_records[0]
            (t1, s1) = self._global_step_records[-1]
            if t1 <= t0:
                return 0.0
            return (s1 - s0) / (t1 - t0)

    # -- worker membership --------------------------------------------------

    def add_running_worker(self, node_id: int):
        with self._lock:
            self._running_workers.add(node_id)
            self._worker_adjust_time = time.time()
            self._max_worker_num = max(
                self._max_worker_num, len(self._running_workers)
            )

    def remove_running_worker(self, node_id: int):
        with self._lock:
            self._running_workers.discard(node_id)
            self._worker_adjust_time = time.time()

    @property
    def running_workers(self) -> Set[int]:
        return set(self._running_workers)

    def worker_adjustment_finished(self) -> bool:
        """Membership stable long enough for a fair speed judgement."""
        ctx = get_context()
        with self._lock:
            return (
                time.time() - self._worker_adjust_time
                >= ctx.seconds_for_stable_worker_count
            )

    def reset_running_speed_monitor(self):
        with self._lock:
            self._global_step_records.clear()
