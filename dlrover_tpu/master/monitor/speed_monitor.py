"""Training speed tracking on the master.

Role parity: ``dlrover/python/master/monitor/speed_monitor.py:43-193`` —
global-step reports become a steps/s series; the auto-scaler asks it whether
the current worker membership has run long enough to be judged
(``worker_adjustment_finished``).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional, Set, Tuple

from dlrover_tpu.common.config import get_context
from dlrover_tpu.telemetry import get_registry, names as tm


class SpeedMonitor:
    def __init__(self):
        self._lock = threading.Lock()
        ctx = get_context()
        self._max_records = ctx.train_speed_record_num
        reg = get_registry()
        self._g_step = reg.gauge(
            tm.MASTER_GLOBAL_STEP,
            help="newest global step reported by any worker")
        self._g_speed = reg.gauge(
            tm.MASTER_TRAIN_SPEED,
            help="steps/s over the master's report window")
        # (timestamp, global_step) samples
        self._global_step_records: Deque[Tuple[float, int]] = deque(
            maxlen=self._max_records
        )
        self._global_step = 0
        self._init_time = time.time()
        self._start_training_time: Optional[float] = None
        self._sample_count = 0
        self._completed_records = 0
        self._running_workers: Set[int] = set()
        self._worker_adjust_time = time.time()
        self._max_worker_num = 0
        # per-node diagnosis verdicts pushed by the straggler detector
        # (node_id -> "healthy" | "straggler" | "hung"); the auto-scaler
        # reads these before judging speed
        self._node_verdicts: Dict[int, str] = {}

    # -- step reports -------------------------------------------------------

    def collect_global_step(self, step: int, timestamp: Optional[float] = None):
        # gauge updates stay INSIDE the lock: a second reporter racing
        # this method could otherwise publish a stale speed over a newer
        # one (the old code computed running_speed() after release)
        with self._lock:
            if self._start_training_time is None:
                self._start_training_time = time.time()
            ts = timestamp or time.time()
            self._global_step = max(self._global_step, step)
            self._global_step_records.append((ts, step))
            self._sample_count += 1
            self._g_step.set(self._global_step)
            self._g_speed.set(self._running_speed_locked())

    def reset_step(self, step: int, timestamp: Optional[float] = None):
        """The truth REWOUND (non-finite rollback restored an older
        checkpoint, or a live reshard resumed from a snapshot): the
        monotone ``max()`` would keep the gauge and speed series
        stale-high forever. Reset to the reported step and restart the
        speed window from here."""
        with self._lock:
            ts = timestamp or time.time()
            self._global_step = int(step)
            self._global_step_records.clear()
            self._global_step_records.append((ts, int(step)))
            self._g_step.set(self._global_step)
            self._g_speed.set(0.0)

    def mark_task_completed(self, record_count: int):
        with self._lock:
            self._completed_records += record_count

    @property
    def completed_global_step(self) -> int:
        with self._lock:
            return self._global_step

    @property
    def sample_count(self) -> int:
        with self._lock:
            return self._sample_count

    def running_speed(self) -> float:
        """steps/s over the recorded window (0 if not enough samples)."""
        with self._lock:
            return self._running_speed_locked()

    def _running_speed_locked(self) -> float:
        if len(self._global_step_records) < 2:
            return 0.0
        (t0, s0) = self._global_step_records[0]
        (t1, s1) = self._global_step_records[-1]
        if t1 <= t0:
            return 0.0
        return (s1 - s0) / (t1 - t0)

    # -- worker membership --------------------------------------------------

    def add_running_worker(self, node_id: int):
        with self._lock:
            self._running_workers.add(node_id)
            self._worker_adjust_time = time.time()
            self._max_worker_num = max(
                self._max_worker_num, len(self._running_workers)
            )

    def remove_running_worker(self, node_id: int):
        with self._lock:
            self._running_workers.discard(node_id)
            self._worker_adjust_time = time.time()

    @property
    def running_workers(self) -> Set[int]:
        return set(self._running_workers)

    def worker_adjustment_finished(self) -> bool:
        """Membership stable long enough for a fair speed judgement."""
        ctx = get_context()
        with self._lock:
            return (
                time.time() - self._worker_adjust_time
                >= ctx.seconds_for_stable_worker_count
            )

    def reset_running_speed_monitor(self):
        with self._lock:
            self._global_step_records.clear()

    # -- per-node diagnosis verdicts ----------------------------------------

    def update_node_verdict(self, node_id: int, verdict: str,
                            evidence: Optional[Dict] = None):
        """Fed by the straggler detector; ``evidence`` is accepted for
        interface parity but the monitor stores only the verdict (the
        detector keeps the full evidence)."""
        with self._lock:
            if verdict == "healthy":
                self._node_verdicts.pop(node_id, None)
            else:
                self._node_verdicts[node_id] = verdict

    @property
    def straggler_nodes(self) -> List[int]:
        with self._lock:
            return sorted(n for n, v in self._node_verdicts.items()
                          if v == "straggler")

    @property
    def unhealthy_nodes(self) -> List[int]:
        with self._lock:
            return sorted(self._node_verdicts)
