"""Master-side continuous durability audit: the recovery-readiness
plane.

PR 15's replication plane is only observable AFTER a failure — nothing
answers "if node N dies right now, do we survive, via which rung, and
how long does it take?". This auditor sweeps the ``ReplicaDirectory``'s
admitted assignments against the stores' live ``inventory()`` facts on
the master's stats tick and keeps three judgements current:

* **coverage** — every owner's regions committed on at least the
  admitted k live peer holders (a holder counts only with a committed,
  crc-checked manifest — the store refuses anything else);
* **staleness** — the newest fully-held replica step may trail the
  owner's reported step by at most ``readiness_stale_factor`` × the
  master-computed cadence;
* **budget** — the admitted k reached the requested k, and no holder
  sits over its declared DRAM budget.

A node whose owner regions fail any dimension gets a ``DIAG_DURABILITY``
verdict (failure-class, error-coded, evidence attached, fresh incident
trace id) delivered through the same listener machinery as the
straggler detector — so the RuntimeOptimizer's ``on_verdict`` fires a
``durability:<node>`` re-plan under the verdict's trace scope, and the
whole verdict → replan → clear arc shares one incident id. The cluster
posture edge (any node at risk ⇄ none) emits ``READINESS_DEGRADED`` /
``READINESS_RESTORED`` — the mttr ``durability_at_risk`` scenario.

Each sweep also prices the **blast radius** of every node: the best
survivable rung of the recovery ladder (live_reshard / peer_rebuild /
storage_restore / init) with a predicted MTTR from the calibrated
``RungPricer`` (drain + fetch-bytes/link-bw + device_put — the
BENCH_r14 decomposition, EMA-corrected against every realized
incident). The table feeds the ``{node=,rung=}`` gauges, the
``ReadinessRequest`` RPC behind ``tpurun readiness``, and — attached to
recovery plans — the worker's priced rung choice in
``trainer/failover`` / ``ElasticTrainer.prepare``.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from dlrover_tpu.common.config import get_context
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.master.monitor.straggler import (
    VERDICT_HEALTHY,
    NodeVerdict,
)
from dlrover_tpu.telemetry import (
    EventKind,
    emit_event,
    get_registry,
    names as tm,
)
from dlrover_tpu.telemetry.events import default_events_path, read_events
from dlrover_tpu.telemetry.mttr import derive_incidents
from dlrover_tpu.telemetry.readiness import (
    RUNG_INDEX,
    RUNG_INIT,
    RUNG_LADDER,
    RUNG_LIVE_RESHARD,
    RUNG_PEER_REBUILD,
    RUNG_STORAGE_RESTORE,
    RungPricer,
    cheapest_viable_rung,
)
from dlrover_tpu.telemetry.trace_context import new_trace_id, trace_scope

logger = get_logger("master.readiness")

VERDICT_DURABILITY = "durability"


def _default_inventory_fn(endpoints: List[Dict[str, Any]]
                          ) -> Dict[str, Dict[str, Any]]:
    """The live sweep: one ReplicaInfoRequest per reachable store,
    over the same cached retrying channels the fetch side uses."""
    from dlrover_tpu.checkpoint.replication import (
        _collect_inventories,
        replica_channel_factory,
    )

    factory, close = replica_channel_factory()
    try:
        return _collect_inventories(endpoints, factory)
    finally:
        close()


class ReadinessAuditor:
    """Continuous durability audit + per-node blast-radius pricing.

    Ticked from the master's stats loop (``sweep()`` self-paces by
    ``readiness_sweep_secs``); ``sweep(force=True)`` runs regardless —
    the RPC handler's refresh path and tests. Verdict listeners follow
    the StragglerDetector contract exactly: ``fn(node_id, verdict)``
    called OUTSIDE the auditor lock, under the verdict's trace scope.
    """

    def __init__(
        self,
        directory,
        cadence_fn: Callable[[], int],
        replicas_fn: Callable[[], int],
        inventory_fn: Optional[Callable] = None,
        sweep_secs: Optional[float] = None,
        stale_factor: Optional[float] = None,
    ):
        ctx = get_context()
        self._directory = directory
        self._cadence_fn = cadence_fn
        self._replicas_fn = replicas_fn
        self._inventory_fn = inventory_fn or _default_inventory_fn
        self._sweep_secs = float(
            sweep_secs if sweep_secs is not None
            else getattr(ctx, "readiness_sweep_secs", 30.0))
        self._stale_factor = float(
            stale_factor if stale_factor is not None
            else getattr(ctx, "readiness_stale_factor", 2.0))
        self.pricer = RungPricer()
        self._lock = threading.Lock()
        self._last_sweep = 0.0
        self._sweeps = 0
        self._verdicts: Dict[int, NodeVerdict] = {}
        self._listeners: List = []
        self._pending_notices: List[Tuple[int, str, str]] = []
        # cluster posture: the trace id of the open READINESS_DEGRADED
        # edge (None = ready)
        self._degraded_tid: Optional[str] = None
        # per-node snapshot of the last sweep (report() serves it)
        self._nodes: Dict[int, Dict[str, Any]] = {}
        self._admitted: Dict[str, Any] = {}
        # calibration bookkeeping: push cycles already folded in
        # (node -> registration ts) and incidents already EMA'd
        self._seen_push: Dict[int, float] = {}
        self._seen_incidents: Set[Tuple[str, float]] = set()
        self._events_mtime = 0.0
        # gauge label sets currently exported, for retraction
        self._exported: Dict[str, Set[Tuple[Tuple[str, str], ...]]] = {}
        reg = get_registry()
        self._c_sweeps = reg.counter(
            tm.READINESS_SWEEPS, help="durability audit sweeps completed")
        self._h_sweep = reg.histogram(
            tm.READINESS_SWEEP_TIME, help="wall seconds of one sweep")
        self._c_flags = reg.counter(
            tm.DIAG_DURABILITY_FLAGS,
            help="durability verdicts confirmed by the audit")
        self._c_recoveries = reg.counter(
            tm.DIAG_RECOVERIES, help="verdicts cleared by recovery")

    # -- listener machinery (the StragglerDetector contract) -----------------

    def add_verdict_listener(self, fn) -> None:
        self._listeners.append(fn)

    def _notify(self, node_id: int, verdict: str, trace_id: str) -> None:
        self._pending_notices.append((node_id, verdict, trace_id))

    def _drain_notices(self) -> None:
        with self._lock:
            pending, self._pending_notices = self._pending_notices, []
        for node_id, verdict, tid in pending:
            with trace_scope(tid or None):
                for fn in self._listeners:
                    try:
                        fn(node_id, verdict)
                    except Exception:  # noqa: BLE001 — a listener must
                        # not kill the audit tick
                        logger.exception(
                            "readiness verdict listener failed for node "
                            "%d (%s)", node_id, verdict)

    # -- calibration feeds ---------------------------------------------------

    def _calibrate_from_directory(self, nodes: Dict[str, Dict]) -> None:
        """Fold each node's newest push-cycle stats in exactly once
        (keyed by registration ts — re-reading the same cycle would
        over-weight it in the EMA)."""
        for key, info in nodes.items():
            try:
                node_id = int(key)
            except (TypeError, ValueError):
                continue
            ts = float(info.get("ts", 0.0))
            if ts <= self._seen_push.get(node_id, 0.0):
                continue
            pb = float(info.get("push_bytes", 0.0) or 0.0)
            ps = float(info.get("push_seconds", 0.0) or 0.0)
            if pb > 0 and ps > 0:
                self.pricer.observe_push(pb, ps)
                self._seen_push[node_id] = ts

    def _calibrate_from_events(self) -> None:
        """EMA-correct rung prices against every newly CLOSED incident
        in the shared timeline, and feed the device_put leg from
        stamped rebuild events. Gated on the file's mtime so a quiet
        timeline costs one stat call per sweep."""
        path = default_events_path()
        if not path or not os.path.exists(path):
            return
        try:
            mtime = os.path.getmtime(path)
        except OSError:
            return
        if mtime <= self._events_mtime:
            return
        self._events_mtime = mtime
        try:
            events = read_events(path)
        except Exception:  # noqa: BLE001 — a torn timeline read only
            # delays calibration to the next sweep
            logger.exception("readiness calibration read failed")
            return
        from dlrover_tpu.telemetry.readiness import SCENARIO_RUNG

        for inc in derive_incidents(events):
            realized = inc.get("recovery_seconds")
            rung = SCENARIO_RUNG.get(inc.get("scenario", ""))
            started = inc.get("started_ts")
            if realized is None or rung is None or started is None:
                continue
            key = (inc["scenario"], round(float(started), 6))
            if key in self._seen_incidents:
                continue
            self._seen_incidents.add(key)
            self.pricer.observe_realized(rung, float(realized))
        for rec in events:
            if rec.get("kind") != EventKind.PEER_REBUILD_DONE:
                continue
            try:
                put_s = float(rec.get("put_seconds", 0.0) or 0.0)
                put_b = float(rec.get("bytes_from_peers", 0.0) or 0.0)
                pred = float(rec.get("predicted_mttr_s", 0.0) or 0.0)
                realz = float(rec.get("realized_mttr_s", 0.0) or 0.0)
            except (TypeError, ValueError):
                continue
            key = ("put", round(float(rec.get("ts", 0.0)), 6))
            if key in self._seen_incidents:
                continue
            self._seen_incidents.add(key)
            if put_s > 0 and put_b > 0:
                self.pricer.observe_put(put_b, put_s)
            # the worker stamped its own predicted-vs-realized pair:
            # the exact signal the multiplicative correction EMA wants
            if pred > 0 and realz > 0:
                self.pricer.observe_realized(
                    RUNG_PEER_REBUILD, realz, predicted_s=pred)

    # -- gauge export (absent-not-zero + retract) ----------------------------

    def _export(self, reg, name: str, help_: str,
                values: Dict[Tuple[Tuple[str, str], ...], float]) -> None:
        """Set one gauge family's series to exactly ``values`` —
        departed label sets are RETRACTED, never left at a stale
        number."""
        prev = self._exported.get(name, set())
        for labels in prev - set(values):
            reg.remove(name, labels=dict(labels))
        for labels, value in values.items():
            reg.gauge(name, help=help_, labels=dict(labels)).set(value)
        self._exported[name] = set(values)

    def _export_gauges(self, reg, admitted: Dict,
                       per_node: Dict[int, Dict]) -> None:
        def node_label(n) -> Tuple[Tuple[str, str], ...]:
            return (("node", str(n)),)

        self._export(
            reg, tm.REPLICA_HOLDER_LOAD_MB,
            "assigned peer-replica load per holder (MB)",
            {node_label(n): round(v, 3)
             for n, v in (admitted.get("load") or {}).items()})
        self._export(
            reg, tm.REPLICA_HOLDER_HEADROOM_MB,
            "holder DRAM budget minus assigned load (MB; absent when "
            "the holder is uncapped)",
            {node_label(n): round(v, 3)
             for n, v in (admitted.get("headroom_mb") or {}).items()})
        # plan-wide scalars: exported only while the plane is on
        # (requested > 0) — absent-not-zero
        scalars: Dict[Tuple[Tuple[str, str], ...], float] = {}
        if int(admitted.get("requested", 0)) > 0:
            scalars[()] = float(admitted.get("replicas", 0))
        self._export(reg, tm.REPLICA_ASSIGNED_K,
                     "admitted replica count k", scalars)
        degraded: Dict[Tuple[Tuple[str, str], ...], float] = {}
        if int(admitted.get("requested", 0)) > 0:
            degraded[()] = float(
                int(admitted["requested"]) - int(admitted["replicas"]))
        self._export(reg, tm.REPLICA_DEGRADED_K,
                     "requested minus admitted replica count", degraded)
        self._export(
            reg, tm.READINESS_COVERAGE,
            "1 = owner regions on >= k live committed holders",
            {node_label(n): 1.0 if d["coverage_ok"] else 0.0
             for n, d in per_node.items() if d.get("owner")})
        self._export(
            reg, tm.READINESS_STALENESS,
            "steps the newest fully-held replica group trails the owner",
            {node_label(n): float(d["staleness_steps"])
             for n, d in per_node.items()
             if d.get("owner") and d.get("staleness_steps") is not None})
        self._export(
            reg, tm.READINESS_BEST_RUNG,
            "best survivable rung index (0=live_reshard..3=init)",
            {node_label(n): float(RUNG_INDEX[d["best_rung"]])
             for n, d in per_node.items() if d.get("best_rung")})
        self._export(
            reg, tm.READINESS_PREDICTED_MTTR,
            "predicted MTTR of rung {rung=} for node {node=} (seconds)",
            {(("node", str(n)), ("rung", rung)): s
             for n, d in per_node.items()
             for rung, s in (d.get("predicted_mttr") or {}).items()})

    # -- the sweep -----------------------------------------------------------

    def sweep(self, now: Optional[float] = None,
              force: bool = False) -> Optional[Dict[str, Any]]:
        """One audit pass. Self-paced unless forced; returns the sweep
        summary, or None when the interval gate skipped it."""
        now = time.time() if now is None else now
        with self._lock:
            if not force and (
                self._sweep_secs <= 0
                or now - self._last_sweep < self._sweep_secs
            ):
                return None
            self._last_sweep = now
        t0 = time.monotonic()
        requested = int(self._replicas_fn())
        report = self._directory.to_report()
        nodes = report.get("nodes", {})
        failed = set(int(f) for f in report.get("failed", []))
        self._calibrate_from_directory(nodes)
        self._calibrate_from_events()
        admitted = self._directory.admitted_replicas(requested)
        k = int(admitted.get("replicas", 0))
        cadence = int(self._cadence_fn() or 0)
        allowed_steps = (
            int(self._stale_factor * cadence) if cadence > 0 else None)

        # live inventory sweep over every registered endpoint that is
        # not known-failed (a dead store simply doesn't answer — its
        # holdings drop out of coverage, which IS the detection)
        endpoints = [
            {"addr": info.get("addr", ""), "node_id": key}
            for key, info in nodes.items()
            if int(key) not in failed
        ]
        inventories = self._inventory_fn(endpoints) if endpoints else {}
        addr_to_node = {
            info.get("addr", ""): int(key) for key, info in nodes.items()
        }
        # owner -> {holder node -> newest committed step for that owner}
        held: Dict[int, Dict[int, int]] = {}
        for addr, inv in inventories.items():
            holder = addr_to_node.get(addr)
            if holder is None:
                continue
            for owner_key, entry in (inv or {}).items():
                try:
                    owner = int(owner_key)
                    steps = entry.get("steps") or {
                        str(entry["step"]): entry.get("manifest", {})}
                    newest = max(int(s) for s in steps)
                except (TypeError, ValueError, KeyError):
                    continue
                cur = held.setdefault(owner, {})
                cur[holder] = max(cur.get(holder, -1), newest)

        per_node: Dict[int, Dict[str, Any]] = {}
        at_risk: Dict[int, Tuple[str, Dict[str, Any]]] = {}
        for key, info in nodes.items():
            node_id = int(key)
            owner = float(info.get("snapshot_mb", 0.0)) > 0
            lender = float(info.get("budget_mb", 0.0)) >= 0
            region_bytes = float(info.get("snapshot_mb", 0.0)) * 1024 * 1024
            owner_step = int(info.get("step", -1))
            holders = dict(held.get(node_id, {}))
            peer_holders = {
                h: s for h, s in holders.items()
                if h != node_id and h not in failed
            }
            detail: Dict[str, Any] = {
                "owner": owner,
                "lender": lender,
                "failed": node_id in failed,
                "regions_mb": round(float(info.get("snapshot_mb", 0.0)), 3),
                "holders": sorted(peer_holders),
                "coverage_ok": True,
                "staleness_steps": None,
            }
            verdict: Optional[Tuple[str, Dict[str, Any]]] = None
            if owner and requested > 0 and node_id not in failed:
                required = max(1, k)
                # the newest step held by >= required peer holders: the
                # step a rebuild of THIS node would actually come back at
                steps_held = sorted(peer_holders.values(), reverse=True)
                covered_step = (
                    steps_held[required - 1]
                    if len(steps_held) >= required else None)
                if k == 0:
                    detail["coverage_ok"] = False
                    verdict = ("REPLICA_BUDGET", {
                        "requested": requested, "admitted": k,
                        "reason": admitted.get("reason", ""),
                    })
                elif covered_step is None:
                    detail["coverage_ok"] = False
                    verdict = ("DURABILITY_COVERAGE", {
                        "required": required,
                        "held": len(peer_holders),
                        "holders": sorted(peer_holders),
                        "requested": requested, "admitted": k,
                    })
                else:
                    staleness = max(0, owner_step - covered_step) \
                        if owner_step >= 0 else 0
                    detail["staleness_steps"] = staleness
                    detail["covered_step"] = covered_step
                    if (allowed_steps is not None
                            and staleness > allowed_steps):
                        verdict = ("REPLICA_STALE", {
                            "staleness_steps": staleness,
                            "allowed_steps": allowed_steps,
                            "owner_step": owner_step,
                            "covered_step": covered_step,
                        })
            # blast radius: the ladder this node's death is survivable
            # through, priced with drain=0 (a dead node drains nothing)
            viable = {
                # nothing of this node's training state is lost when it
                # owns no regions: the survivors absorb the membership
                # change in-process
                RUNG_LIVE_RESHARD: not owner,
                RUNG_PEER_REBUILD: owner and detail["coverage_ok"]
                and verdict is None and requested > 0,
                RUNG_STORAGE_RESTORE: True,
                RUNG_INIT: True,
            }
            table = self.pricer.table(region_bytes, drain_s=0.0)
            detail["predicted_mttr"] = table
            detail["best_rung"] = cheapest_viable_rung(table, viable)
            per_node[node_id] = detail
            if verdict is not None:
                at_risk[node_id] = verdict

        self._flag_and_clear(at_risk, per_node, now)
        reg = get_registry()
        self._export_gauges(reg, admitted, per_node)
        sweep_s = time.monotonic() - t0
        self._c_sweeps.inc()
        self._h_sweep.observe(sweep_s)
        with self._lock:
            self._sweeps += 1
            self._nodes = per_node
            self._admitted = {
                kk: vv for kk, vv in admitted.items()
                if kk != "assignments"
            }
            summary = self._report_locked(now)
        self._drain_notices()
        return summary

    def _flag_and_clear(self, at_risk: Dict[int, Tuple[str, Dict]],
                        per_node: Dict[int, Dict],
                        now: float) -> None:
        with self._lock:
            for node_id, (code, evidence) in at_risk.items():
                cur = self._verdicts.get(node_id)
                if cur is not None:
                    # refresh evidence; the incident stays open under
                    # its original trace id
                    cur.evidence = dict(evidence)
                    continue
                tid = new_trace_id()
                self._verdicts[node_id] = NodeVerdict(
                    node_id=node_id, verdict=VERDICT_DURABILITY,
                    since_ts=now, trace_id=tid, evidence=dict(evidence),
                )
                self._c_flags.inc()
                emit_event(EventKind.DIAG_DURABILITY, error_code=code,
                           trace_id=tid, diag_node=node_id, **evidence)
                logger.warning(
                    "node %d durability at risk [%s] %s: %s",
                    node_id, tid, code, evidence)
                self._notify(node_id, VERDICT_DURABILITY, tid)
            for node_id in [n for n in self._verdicts if n not in at_risk]:
                cur = self._verdicts.pop(node_id)
                self._c_recoveries.inc()
                emit_event(
                    EventKind.DIAG_RECOVERED, trace_id=cur.trace_id,
                    diag_node=node_id, was=VERDICT_DURABILITY,
                    flagged_seconds=round(now - cur.since_ts, 1))
                logger.info(
                    "node %d durability restored", node_id)
                self._notify(node_id, VERDICT_HEALTHY, cur.trace_id)
            # the cluster posture edge (the mttr durability_at_risk
            # scenario): first node at risk opens it, last clear
            # closes it under the SAME trace id
            if self._verdicts and self._degraded_tid is None:
                first = min(
                    self._verdicts.values(), key=lambda v: v.since_ts)
                self._degraded_tid = first.trace_id
                code = next(iter(at_risk.values()))[0] if at_risk \
                    else "DURABILITY_COVERAGE"
                emit_event(
                    EventKind.READINESS_DEGRADED, error_code=code,
                    trace_id=self._degraded_tid,
                    nodes=sorted(self._verdicts))
            elif not self._verdicts and self._degraded_tid is not None:
                emit_event(EventKind.READINESS_RESTORED,
                           trace_id=self._degraded_tid)
                self._degraded_tid = None
                emit_event(
                    EventKind.READINESS_SWEEP, posture="ready",
                    at_risk=0, nodes=len(per_node))
            if self._verdicts and self._degraded_tid is not None \
                    and at_risk:
                # posture-change summary (only while something changed
                # this sweep — a steady degraded state does not spam
                # the timeline)
                new_flags = [
                    n for n in at_risk
                    if self._verdicts.get(n) is not None
                    and self._verdicts[n].since_ts == now
                ]
                if new_flags:
                    emit_event(
                        EventKind.READINESS_SWEEP, posture="degraded",
                        at_risk=len(self._verdicts),
                        nodes=len(per_node))

    # -- views ---------------------------------------------------------------

    def _report_locked(self, now: float) -> Dict[str, Any]:
        return {
            "posture": ("degraded" if self._verdicts else "ready"),
            "at_risk": {
                str(n): v.to_dict() for n, v in self._verdicts.items()
            },
            "at_risk_nodes": sorted(str(n) for n in self._verdicts),
            "nodes": {
                str(n): dict(d) for n, d in self._nodes.items()
            },
            "admitted": dict(self._admitted),
            "calibration": self.pricer.to_dict(),
            "ladder": list(RUNG_LADDER),
            "sweeps": self._sweeps,
            "swept_ts": self._last_sweep,
            "ts": now,
        }

    def report(self) -> Dict[str, Any]:
        """The ReadinessRequest RPC payload (and `tpurun readiness
        --addr`'s live view)."""
        with self._lock:
            return self._report_locked(time.time())

    def verdicts(self) -> Dict[int, NodeVerdict]:
        with self._lock:
            return dict(self._verdicts)

    def predicted_mttr_table(self, node_id: int = -1) -> Dict[str, float]:
        """The per-rung predicted-MTTR table for ``node_id`` — what
        recovery plans attach so the worker's rung choice is the priced
        one. Calibration is refreshed from the directory's push stats
        and the event timeline first (both local reads, no RPC): a plan
        requested before the first periodic sweep still gets real
        prices, not priors."""
        try:
            nodes = self._directory.to_report().get("nodes", {})
        except Exception:  # noqa: BLE001 — price from current state
            logger.warning("directory report failed; pricing without node facts",
                           exc_info=True)
            nodes = {}
        self._calibrate_from_directory(nodes)
        self._calibrate_from_events()
        info = nodes.get(str(node_id)) or {}
        region_bytes = float(info.get("snapshot_mb", 0.0)) * 1024 * 1024
        return self.pricer.table(region_bytes, drain_s=0.0)
