"""Per-node runtime time series on the master.

The cluster-blindness fix: workers push node-tagged
``comm.NodeRuntimeReport`` snapshots of their PR 4 instruments
(cumulative step-time / dispatch / host-sync histogram counts, window
occupancy, RSS, device memory) through the ordinary report RPC; this
store diffs consecutive cumulative snapshots into per-window samples,
keeps a bounded series per node, and mirrors the latest sample into
labeled registry gauges so the master's ``/metrics`` exporter serves a
``{node="<id>"}`` series for every reporting node.

The straggler/hang detector (``straggler.py``) reads these series; the
``tpurun diagnose`` CLI and the ``DiagnosisRequest`` RPC read the
summaries.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import asdict, dataclass, field
from typing import Deque, Dict, List, Optional

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.telemetry import get_registry, names as tm
from dlrover_tpu.telemetry.metrics import percentile_from_counts

logger = get_logger("master.node_series")


@dataclass
class NodeSample:
    """One windowed sample for one node (the diff of two consecutive
    cumulative reports; the first report of a node is its own window).
    ``overflow`` marks a +Inf-bucket clamped p95 — the value is a LOWER
    bound, and verdicts must not treat it as a measurement."""

    ts: float
    step: int
    steps_total: float
    window_steps: float  # steps covered by THIS window
    step_p50: Optional[float] = None
    step_p95: Optional[float] = None
    dispatch_p50: Optional[float] = None
    host_sync_p50: Optional[float] = None
    window_occupancy: float = 0.0
    lagged_age: float = 0.0
    rss_mb: float = 0.0
    # None = not measured (CPU backends expose no memory stats; MFU /
    # exposed-comm arrive only once the worker captured an attribution
    # record) — the labeled gauges below export ONLY present values
    device_mem_mb: Optional[float] = None
    hbm_headroom_mb: Optional[float] = None
    mfu: Optional[float] = None
    exposed_comm_frac: Optional[float] = None
    flops_per_step: Optional[float] = None
    peak_hbm_mb: Optional[float] = None
    # data plane: the worker's input-wait fraction over its last
    # materialization window (None until the executor measured one)
    input_wait_frac: Optional[float] = None
    # serving tier (reports with node_type="serve"): step_p50/p95 hold
    # the windowed DECODE-step percentiles, steps_total the decode
    # steps; tokens_per_s is the windowed token rate (None on a node's
    # first report — no window to rate over)
    node_type: str = "worker"
    serve_tokens_total: Optional[float] = None
    serve_tokens_per_s: Optional[float] = None
    serve_queue_len: Optional[float] = None
    serve_slot_occupancy: Optional[float] = None
    serve_slots: Optional[float] = None
    # speculative decode: cumulative drafted/accepted totals and the
    # WINDOWED acceptance rate diffed from them (None until a window
    # with drafts — absent, never a fake 0)
    serve_spec_drafted_total: Optional[float] = None
    serve_spec_accepted_total: Optional[float] = None
    serve_spec_accept_rate: Optional[float] = None
    overflow: bool = False


@dataclass
class _NodeState:
    samples: Deque[NodeSample] = field(default_factory=deque)
    # previous CUMULATIVE counts per instrument, for the window diff
    prev_counts: Dict[str, List[int]] = field(default_factory=dict)
    prev_steps_total: float = 0.0
    node_type: str = "worker"


def _window_counts(prev: Optional[List[int]],
                   cur: Optional[List[int]]) -> Optional[List[int]]:
    if cur is None:
        return None
    if prev is None or len(prev) != len(cur):
        return list(cur)
    window = [c - p for c, p in zip(cur, prev)]
    if any(w < 0 for w in window):
        # the worker restarted (counters reset): its fresh cumulative
        # counts ARE the window
        return list(cur)
    return window


class NodeRuntimeStore:
    """Bounded per-node runtime series, fed by the servicer."""

    def __init__(self, max_samples: int = 256):
        self._lock = threading.Lock()
        self._max_samples = max_samples
        self._nodes: Dict[int, _NodeState] = {}
        reg = get_registry()
        self._c_ingested = reg.counter(
            tm.NODE_REPORTS_INGESTED,
            help="NodeRuntimeReport snapshots ingested by the master")

    def ingest(self, report, now: Optional[float] = None) -> NodeSample:
        """Diff a cumulative report into a windowed NodeSample, append
        it to the node's series, and refresh the labeled gauges.

        Samples are stamped with the MASTER's receive clock (``now``),
        not the worker's ``report.timestamp``: report ages drive the
        hang diagnosis and peer-freshness cuts, and a worker whose wall
        clock is skewed by minutes would otherwise forge (or mask) a
        DIAG_NODE_HANG on its very first report."""
        self._c_ingested.inc()
        ts = float(now if now is not None else time.time())
        bounds = list(report.bounds or [])
        with self._lock:
            state = self._nodes.setdefault(int(report.node_id),
                                           _NodeState())
            state.node_type = report.node_type or state.node_type
            windows = {}
            for key, cur in (
                ("step_time", report.step_time_counts),
                ("dispatch", report.dispatch_counts),
                ("host_sync", report.host_sync_counts),
            ):
                windows[key] = _window_counts(state.prev_counts.get(key),
                                              cur)
                if cur is not None:
                    state.prev_counts[key] = list(cur)
            window_steps = float(report.steps_total) - state.prev_steps_total
            if window_steps < 0:  # worker restart
                window_steps = float(report.steps_total)
            state.prev_steps_total = float(report.steps_total)

            def pct(key: str, q: float):
                counts = windows.get(key)
                if not bounds or counts is None:
                    return None, False
                return percentile_from_counts(bounds, counts, q,
                                              with_overflow=True)

            p50, of50 = pct("step_time", 0.50)
            p95, of95 = pct("step_time", 0.95)
            d50, _ = pct("dispatch", 0.50)
            s50, _ = pct("host_sync", 0.50)
            def opt(value):
                return float(value) if value is not None else None

            # serving: windowed token rate from the cumulative token
            # total and the previous sample's receive clock
            tokens_total = opt(getattr(report, "serve_tokens_total",
                                       None))
            tokens_per_s = None
            if tokens_total is not None and state.samples:
                prev = state.samples[-1]
                prev_tokens = prev.serve_tokens_total
                dt = ts - prev.ts
                if prev_tokens is not None and dt > 0 \
                        and tokens_total >= prev_tokens:
                    tokens_per_s = (tokens_total - prev_tokens) / dt
            # speculative decode: the windowed acceptance rate from
            # the cumulative drafted/accepted diffs — a regression is
            # visible the window it happens, not diluted by lifetime
            # totals
            spec_drafted = opt(getattr(
                report, "serve_spec_drafted_total", None))
            spec_accepted = opt(getattr(
                report, "serve_spec_accepted_total", None))
            spec_rate = None
            if spec_drafted is not None and spec_accepted is not None \
                    and state.samples:
                prev = state.samples[-1]
                pd = prev.serve_spec_drafted_total
                pa = prev.serve_spec_accepted_total
                if pd is not None and pa is not None \
                        and spec_drafted > pd and spec_accepted >= pa:
                    spec_rate = (spec_accepted - pa) / (spec_drafted
                                                        - pd)
            sample = NodeSample(
                ts=ts,
                step=int(report.step),
                steps_total=float(report.steps_total),
                window_steps=window_steps,
                step_p50=p50,
                step_p95=p95,
                dispatch_p50=d50,
                host_sync_p50=s50,
                window_occupancy=float(report.window_occupancy),
                lagged_age=float(report.lagged_age),
                rss_mb=float(report.rss_mb),
                device_mem_mb=opt(getattr(report, "device_mem_mb",
                                          None)),
                hbm_headroom_mb=opt(getattr(report, "hbm_headroom_mb",
                                            None)),
                mfu=opt(getattr(report, "mfu", None)),
                exposed_comm_frac=opt(getattr(report,
                                              "exposed_comm_frac",
                                              None)),
                flops_per_step=opt(getattr(report, "flops_per_step",
                                           None)),
                peak_hbm_mb=opt(getattr(report, "peak_hbm_mb", None)),
                input_wait_frac=opt(getattr(report, "input_wait_frac",
                                            None)),
                node_type=state.node_type,
                serve_tokens_total=tokens_total,
                serve_tokens_per_s=tokens_per_s,
                serve_queue_len=opt(getattr(report, "serve_queue_len",
                                            None)),
                serve_slot_occupancy=opt(getattr(
                    report, "serve_slot_occupancy", None)),
                serve_slots=opt(getattr(report, "serve_slots", None)),
                serve_spec_drafted_total=spec_drafted,
                serve_spec_accepted_total=spec_accepted,
                serve_spec_accept_rate=spec_rate,
                overflow=bool(of50 or of95),
            )
            state.samples.append(sample)
            while len(state.samples) > self._max_samples:
                state.samples.popleft()
        self._export_gauges(int(report.node_id), sample)
        return sample

    def _export_gauges(self, node_id: int, s: NodeSample) -> None:
        reg = get_registry()
        labels = {"node": str(node_id)}
        if s.node_type == "serve":
            # a serve worker's report: its step histogram holds DECODE
            # steps — export the serving names, never the training ones
            # (a scraper must not read a decode p50 as a train step)
            self._export_serve_gauges(reg, labels, s)
            return
        if s.step_p50 is not None:
            reg.gauge(tm.NODE_STEP_P50, labels=labels,
                      help="per-node windowed step-time p50").set(s.step_p50)
        if s.step_p95 is not None:
            reg.gauge(tm.NODE_STEP_P95, labels=labels,
                      help="per-node windowed step-time p95").set(s.step_p95)
        if s.dispatch_p50 is not None:
            reg.gauge(tm.NODE_DISPATCH_P50, labels=labels,
                      help="per-node windowed dispatch p50").set(
                          s.dispatch_p50)
        if s.host_sync_p50 is not None:
            reg.gauge(tm.NODE_HOST_SYNC_P50, labels=labels,
                      help="per-node windowed host-sync p50").set(
                          s.host_sync_p50)
        reg.gauge(tm.NODE_WINDOW_OCCUPANCY, labels=labels,
                  help="per-node dispatch-window occupancy").set(
                      s.window_occupancy)
        reg.gauge(tm.NODE_RSS_MB, labels=labels,
                  help="per-node worker process RSS (MB)").set(s.rss_mb)
        # absent-valued stats (CPU backend, attribution not captured)
        # export NO series — a scraper must never read a fake 0, and a
        # stat that BECOMES absent (program swap, failed re-capture)
        # retracts its series rather than freezing the last value
        optional = (
            (tm.NODE_DEVICE_MEM_MB, s.device_mem_mb,
             "per-node accelerator bytes_in_use (MB)"),
            (tm.NODE_HBM_HEADROOM_MB, s.hbm_headroom_mb,
             "per-node HBM bytes_limit - bytes_in_use (MB)"),
            (tm.NODE_MFU, s.mfu,
             "per-node live model-FLOPs utilization"),
            (tm.NODE_EXPOSED_COMM_FRAC, s.exposed_comm_frac,
             "per-node exposed-communication fraction (upper bound)"),
            (tm.NODE_FLOPS_PER_STEP, s.flops_per_step,
             "per-node compiled FLOPs per step"),
            (tm.NODE_PEAK_HBM_MB, s.peak_hbm_mb,
             "per-node compiled peak HBM (MB)"),
            (tm.NODE_INPUT_WAIT_FRAC, s.input_wait_frac,
             "per-node input-pipeline wait fraction of the step window"),
        )
        for name, value, help_text in optional:
            if value is not None:
                reg.gauge(name, labels=labels, help=help_text).set(value)
            else:
                reg.remove(name, labels=labels)
        reg.gauge(tm.NODE_STEPS_TOTAL, labels=labels,
                  help="per-node optimizer steps materialized").set(
                      s.steps_total)

    def _export_serve_gauges(self, reg, labels, s: NodeSample) -> None:
        if s.step_p50 is not None:
            reg.gauge(tm.NODE_SERVE_DECODE_P50, labels=labels,
                      help="per-serve-node windowed decode-step p50"
                      ).set(s.step_p50)
        if s.step_p95 is not None:
            reg.gauge(tm.NODE_SERVE_DECODE_P95, labels=labels,
                      help="per-serve-node windowed decode-step p95"
                      ).set(s.step_p95)
        reg.gauge(tm.NODE_SERVE_STEPS_TOTAL, labels=labels,
                  help="per-serve-node decode steps dispatched").set(
                      s.steps_total)
        reg.gauge(tm.NODE_RSS_MB, labels=labels,
                  help="per-node worker process RSS (MB)").set(s.rss_mb)
        # absent-not-zero, the attribution-gauge discipline: a rate
        # needs two samples; queue/occupancy only when reported
        optional = (
            (tm.NODE_SERVE_TOKENS_PER_S, s.serve_tokens_per_s,
             "per-serve-node windowed tokens per second"),
            (tm.NODE_SERVE_QUEUE_LEN, s.serve_queue_len,
             "per-serve-node worker-local queued requests"),
            (tm.NODE_SERVE_SLOT_OCCUPANCY, s.serve_slot_occupancy,
             "per-serve-node slots holding a live request"),
            (tm.NODE_SERVE_SLOTS, s.serve_slots,
             "per-serve-node compiled slot-batch width"),
            (tm.NODE_SERVE_SPEC_ACCEPT_RATE, s.serve_spec_accept_rate,
             "per-serve-node windowed speculative acceptance rate"),
        )
        for name, value, help_text in optional:
            if value is not None:
                reg.gauge(name, labels=labels, help=help_text).set(value)
            else:
                reg.remove(name, labels=labels)

    # -- queries -------------------------------------------------------------

    def node_ids(self) -> List[int]:
        with self._lock:
            return sorted(self._nodes)

    def forget(self, node_id: int) -> None:
        """Drop a departed node's series (the detector's cleanup; a
        returning node re-primes from its first fresh report)."""
        with self._lock:
            self._nodes.pop(node_id, None)

    def latest(self, node_id: int) -> Optional[NodeSample]:
        with self._lock:
            state = self._nodes.get(node_id)
            if state is None or not state.samples:
                return None
            return state.samples[-1]

    def series(self, node_id: int, n: int = 0) -> List[NodeSample]:
        with self._lock:
            state = self._nodes.get(node_id)
            if state is None:
                return []
            out = list(state.samples)
        return out[-n:] if n else out

    def last_report_age(self, node_id: int,
                        now: Optional[float] = None) -> Optional[float]:
        latest = self.latest(node_id)
        if latest is None:
            return None
        return max(0.0, (now or time.time()) - latest.ts)

    def summary(self, now: Optional[float] = None) -> Dict[int, Dict]:
        """Per-node latest-sample dicts (the diagnose CLI / RPC view)."""
        now = now or time.time()
        out: Dict[int, Dict] = {}
        for node_id in self.node_ids():
            latest = self.latest(node_id)
            if latest is None:
                continue
            d = asdict(latest)
            d["report_age_s"] = round(now - latest.ts, 3)
            d["samples"] = len(self.series(node_id))
            out[node_id] = d
        return out
