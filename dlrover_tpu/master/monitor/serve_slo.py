"""Master-side serving SLO verdict engine + the scale-policy loop.

The serving tier's counterpart of the straggler detector: declared SLO
targets (``serve_slo_ttft_p95_secs``, ``serve_slo_queue_depth`` —
knob-table defaults OFF) are evaluated over rolling windows against
the request router's live state, with MULTI-WINDOW burn-rate
confirmation mirroring ``diagnosis_confirm_windows``: one queue spike
cannot flag a violation, and one quiet window cannot clear it. A
confirmed violation emits ``SERVE_SLO_VIOLATION`` (failure-class: it
carries an error code and the burn-rate evidence) under a freshly
minted incident trace id; the recovery emits ``SERVE_SLO_RECOVERED``
under the SAME id, and the pair derives the ``serving_scale`` MTTR /
goodput scenario.

``ServingScalePolicy`` closes ROADMAP item 3's open loop: it listens
to the engine's verdicts (the PR 6/7 verdict-listener pattern) and
turns them into serving scale PROPOSALS — scale-out on a sustained
violation, scale-in on sustained idle slots — guarded by a
``ProposalCooldown`` (hysteresis: flapping SLOs cannot thrash the
serving world), handed to ``JobAutoScaler`` for immediate evaluation
and applied through the existing lease-holding live-resize path (the
worker's ``request_resize`` / a ScalePlan on scheduled deployments).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Dict, List, Optional

from dlrover_tpu.common.config import get_context
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.telemetry import (
    EventKind,
    emit_event,
    get_registry,
    names as tm,
)
from dlrover_tpu.telemetry.metrics import percentile_from_counts
from dlrover_tpu.telemetry.trace_context import new_trace_id, trace_scope

logger = get_logger("master.serve_slo")

SLO_TTFT_P95 = "ttft_p95"
SLO_QUEUE_DEPTH = "queue_depth"


class ServeSLOEngine:
    """Rolling-window SLO evaluation over the router's live state.

    One ``evaluate()`` tick per window (the master's stats loop drives
    it; tests inject ``now``): each enabled target observes its
    current value, computes the burn rate (observed / target; > 1 =
    out of SLO), and advances per-target over/under counters. A target
    over budget for ``confirm`` CONSECUTIVE windows flags a violation;
    an active violation under budget for ``confirm`` windows recovers.
    TTFT percentiles are windowed by diffing the router histogram's
    cumulative bucket counts between ticks (the node-series
    discipline) — a p95 poisoned by yesterday's incident must not flag
    today."""

    def __init__(self, router, store=None,
                 ttft_p95_secs: Optional[float] = None,
                 queue_depth: Optional[float] = None,
                 window_secs: Optional[float] = None,
                 confirm_windows: Optional[int] = None):
        ctx = get_context()
        self.router = router
        self._store = store
        self._ttft_target = float(
            ttft_p95_secs if ttft_p95_secs is not None
            else getattr(ctx, "serve_slo_ttft_p95_secs", 0.0))
        self._queue_target = float(
            queue_depth if queue_depth is not None
            else getattr(ctx, "serve_slo_queue_depth", 0.0))
        self._window = float(
            window_secs if window_secs is not None
            else getattr(ctx, "serve_slo_window_secs", 30.0))
        confirm = int(
            confirm_windows if confirm_windows is not None
            else getattr(ctx, "serve_slo_confirm_windows", 0))
        if confirm <= 0:
            confirm = int(getattr(ctx, "diagnosis_confirm_windows", 3))
        self._confirm = max(1, confirm)
        self._lock = threading.Lock()
        self._last_eval = 0.0
        self._prev_ttft_counts: Optional[List[int]] = None
        # per-target: consecutive over/under window counts + the
        # active violation record ({trace_id, since, evidence})
        self._over: Dict[str, int] = {}
        self._under: Dict[str, int] = {}
        self._burns: Dict[str, collections.deque] = {}
        self._active: Dict[str, Dict] = {}
        self._listeners: List[Callable] = []
        self._pending: List = []
        reg = get_registry()
        self._c_violations = reg.counter(
            tm.SERVE_SLO_VIOLATIONS,
            help="serving SLO violations confirmed")
        self._c_recoveries = reg.counter(
            tm.SERVE_SLO_RECOVERIES,
            help="serving SLO violations recovered")

    def enabled(self) -> bool:
        return self._ttft_target > 0 or self._queue_target > 0

    def add_verdict_listener(self, fn: Callable) -> None:
        """``fn(slo_name, verdict, info)`` with verdict in
        {"violation", "recovered"}; fired OUTSIDE the engine lock
        under the incident's trace scope (the straggler-detector
        listener discipline); failures are logged, never raised into
        the evaluation tick."""
        self._listeners.append(fn)

    def _drain_notices(self) -> None:
        with self._lock:
            pending, self._pending = self._pending, []
        for slo, verdict, info in pending:
            with trace_scope(info.get("trace_id") or None):
                for fn in self._listeners:
                    try:
                        fn(slo, verdict, dict(info))
                    except Exception:  # noqa: BLE001 — a broken
                        # listener must not kill SLO evaluation
                        logger.exception(
                            "SLO verdict listener failed for %s (%s)",
                            slo, verdict)

    # -- evaluation ----------------------------------------------------------

    def _observations(self) -> Dict[str, tuple]:
        """Per-target ``(observed, overflow)``; overflow marks a
        +Inf-bucket clamped percentile — a LOWER bound, not a
        measurement (the diagnosis-verdict discipline)."""
        obs = self.router.slo_observations()
        out: Dict[str, tuple] = {
            SLO_QUEUE_DEPTH: (float(obs.get("queue_depth", 0)), False),
            SLO_TTFT_P95: (None, False),
        }
        counts = obs.get("ttft_counts")
        bounds = obs.get("ttft_bounds")
        if counts and bounds:
            prev = self._prev_ttft_counts
            self._prev_ttft_counts = list(counts)
            if prev is not None and len(prev) == len(counts):
                window = [c - p for c, p in zip(counts, prev)]
                if any(w > 0 for w in window):
                    out[SLO_TTFT_P95] = percentile_from_counts(
                        bounds, window, 0.95, with_overflow=True)
            elif sum(counts) > 0:
                # a node's first window is its own window (the
                # node-series rule)
                out[SLO_TTFT_P95] = percentile_from_counts(
                    bounds, counts, 0.95, with_overflow=True)
        return out

    def evaluate(self, now: Optional[float] = None,
                 force: bool = False) -> Dict[str, Dict]:
        """One window tick (no-op inside the window unless forced);
        returns the active violation verdicts."""
        if not self.enabled():
            return {}
        now = float(now if now is not None else time.monotonic())
        with self._lock:
            if not force and now - self._last_eval < self._window:
                return {k: dict(v) for k, v in self._active.items()}
            self._last_eval = now
            observations = self._observations()
            targets = {}
            if self._ttft_target > 0:
                targets[SLO_TTFT_P95] = self._ttft_target
            if self._queue_target > 0:
                targets[SLO_QUEUE_DEPTH] = self._queue_target
            for slo, target in targets.items():
                observed, overflow = observations.get(slo,
                                                      (None, False))
                if observed is None:
                    # no observations this window (e.g. no completions
                    # landed a TTFT): neither over nor under — hold the
                    # counters, the queue-depth target still watches a
                    # stalled system
                    continue
                burn = observed / target
                if overflow and burn <= 1.0:
                    # the percentile was CLAMPED at the last finite
                    # bucket bound: the true value is only known to be
                    # >= observed, so "under budget" is not concluded
                    # — an active violation must not count a censored
                    # window toward recovery (over IS conclusive: a
                    # lower bound above target is above target)
                    continue
                burns = self._burns.setdefault(
                    slo, collections.deque(maxlen=self._confirm))
                burns.append(round(burn, 4))
                if burn > 1.0:
                    self._over[slo] = self._over.get(slo, 0) + 1
                    self._under[slo] = 0
                    if (self._over[slo] >= self._confirm
                            and slo not in self._active):
                        self._flag(slo, observed, target, now,
                                   overflow=overflow)
                else:
                    self._under[slo] = self._under.get(slo, 0) + 1
                    self._over[slo] = 0
                    if (slo in self._active
                            and self._under[slo] >= self._confirm):
                        self._recover(slo, observed, target, now)
            verdicts = {k: dict(v) for k, v in self._active.items()}
        self._drain_notices()
        return verdicts

    def _flag(self, slo: str, observed: float, target: float,
              now: float, overflow: bool = False) -> None:
        tid = new_trace_id()
        evidence = {
            "slo": slo,
            "observed": round(observed, 6),
            "target": target,
            "burn_rate": round(observed / target, 4),
            "burn_rates": list(self._burns.get(slo, ())),
            "confirm_windows": self._over.get(slo, 0),
            "window_secs": self._window,
        }
        if overflow:
            # histogram-clamped: observed/burn are LOWER bounds
            evidence["overflow"] = True
        self._active[slo] = {
            "trace_id": tid, "since": now, "evidence": evidence,
        }
        self._c_violations.inc()
        get_registry().gauge(
            tm.SERVE_SLO_BURN_RATE, labels={"slo": slo},
            help="observed/target per declared serving SLO (>1 = out "
                 "of SLO)").set(evidence["burn_rate"])
        emit_event(
            EventKind.SERVE_SLO_VIOLATION,
            error_code="SERVE_SLO_VIOLATION",
            trace_id=tid, **evidence,
        )
        logger.warning("serving SLO %s violated [%s]: %s", slo, tid,
                       evidence)
        self._pending.append((slo, "violation",
                              {"trace_id": tid, **evidence}))

    def _recover(self, slo: str, observed: float, target: float,
                 now: float) -> None:
        active = self._active.pop(slo)
        self._c_recoveries.inc()
        get_registry().gauge(
            tm.SERVE_SLO_BURN_RATE, labels={"slo": slo}).set(
                round(observed / target, 4))
        emit_event(
            EventKind.SERVE_SLO_RECOVERED,
            trace_id=active["trace_id"], slo=slo,
            observed=round(observed, 6), target=target,
            violated_seconds=round(now - active["since"], 3),
            confirm_windows=self._under.get(slo, 0),
        )
        logger.info("serving SLO %s recovered after %.1fs", slo,
                    now - active["since"])
        self._pending.append((
            slo, "recovered",
            {"trace_id": active["trace_id"], "slo": slo,
             "observed": round(observed, 6), "target": target}))

    # -- queries -------------------------------------------------------------

    def verdicts(self) -> Dict[str, Dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._active.items()}

    def report(self) -> Dict:
        """The ``tpurun serve slo --addr`` payload."""
        with self._lock:
            return {
                "enabled": self.enabled(),
                "targets": {
                    SLO_TTFT_P95: self._ttft_target or None,
                    SLO_QUEUE_DEPTH: self._queue_target or None,
                },
                "window_secs": self._window,
                "confirm_windows": self._confirm,
                "burn_rates": {k: list(v)
                               for k, v in self._burns.items()},
                "verdicts": {k: dict(v)
                             for k, v in self._active.items()},
            }


class ServingScalePolicy:
    """Verdict -> proposal: the first queue-depth/SLO-driven serving
    scale policy. Registered as a listener on the SLO engine; also
    ``tick()``-ed by the master's stats loop to watch for sustained
    IDLE slots (the scale-in direction — an SLO can only ask for
    more)."""

    def __init__(self, slo_engine: ServeSLOEngine, store=None,
                 auto_scaler=None, apply: Optional[Callable] = None,
                 cooldown_secs: Optional[float] = None,
                 idle_windows: Optional[int] = None):
        from dlrover_tpu.parallel.search import ProposalCooldown

        ctx = get_context()
        self._engine = slo_engine
        self._store = store
        self._auto_scaler = auto_scaler
        self._apply = apply
        self._cooldown = ProposalCooldown(float(
            cooldown_secs if cooldown_secs is not None
            else getattr(ctx, "serve_scale_cooldown_secs", 120.0)))
        # consecutive idle ticks before a scale-in proposal (0 = the
        # scale-in direction is off; knob-table default off)
        self._idle_windows = int(
            idle_windows if idle_windows is not None
            else getattr(ctx, "serve_scale_idle_windows", 0))
        self._idle_count = 0
        self.proposals: collections.deque = collections.deque(maxlen=64)
        self._c_proposals = get_registry().counter(
            tm.SERVE_SCALE_PROPOSALS,
            help="SLO/idle-driven serving scale proposals issued")
        slo_engine.add_verdict_listener(self._on_verdict)

    def attach_auto_scaler(self, auto_scaler) -> None:
        self._auto_scaler = auto_scaler

    def attach_apply(self, fn: Callable) -> None:
        """The resize actuator (deployment-specific): called with the
        proposal dict. Standalone wedges wire it to a serve worker's
        ``request_resize`` — the existing lease-holding live-resize
        path; scheduled deployments translate it into a ScalePlan."""
        self._apply = fn

    def _on_verdict(self, slo: str, verdict: str, info: Dict) -> None:
        if verdict == "violation":
            self._propose("scale_out", reason=f"slo:{slo}",
                          trace_id=info.get("trace_id", ""),
                          evidence=info)
        # a recovery needs no proposal: the violated state asked for
        # capacity, its clearing just stops asking

    def tick(self, now: Optional[float] = None) -> None:
        """Idle watch (the scale-in direction): every serve node's
        occupancy at 0 and the router queue empty for
        ``serve_scale_idle_windows`` consecutive ticks proposes a
        scale-in."""
        if self._idle_windows <= 0 or self._store is None:
            return
        serve_nodes = [
            s for s in (self._store.latest(nid)
                        for nid in self._store.node_ids())
            if s is not None and getattr(s, "node_type", "") == "serve"
        ]
        if not serve_nodes:
            self._idle_count = 0
            return
        occupied = any((s.serve_slot_occupancy or 0) > 0
                       for s in serve_nodes)
        queued = self._engine.router.queue_depth() > 0
        if occupied or queued:
            self._idle_count = 0
            return
        self._idle_count += 1
        if self._idle_count >= self._idle_windows:
            self._idle_count = 0
            self._propose("scale_in", reason="idle_slots",
                          trace_id="",
                          evidence={"idle_windows": self._idle_windows})

    def _propose(self, direction: str, reason: str, trace_id: str,
                 evidence: Dict) -> None:
        key = f"serve_scale|{direction}"
        if not self._cooldown.check(key):
            logger.info("serving scale proposal (%s) suppressed by "
                        "cooldown", direction)
            return
        proposal = {
            "direction": direction,
            "reason": reason,
            "trace_id": trace_id,
            "ts": time.time(),
            "evidence": {k: v for k, v in (evidence or {}).items()
                         if k != "trace_id"},
        }
        self.proposals.append(proposal)
        self._c_proposals.inc()
        emit_event(
            EventKind.SERVE_SCALE_PROPOSED,
            trace_id=trace_id or None, direction=direction,
            reason=reason,
        )
        logger.warning("serving scale proposal: %s (%s)", direction,
                       reason)
        if self._auto_scaler is not None:
            try:
                self._auto_scaler.submit_serving_proposal(proposal)
            except Exception:  # noqa: BLE001 — the proposal is
                # recorded either way; the scaler loop must not be
                # able to kill SLO evaluation
                logger.exception("auto-scaler rejected serving "
                                 "proposal")
        if self._apply is not None:
            try:
                self._apply(dict(proposal))
            except Exception:  # noqa: BLE001 — actuator failures are
                # the next evaluation window's problem, not this one's
                logger.exception("serving scale apply failed")

    def to_report(self) -> Dict:
        return {"proposals": [dict(p) for p in self.proposals]}
