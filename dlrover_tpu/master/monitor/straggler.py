"""Master-side straggler & node-hang diagnosis over the node series.

The verdict the control loop (ROADMAP item 1) actuates on: each node's
windowed step-time p50 is compared against the MEDIAN of its peers'
(excluding itself — robust down to 2-node clusters); a node must exceed
``diagnosis_straggler_ratio`` for ``diagnosis_confirm_windows``
CONSECUTIVE report windows before it is flagged, so one box-noise spike
cannot brand a healthy node. A node whose reports stop arriving while a
peer is still reporting is diagnosed hung after ``diagnosis_hang_secs``.

Verdicts are:

  * emitted as ``DIAG_STRAGGLER`` / ``DIAG_NODE_HANG`` timeline events
    with the full evidence attached (node p50/p95, peer median, ratio,
    confirm windows, overflow marker) and a freshly minted incident
    trace id;
  * pushed into ``SpeedMonitor`` (``update_node_verdict``) so speed
    judgements and the auto-scaler see the per-node health; and
  * queryable via ``verdicts()`` / the master's ``DiagnosisRequest``
    RPC / ``tpurun diagnose``.

A p50 clamped by the histogram's +Inf bucket (``overflow``) is treated
as a LOWER bound: it can confirm a straggler (the node is at least that
slow) but the evidence carries ``overflow: true`` so operators know the
magnitude is censored.
"""

from __future__ import annotations

import statistics
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from dlrover_tpu.common.config import get_context
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.master.monitor.node_series import NodeRuntimeStore
from dlrover_tpu.telemetry import (
    EventKind,
    emit_event,
    get_registry,
    names as tm,
)
from dlrover_tpu.telemetry.trace_context import new_trace_id, trace_scope

logger = get_logger("master.straggler")

VERDICT_HEALTHY = "healthy"
VERDICT_STRAGGLER = "straggler"
VERDICT_HUNG = "hung"

# the bound-triad peer-delta: a node's input-wait / exposed-comm
# fraction must exceed the healthy peers' median by this much before
# the leg names it. ONE constant shared with the runtime optimizer's
# input-bound replan gate — the verdict's label and the gate's
# judgement must never desynchronize.
BOUND_PEER_DELTA = 0.1


@dataclass
class NodeVerdict:
    node_id: int
    verdict: str = VERDICT_HEALTHY
    since_ts: float = 0.0
    trace_id: str = ""
    evidence: Dict = field(default_factory=dict)

    def to_dict(self) -> Dict:
        return {
            "node_id": self.node_id,
            "verdict": self.verdict,
            "since_ts": self.since_ts,
            "trace_id": self.trace_id,
            "evidence": dict(self.evidence),
        }


class StragglerDetector:
    def __init__(
        self,
        store: NodeRuntimeStore,
        speed_monitor=None,
        ratio: Optional[float] = None,
        confirm_windows: Optional[int] = None,
        hang_secs: Optional[float] = None,
        freshness_secs: float = 600.0,
    ):
        ctx = get_context()
        self._store = store
        self._speed_monitor = speed_monitor
        self._ratio = float(
            ratio if ratio is not None
            else getattr(ctx, "diagnosis_straggler_ratio", 2.0))
        self._confirm = max(1, int(
            confirm_windows if confirm_windows is not None
            else getattr(ctx, "diagnosis_confirm_windows", 3)))
        self._hang_secs = float(
            hang_secs if hang_secs is not None
            else getattr(ctx, "diagnosis_hang_secs", 120.0))
        # how old a peer's latest window may be and still anchor the
        # cluster median (stale peers would skew the comparison)
        self._freshness = float(freshness_secs)
        # a node silent this long has DEPARTED (deleted pod, scaled
        # away): its verdict and series are dropped so a stale "hung"
        # flag cannot pin the auto-scaler disabled for the rest of the
        # job — the very mechanism that could replace the node
        self._departed_after = max(4 * self._hang_secs, 300.0)
        self._lock = threading.Lock()
        self._over_counts: Dict[int, int] = {}
        self._verdicts: Dict[int, NodeVerdict] = {}
        # verdict-change listeners (runtime optimizer re-plan trigger,
        # auto-scaler immediate re-evaluation on recovery): called with
        # (node_id, verdict) where verdict is "healthy" on clear/depart.
        # Registered post-construction (add_verdict_listener) so owners
        # built after the detector (dist master's scaler) can attach.
        self._listeners: List = []
        # (node_id, verdict, trace_id) queued under the lock, delivered
        # outside it by _drain_notices
        self._pending_notices: List = []
        reg = get_registry()
        self._c_stragglers = reg.counter(
            tm.DIAG_STRAGGLERS, help="straggler verdicts confirmed")
        self._c_hangs = reg.counter(
            tm.DIAG_NODE_HANGS, help="node-hang verdicts confirmed")
        self._c_recoveries = reg.counter(
            tm.DIAG_RECOVERIES, help="verdicts cleared by recovery")

    def add_verdict_listener(self, fn) -> None:
        """Register a ``fn(node_id, verdict)`` callback fired on every
        verdict CHANGE (flag, recovery, departure — the latter two as
        "healthy"). Listeners run OUTSIDE the detector lock (a slow or
        re-entrant listener — the runtime optimizer's full re-plan pass
        — must neither block other nodes' report ingest nor deadlock),
        under the verdict's trace scope so everything they emit joins
        the incident's trail. Listener failures are logged, never
        raised into the ingest path."""
        self._listeners.append(fn)

    def _notify(self, node_id: int, verdict: str, trace_id: str) -> None:
        """Queue a verdict-change notification (lock held); delivered by
        ``_drain_notices`` after the locked region exits."""
        self._pending_notices.append((node_id, verdict, trace_id))

    def _drain_notices(self) -> None:
        with self._lock:
            pending, self._pending_notices = self._pending_notices, []
        for node_id, verdict, tid in pending:
            with trace_scope(tid or None):
                for fn in self._listeners:
                    try:
                        fn(node_id, verdict)
                    except Exception:  # noqa: BLE001 — must not kill ingest
                        logger.exception(
                            "verdict listener failed for node %d (%s)",
                            node_id, verdict,
                        )

    # -- evaluation ----------------------------------------------------------

    def observe(self, node_id: int, now: Optional[float] = None) -> None:
        """Evaluate after one node's report landed: that node's
        straggler window advances, a hung verdict on it clears (it just
        reported), and the cluster hang scan runs."""
        now = now or time.time()
        with self._lock:
            self._clear_if_hung(node_id, now)
            self._judge_straggler(node_id, now)
        self._drain_notices()
        self.scan_hangs(now)

    def scan_hangs(self, now: Optional[float] = None) -> None:
        """Flag nodes whose reports stopped while a peer still reports
        (called from observe() and the master's periodic stats loop, so
        a hang is noticed even when NO report arrives to trigger it)."""
        if self._hang_secs <= 0:
            return
        now = now or time.time()
        ages = {
            nid: self._store.last_report_age(nid, now)
            for nid in self._store.node_ids()
        }
        ages = {n: a for n, a in ages.items() if a is not None}
        if not ages:
            return
        freshest = min(ages.values())
        if freshest > self._hang_secs:
            # EVERY node went quiet: the job ended or the master is
            # partitioned — a per-node hang verdict would be noise
            return
        with self._lock:
            for nid, age in ages.items():
                if age > self._departed_after:
                    self._forget(nid, age)
                    continue
                if age <= self._hang_secs:
                    continue
                cur = self._verdicts.get(nid)
                if cur is not None and cur.verdict == VERDICT_HUNG:
                    continue
                self._flag(
                    nid, VERDICT_HUNG, now,
                    evidence={
                        "report_age_s": round(age, 1),
                        "hang_secs": self._hang_secs,
                        "freshest_peer_age_s": round(freshest, 1),
                    },
                )
        self._drain_notices()

    def _judge_straggler(self, node_id: int, now: float) -> None:
        mine = self._store.latest(node_id)
        if mine is None or mine.step_p50 is None or mine.window_steps <= 0:
            return
        workload = getattr(mine, "node_type", "worker")
        peers = []
        peer_fracs = []
        peer_input_fracs = []
        for nid in self._store.node_ids():
            if nid == node_id:
                continue
            s = self._store.latest(nid)
            if (s is None or s.step_p50 is None
                    or now - s.ts > self._freshness):
                continue
            if getattr(s, "node_type", "worker") != workload:
                # a decode worker's step is a different animal from a
                # train step: peers anchor the median ONLY within the
                # same workload (serve vs serve, train vs train)
                continue
            peers.append(s.step_p50)
            if getattr(s, "exposed_comm_frac", None) is not None:
                peer_fracs.append(s.exposed_comm_frac)
            if getattr(s, "input_wait_frac", None) is not None:
                peer_input_fracs.append(s.input_wait_frac)
        if not peers:
            # no fresh peer anchors a median: there is no evidence
            # basis, so an existing straggler verdict must not outlive
            # the comparison that produced it
            self._over_counts[node_id] = 0
            self._clear_if(node_id, VERDICT_STRAGGLER, now,
                           reason="no_fresh_peers")
            return
        peer_median = statistics.median(peers)
        if peer_median <= 0:
            return
        ratio = mine.step_p50 / peer_median
        if ratio < self._ratio:
            self._over_counts[node_id] = 0
            self._clear_if(node_id, VERDICT_STRAGGLER, now, ratio=ratio)
            return
        self._over_counts[node_id] = self._over_counts.get(node_id, 0) + 1
        over = self._over_counts[node_id]
        cur = self._verdicts.get(node_id)
        already = cur is not None and cur.verdict == VERDICT_STRAGGLER
        if over < self._confirm or already:
            return
        evidence = {
            "step_p50_s": round(mine.step_p50, 6),
            "step_p95_s": (round(mine.step_p95, 6)
                           if mine.step_p95 is not None else None),
            "peer_median_p50_s": round(peer_median, 6),
            "ratio": round(ratio, 3),
            "threshold": self._ratio,
            "confirm_windows": over,
            "window_steps": mine.window_steps,
            "overflow": mine.overflow,
        }
        if workload == "serve":
            # the serve evidence flavor: the p50s above are DECODE-step
            # percentiles, and the serving facts say what the slow
            # decode is starving (tokens/sec, held slots)
            evidence["workload"] = "serve"
            if getattr(mine, "serve_tokens_per_s", None) is not None:
                evidence["tokens_per_s"] = round(
                    mine.serve_tokens_per_s, 3)
            if getattr(mine, "serve_slot_occupancy", None) is not None:
                evidence["slot_occupancy"] = mine.serve_slot_occupancy
        # bound labeling — the WHY behind a slow node, judged in triad
        # order: input-bound, then comm-bound, then compute-bound. A
        # starved input pipeline inflates BOTH the step time and the
        # exposed-comm fraction (the residual 1 - compute/step rises
        # with any non-compute time), so without the input leg a
        # data-starved node reads as comm/compute-bound and the
        # optimizer burns a drain on a mesh replan that cannot help.
        # Every leg is judged RELATIVE to the healthy peers' median
        # (delta >= 0.1), never an absolute threshold: input wait and
        # exposed comm both rise cluster-wide with shared causes, and
        # only the node's EXCESS over its peers names the culprit.
        bound = None
        input_frac = getattr(mine, "input_wait_frac", None)
        if input_frac is not None:
            evidence["input_wait_frac"] = round(input_frac, 4)
            if peer_input_fracs:
                peer_input = statistics.median(peer_input_fracs)
                evidence["peer_median_input_wait_frac"] = round(
                    peer_input, 4)
                if input_frac - peer_input >= BOUND_PEER_DELTA:
                    bound = "input-bound"
        frac = getattr(mine, "exposed_comm_frac", None)
        if frac is not None:
            evidence["exposed_comm_frac"] = round(frac, 4)
            if peer_fracs:
                peer_frac = statistics.median(peer_fracs)
                evidence["peer_median_comm_frac"] = round(peer_frac, 4)
                if bound is None:
                    bound = ("comm-bound"
                             if frac - peer_frac >= BOUND_PEER_DELTA
                             else "compute-bound")
        if bound is not None:
            evidence["bound"] = bound
        if getattr(mine, "mfu", None) is not None:
            evidence["mfu"] = round(mine.mfu, 6)
        self._flag(node_id, VERDICT_STRAGGLER, now, evidence=evidence)

    # -- verdict bookkeeping (lock held) -------------------------------------

    def _flag(self, node_id: int, verdict: str, now: float,
              evidence: Dict) -> None:
        tid = new_trace_id()
        self._verdicts[node_id] = NodeVerdict(
            node_id=node_id, verdict=verdict, since_ts=now,
            trace_id=tid, evidence=evidence,
        )
        if verdict == VERDICT_STRAGGLER:
            self._c_stragglers.inc()
            emit_event(EventKind.DIAG_STRAGGLER, error_code="STRAGGLER",
                       trace_id=tid, diag_node=node_id, **evidence)
        else:
            self._c_hangs.inc()
            emit_event(EventKind.DIAG_NODE_HANG, error_code="NODE_HANG",
                       trace_id=tid, diag_node=node_id, **evidence)
        logger.warning("node %d diagnosed %s [%s]: %s",
                       node_id, verdict, tid, evidence)
        self._push_verdict(node_id)
        self._notify(node_id, verdict, tid)

    def _clear_if(self, node_id: int, verdict: str, now: float,
                  **extra) -> None:
        cur = self._verdicts.get(node_id)
        if cur is None or cur.verdict != verdict:
            return
        # recovered nodes are POPPED, not parked as "healthy" rows: the
        # verdict map (and so DiagnosisRequest / `tpurun diagnose`)
        # holds only ACTIVE judgements, and an operator never reads a
        # stale VERDICT line for a node that recovered an hour ago
        self._verdicts.pop(node_id)
        self._c_recoveries.inc()
        emit_event(EventKind.DIAG_RECOVERED, trace_id=cur.trace_id,
                   diag_node=node_id, was=verdict,
                   flagged_seconds=round(now - cur.since_ts, 1), **extra)
        logger.info("node %d recovered from %s verdict", node_id, verdict)
        if self._speed_monitor is not None:
            try:
                self._speed_monitor.update_node_verdict(
                    node_id, VERDICT_HEALTHY)
            except Exception:  # noqa: BLE001 — verdicts must not kill ingest
                logger.exception("failed to push verdict to speed monitor")
        self._notify(node_id, VERDICT_HEALTHY, cur.trace_id)

    def _clear_if_hung(self, node_id: int, now: float) -> None:
        self._clear_if(node_id, VERDICT_HUNG, now)

    def _forget(self, node_id: int, age: float) -> None:
        """Drop a DEPARTED node entirely (verdict, window counter, and
        series): it is no longer part of the cluster being judged."""
        cur = self._verdicts.pop(node_id, None)
        self._over_counts.pop(node_id, None)
        self._store.forget(node_id)
        if cur is not None and cur.verdict != VERDICT_HEALTHY:
            self._c_recoveries.inc()
            emit_event(EventKind.DIAG_RECOVERED, trace_id=cur.trace_id,
                       diag_node=node_id, was=cur.verdict,
                       departed=True, report_age_s=round(age, 1))
        logger.info("node %d departed (silent %.0fs): series and "
                    "verdict dropped", node_id, age)
        if self._speed_monitor is not None:
            try:
                self._speed_monitor.update_node_verdict(
                    node_id, VERDICT_HEALTHY)
            except Exception:  # noqa: BLE001 — cleanup must not raise
                logger.exception("failed to clear departed verdict")
        if cur is not None and cur.verdict != VERDICT_HEALTHY:
            self._notify(node_id, VERDICT_HEALTHY, cur.trace_id)

    def _push_verdict(self, node_id: int) -> None:
        if self._speed_monitor is None:
            return
        latest = self._store.latest(node_id)
        if latest is not None and getattr(
                latest, "node_type", "worker") == "serve":
            # serve verdicts must not freeze the TRAINING auto-scaler
            # (it defers while any verdict is active); the SLO policy
            # loop is the serving actuator
            return
        v = self._verdicts[node_id]
        try:
            self._speed_monitor.update_node_verdict(
                node_id, v.verdict, evidence=v.evidence)
        except Exception:  # noqa: BLE001 — verdicts must not kill ingest
            logger.exception("failed to push verdict to speed monitor")

    # -- queries -------------------------------------------------------------

    def verdicts(self) -> Dict[int, Dict]:
        with self._lock:
            return {n: v.to_dict() for n, v in self._verdicts.items()}

    def stragglers(self) -> List[int]:
        with self._lock:
            return sorted(n for n, v in self._verdicts.items()
                          if v.verdict == VERDICT_STRAGGLER)

    def hung_nodes(self) -> List[int]:
        with self._lock:
            return sorted(n for n, v in self._verdicts.items()
                          if v.verdict == VERDICT_HUNG)

    def unhealthy(self) -> List[int]:
        with self._lock:
            return sorted(n for n, v in self._verdicts.items()
                          if v.verdict != VERDICT_HEALTHY)
