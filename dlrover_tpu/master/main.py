"""Master process entry point.

Role parity: ``dlrover/python/master/main.py`` — parse args, build the
master for the platform, serve. Prints ``DLROVER_TPU_MASTER_ADDR=<addr>`` on
stdout once serving so a parent (the standalone launcher) can scrape it.
"""

from __future__ import annotations

import sys
import time

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.master.args import parse_master_args
from dlrover_tpu.master.local_master import LocalJobMaster

logger = get_logger("master.main")


def run(args) -> int:
    if args.platform == "local":
        master = LocalJobMaster(port=args.port, job_name=args.job_name)
    else:
        # the distributed (k8s/ray) master composes a job manager + scaler on
        # top of the local master's services; built in dist_master.py.
        from dlrover_tpu.master.dist_master import DistributedJobMaster

        job_args = None
        if args.platform == "ray":
            import json

            from dlrover_tpu.scheduler.ray import ray_job_args

            conf = json.loads(args.ray_conf) if args.ray_conf else {
                "worker": {"count": args.node_num},
            }
            job_args = ray_job_args(
                conf, job_name=args.job_name, namespace=args.namespace,
            )
        master = DistributedJobMaster(
            port=args.port, job_name=args.job_name, platform=args.platform,
            node_num=args.node_num, job_args=job_args,
        )
    master.prepare()
    print(f"DLROVER_TPU_MASTER_ADDR={master.addr}", flush=True)
    if args.timeout > 0:
        deadline = time.time() + args.timeout

        def _watchdog():
            while time.time() < deadline:
                time.sleep(1)
                if master.servicer.job_exit_requested:
                    return
            logger.error("master timeout after %.0fs", args.timeout)
            master.servicer.job_success = False
            master.servicer.job_exit_requested = True

        import threading

        threading.Thread(target=_watchdog, daemon=True).start()
    return master.run()


def main(argv=None) -> int:
    return run(parse_master_args(argv))


if __name__ == "__main__":
    sys.exit(main())
