"""Hooks fired by the job manager on node lifecycle edges.

Role parity: ``dlrover/python/master/node/event_callback.py``
(``NodeEventCallback``, ``TaskRescheduleCallback``,
``AllReduceNodeHandlingCallback``) — decouples node lifecycle from the
subsystems that care about it (data sharding recovery, rendezvous liveness,
speed monitoring, job completion).
"""

from __future__ import annotations

from abc import ABC
from typing import Optional

from dlrover_tpu.common.constants import (
    JobExitReason,
    NodeExitReason,
    NodeType,
)
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.node import Node

logger = get_logger("node.callback")


class NodeEventCallback(ABC):
    def on_node_started(self, node: Node, cluster_context):
        ...

    def on_node_succeeded(self, node: Node, cluster_context):
        ...

    def on_node_failed(self, node: Node, cluster_context):
        ...

    def on_node_deleted(self, node: Node, cluster_context):
        ...


class ClusterContext:
    """What callbacks get to see of the master (reference: ClusterContext)."""

    def __init__(self, job_manager):
        self.job_manager = job_manager


class TaskRescheduleCallback(NodeEventCallback):
    """Re-queue the data shards a dead worker was holding."""

    def __init__(self, task_manager):
        self._task_manager = task_manager

    def on_node_failed(self, node: Node, cluster_context):
        if node.rank_index is not None:
            self._task_manager.recover_tasks(node.rank_index)

    def on_node_deleted(self, node: Node, cluster_context):
        if node.rank_index is not None:
            self._task_manager.recover_tasks(node.rank_index)


class AllReduceNodeHandlingCallback(NodeEventCallback):
    """SPMD-job bookkeeping: rendezvous liveness, speed monitor, job exit.

    Role parity: ``event_callback.py:209`` — on start, the node becomes a
    rendezvous candidate; on exit it is removed from the waiting/alive pools
    so the next round forms without it; total failure (no relaunch budget)
    ends the job.
    """

    def __init__(self, master):
        self._master = master

    @property
    def _speed_monitor(self):
        return getattr(self._master, "speed_monitor", None)

    def on_node_started(self, node: Node, cluster_context):
        if node.type == NodeType.WORKER:
            for manager in self._master.rdzv_managers.values():
                manager.add_alive_node(node.rank_index)

    def on_node_succeeded(self, node: Node, cluster_context):
        self._remove_from_rdzv(node)
        if self._speed_monitor is not None:
            self._speed_monitor.remove_running_worker(node.rank_index)
        job_manager = cluster_context.job_manager
        if job_manager.all_critical_node_success():
            self._master.request_stop(
                success=True, reason=JobExitReason.SUCCEEDED
            )

    def on_node_failed(self, node: Node, cluster_context):
        self._remove_from_rdzv(node)
        if self._speed_monitor is not None:
            self._speed_monitor.remove_running_worker(node.rank_index)
        if node.is_unrecoverable_failure():
            reason = (
                JobExitReason.NODE_OOM_ERROR
                if node.exit_reason == NodeExitReason.OOM
                else JobExitReason.NODE_ERROR
            )
            if node.critical:
                self._master.request_stop(success=False, reason=reason)

    def on_node_deleted(self, node: Node, cluster_context):
        self._remove_from_rdzv(node)
        if self._speed_monitor is not None:
            self._speed_monitor.remove_running_worker(node.rank_index)

    def _remove_from_rdzv(self, node: Node):
        if node.type == NodeType.WORKER:
            for manager in self._master.rdzv_managers.values():
                manager.remove_alive_node(node.rank_index)
