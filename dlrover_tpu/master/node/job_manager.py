"""Node-lifecycle management for a distributed job.

Role parity: ``dlrover/python/master/node/dist_job_manager.py``
(``DistributedJobManager``) — owns the in-memory node table, consumes
watcher events through the status state machine, decides relaunches
(OOM ⇒ memory ×2 via the optimizer, fatal ⇒ give up, budget-capped),
detects hangs from resource usage + heartbeats, and executes ScalePlans
through the scaler.

TPU-first: node health includes the ICI network-check verdict (a node that
failed the paired-allgather probe is relaunched even though its process is
alive), and relaunch counts are tracked per slice so a flapping slice is
cordoned rather than relaunched forever.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from dlrover_tpu.common.config import get_context
from dlrover_tpu.common.constants import (
    NodeEventType,
    NodeExitReason,
    NodeStatus,
    NodeType,
)
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.node import Node, NodeGroupResource
from dlrover_tpu.common.status_flow import get_node_state_flow
from dlrover_tpu.diagnosis.error_monitor import ErrorLogMonitor
from dlrover_tpu.master.node.event_callback import ClusterContext, NodeEventCallback
from dlrover_tpu.master.node.ps import ParameterServerManager
from dlrover_tpu.master.node.worker import (
    ChiefManager,
    EvaluatorManager,
    WorkerManager,
)
from dlrover_tpu.master.scaler.base_scaler import ScalePlan, Scaler
from dlrover_tpu.master.watcher.base_watcher import NodeEvent, NodeWatcher
from dlrover_tpu.scheduler.job import JobArgs

logger = get_logger("node.job_manager")


class JobManager:
    """Base used by both the local (no-op) and distributed managers."""

    def handle_training_failure(self, node_id, restart_count, error_data, level):
        ...

    def update_node_resource_usage(self, node_type, node_id, cpu, memory):
        ...

    def collect_node_heartbeat(self, node_id, timestamp):
        ...

    def update_node_reported_status(self, node_type, node_id, status):
        ...


class DistributedJobManager(JobManager):
    def __init__(
        self,
        job_args: JobArgs,
        scaler: Scaler,
        watcher: NodeWatcher,
        job_optimizer=None,
        node_event_callbacks: Optional[List[NodeEventCallback]] = None,
    ):
        self._job_args = job_args
        self._scaler = scaler
        self._watcher = watcher
        self._job_optimizer = job_optimizer
        self._callbacks: List[NodeEventCallback] = list(node_event_callbacks or [])
        self._ctx = get_context()

        self._job_nodes: Dict[str, Dict[int, Node]] = {}
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self._threads: List[threading.Thread] = []

        self._worker_manager: Optional[WorkerManager] = None
        self._ps_manager: Optional[ParameterServerManager] = None
        self._chief_manager: Optional[ChiefManager] = None
        self._evaluator_manager: Optional[EvaluatorManager] = None

        # Slice-level failure bookkeeping (TPU): slice_index -> relaunches.
        # A slice that burns through the job-level budget is cordoned.
        self._slice_relaunches: Dict[int, int] = {}
        self.max_relaunch_count = self._ctx.max_relaunch_count
        self.error_monitor = ErrorLogMonitor()
        # the peer-replication plane's view of node liveness: attached
        # by the master (servicer.replica_directory) so every
        # lifecycle-level loss signal this manager sees — watcher
        # FAILED/DELETED events, agent failure reports, heartbeat-loss
        # relaunches — also excludes the node from replica holder lists
        self.replica_directory = None

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        self._init_nodes()
        self._init_managers()
        plan = self._initial_scale_plan()
        if self._job_optimizer is not None:
            self._job_optimizer.update_job_uuid(self._job_args.job_uuid)
            self._job_optimizer.init_job_resource(plan)
        self._scaler.start()
        self._scaler.scale(plan)
        t = threading.Thread(
            target=self._monitor_nodes, name="node-monitor", daemon=True
        )
        t.start()
        self._threads.append(t)
        t2 = threading.Thread(
            target=self._monitor_node_heartbeat, name="heartbeat-monitor",
            daemon=True,
        )
        t2.start()
        self._threads.append(t2)

    def stop(self):
        self._stopped.set()
        self._watcher.stop()
        self._scaler.stop()

    def _init_nodes(self):
        import copy

        for node_type, args in self._job_args.node_args.items():
            group = args.group_resource
            self._job_nodes[node_type] = {
                i: Node(
                    node_type=node_type,
                    node_id=i,
                    rank_index=i,
                    name=f"{self._job_args.job_name}-{node_type}-{i}",
                    # Each node owns its resource: the OOM relaunch path
                    # mutates it, and that must not alias the group spec.
                    config_resource=copy.deepcopy(group.node_resource),
                    max_relaunch_count=args.restart_count,
                    critical=(node_type in (NodeType.PS, NodeType.CHIEF)),
                    slice_index=i // max(self._job_args.node_unit, 1),
                )
                for i in range(group.count)
            }

    def _init_managers(self):
        def name_fn(node_type: str, node_id: int) -> str:
            return f"{self._job_args.job_name}-{node_type}-{node_id}"

        workers = self._job_nodes.setdefault(NodeType.WORKER, {})
        worker_args = self._job_args.node_args.get(NodeType.WORKER)
        self._worker_manager = WorkerManager(
            workers,
            job_resource=worker_args.group_resource if worker_args else None,
            new_node_name_fn=name_fn,
            node_unit=self._job_args.node_unit,
        )
        self._ps_manager = ParameterServerManager(
            self._job_nodes.setdefault(NodeType.PS, {}), name_fn
        )
        self._chief_manager = ChiefManager(
            self._job_nodes.setdefault(NodeType.CHIEF, {}), name_fn
        )
        self._evaluator_manager = EvaluatorManager(
            self._job_nodes.setdefault(NodeType.EVALUATOR, {}), name_fn
        )

    def _initial_scale_plan(self) -> ScalePlan:
        plan = ScalePlan()
        for node_type, args in self._job_args.node_args.items():
            plan.node_group_resources[node_type] = args.group_resource
            plan.launch_nodes.extend(self._job_nodes[node_type].values())
        return plan

    # -- accessors -----------------------------------------------------------

    @property
    def worker_manager(self) -> WorkerManager:
        return self._worker_manager

    @property
    def ps_manager(self) -> ParameterServerManager:
        return self._ps_manager

    def get_job_nodes(self, node_type: str = "") -> Dict:
        if node_type:
            return dict(self._job_nodes.get(node_type, {}))
        return {t: dict(nodes) for t, nodes in self._job_nodes.items()}

    def _get_node(self, node_type: str, node_id: int) -> Optional[Node]:
        return self._job_nodes.get(node_type, {}).get(node_id)

    def _find_node_by_rank(self, node_type: str, rank: int) -> Optional[Node]:
        newest: Optional[Node] = None
        for node in self._job_nodes.get(node_type, {}).values():
            if node.rank_index == rank and not node.is_released:
                if newest is None or node.id > newest.id:
                    newest = node
        return newest

    # -- monitor loop --------------------------------------------------------

    def _monitor_nodes(self):
        while not self._stopped.is_set():
            try:
                for event in self._watcher.watch():
                    if self._stopped.is_set():
                        return
                    self._process_event(event)
            except Exception:  # noqa: BLE001 - monitor must survive
                logger.exception("node watch failed; restarting watch")
                time.sleep(1)

    def _process_event(self, event: NodeEvent):
        evt_node = event.node
        node = self._get_node(evt_node.type, evt_node.id)
        if node is None:
            # Node the master didn't create (e.g. watcher saw it first).
            node = evt_node
            self._job_nodes.setdefault(node.type, {})[node.id] = node
        new_status = (
            NodeStatus.DELETED
            if event.event_type == NodeEventType.DELETED
            else evt_node.status
        )
        # The agent's traceback-based classification (OOM/hardware/fatal)
        # is more specific than the watcher's exit-code guess; only let the
        # watcher overwrite generic or empty reasons.
        if evt_node.exit_reason and node.exit_reason in (
            "", NodeExitReason.UNKNOWN_ERROR, NodeExitReason.KILLED
        ):
            node.exit_reason = evt_node.exit_reason
        flow = get_node_state_flow(node.status, new_status)
        if flow is None:
            return
        node.update_status(new_status)
        logger.info(
            "%s: %s -> %s (exit=%s)",
            node.name, flow.from_status, flow.to_status, node.exit_reason,
        )
        self._fire_callbacks(node, new_status)
        self._note_replica_liveness(node, new_status)
        if flow.should_relaunch and self._should_relaunch(node):
            self._relaunch_node(node)

    def _note_replica_liveness(self, node: Node, status: str):
        """Feed node-lifecycle transitions into the replica directory:
        a dead/failed worker must drop out of recovery-plan holder
        lists immediately (a fetcher pointed at its DRAM can only burn
        the fallback ladder), and a node seen RUNNING again is a
        holder candidate once it re-registers its endpoint."""
        if self.replica_directory is None or node.type != NodeType.WORKER:
            return
        if status in (
            NodeStatus.FAILED, NodeStatus.DELETED, NodeStatus.BREAKDOWN,
        ):
            self.replica_directory.mark_failed(node.id)

    def _fire_callbacks(self, node: Node, status: str):
        ctx = ClusterContext(self)
        for cb in self._callbacks:
            try:
                if status == NodeStatus.RUNNING:
                    cb.on_node_started(node, ctx)
                elif status == NodeStatus.SUCCEEDED:
                    cb.on_node_succeeded(node, ctx)
                elif status == NodeStatus.FAILED:
                    cb.on_node_failed(node, ctx)
                elif status == NodeStatus.DELETED:
                    cb.on_node_deleted(node, ctx)
            except Exception:  # noqa: BLE001
                logger.exception("event callback failed")

    # -- relaunch policy -----------------------------------------------------

    def _should_relaunch(self, node: Node) -> bool:
        if node.is_released or not node.relaunchable:
            return False
        if node.exit_reason == NodeExitReason.FATAL_ERROR and not (
            self._job_args.relaunch_always
        ):
            logger.warning("%s hit a fatal error; not relaunching", node.name)
            return False
        if node.relaunch_count >= node.max_relaunch_count:
            logger.warning(
                "%s exhausted its relaunch budget (%d)",
                node.name, node.max_relaunch_count,
            )
            return False
        # Slice cordon: if the slice this host belongs to keeps flapping
        # (accumulated relaunches past the job-level budget), stop feeding
        # it replacements — the hardware, not the process, is bad.
        if (
            self._slice_relaunches.get(node.slice_index, 0)
            >= self.max_relaunch_count
        ):
            logger.warning(
                "slice %d cordoned after %d relaunches; not relaunching %s",
                node.slice_index,
                self._slice_relaunches[node.slice_index],
                node.name,
            )
            return False
        if node.exit_reason == NodeExitReason.OOM:
            # Grow memory before relaunching (reference: dist_job_manager
            # _should_relaunch OOM path, local_optimizer oom factor ×2).
            factor = self._ctx.oom_memory_factor
            node.config_resource.memory = int(
                node.config_resource.memory * factor
            )
            limit = self._job_args.resource_limits.memory
            if limit and node.config_resource.memory > limit:
                logger.warning("%s OOM beyond the memory limit", node.name)
                return False
        return True

    def _relaunch_node(self, node: Node):
        if node.type == NodeType.WORKER:
            plan = self._worker_manager.relaunch_node(node)
        elif node.type == NodeType.PS:
            plan = self._ps_manager.relaunch_node(node)
        elif node.type == NodeType.CHIEF:
            plan = self._chief_manager.relaunch_node(node)
        elif node.type == NodeType.EVALUATOR:
            plan = self._evaluator_manager.relaunch_node(node)
        else:
            return
        self._slice_relaunches[node.slice_index] = (
            self._slice_relaunches.get(node.slice_index, 0) + 1
        )
        node.inc_relaunch_count()
        self._scaler.scale(plan)

    # -- reports from agents (via servicer) ----------------------------------

    def handle_training_failure(
        self, node_id: int, restart_count: int, error_data: str, level: str
    ):
        reason = self.error_monitor.process_error(
            node_id, restart_count, error_data, level
        )
        node = self._get_node(NodeType.WORKER, node_id) or self._find_node_by_rank(
            NodeType.WORKER, node_id
        )
        if node is None:
            return
        node.update_reported_status(NodeStatus.FAILED)
        self._note_replica_liveness(node, NodeStatus.FAILED)
        # Remember the classified reason so the relaunch decision (made
        # when the watcher sees the pod die) applies the right policy
        # (OOM memory bump, fatal no-relaunch, hardware cordon).
        if not node.exit_reason:
            node.exit_reason = reason

    def update_node_resource_usage(
        self, node_type: str, node_id: int, cpu: float, memory: int
    ):
        node = self._get_node(node_type, node_id)
        if node is None:
            return
        node.update_resource_usage(cpu, memory)
        # Hang heuristic (reference: dist_job_manager.py:618-631): a running
        # node whose CPU usage sits below the threshold for the grace period
        # is marked hung; the hang watchdog in the master main loop acts.
        threshold = self._ctx.hang_cpu_usage_percentage
        if node.status == NodeStatus.RUNNING and cpu < threshold:
            if node.start_hang_time == 0:
                node.start_hang_time = time.time()
        else:
            node.start_hang_time = 0

    def collect_node_heartbeat(self, node_id: int, timestamp: float):
        node = self._find_node_by_rank(NodeType.WORKER, node_id)
        if node is not None:
            node.update_heartbeat(timestamp)

    def update_node_reported_status(self, node_type, node_id, status):
        node = self._get_node(node_type, node_id)
        if node is None:
            node = self._find_node_by_rank(node_type, node_id)
        if node is None:
            return
        node.update_reported_status(status)
        # An agent reporting BREAKDOWN means the host failed the ICI
        # network check: the process is alive but the chip/link is bad, so
        # the watcher will never see a failure — act on the report itself.
        if (
            status == NodeStatus.BREAKDOWN
            and node.status == NodeStatus.RUNNING
            and not node.is_released
        ):
            node.exit_reason = NodeExitReason.HARDWARE_ERROR
            node.update_status(NodeStatus.BREAKDOWN)
            self._fire_callbacks(node, NodeStatus.FAILED)
            if self._should_relaunch(node):
                self._relaunch_node(node)
            else:
                node.is_released = True

    def _monitor_node_heartbeat(self):
        """Relaunch workers whose agent stopped heartbeating."""
        timeout = self._ctx.heartbeat_timeout_secs
        while not self._stopped.is_set():
            now = time.time()
            for node in list(self._job_nodes.get(NodeType.WORKER, {}).values()):
                if (
                    node.status == NodeStatus.RUNNING
                    and not node.is_released
                    and node.heartbeat_time > 0
                    and now - node.heartbeat_time > timeout
                ):
                    logger.warning(
                        "%s heartbeat lost for %.0fs; relaunching",
                        node.name, now - node.heartbeat_time,
                    )
                    node.exit_reason = NodeExitReason.KILLED
                    node.update_status(NodeStatus.FAILED)
                    # Fire callbacks ourselves: the watcher will not emit a
                    # FAILED event for a process that is alive but hung, and
                    # shard recovery / rdzv removal must still happen.
                    self._fire_callbacks(node, NodeStatus.FAILED)
                    if self._should_relaunch(node):
                        self._relaunch_node(node)
                    else:
                        node.is_released = True
            self._stopped.wait(timeout / 3 if timeout > 0 else 10)

    # -- job-level queries ---------------------------------------------------

    def all_workers_exited(self) -> bool:
        return (
            self._worker_manager.all_nodes_exited()
            and self._chief_manager.all_nodes_exited()
            and self._evaluator_manager.all_nodes_exited()
        )

    def all_workers_succeeded(self) -> bool:
        return self._worker_manager.all_nodes_succeeded()

    def all_critical_node_success(self) -> bool:
        critical = [
            n
            for nodes in self._job_nodes.values()
            for n in nodes.values()
            if n.critical and not n.is_released
        ]
        workers = [
            n for n in self._job_nodes.get(NodeType.WORKER, {}).values()
            if not n.is_released
        ]
        pool = critical or workers
        return bool(pool) and all(
            n.status == NodeStatus.SUCCEEDED for n in pool
        )

    def should_early_stop(self) -> bool:
        """All pending nodes stuck beyond the pending timeout ⇒ give up."""
        timeout = self._ctx.seconds_to_wait_pending_pod
        now = time.time()
        pending = [
            n
            for nodes in self._job_nodes.values()
            for n in nodes.values()
            if n.status == NodeStatus.PENDING and not n.is_released
        ]
        if not pending:
            return False
        # Only give up when nothing is running either — a single straggling
        # pod next to a healthy fleet is the auto-scaler's problem, not a
        # reason to kill the job.
        running = [
            n
            for nodes in self._job_nodes.values()
            for n in nodes.values()
            if n.status == NodeStatus.RUNNING and not n.is_released
        ]
        if running:
            return False
        return all(
            n.create_time is not None and now - n.create_time > timeout
            for n in pending
        )

    def detect_hung_nodes(self) -> List[Node]:
        grace = self._ctx.hang_detection_secs
        now = time.time()
        return [
            n
            for n in self._job_nodes.get(NodeType.WORKER, {}).values()
            if n.start_hang_time > 0 and now - n.start_hang_time > grace
        ]

    def remove_worker(self, worker_rank: int):
        """Task-timeout callback target: drop a straggling worker."""
        node = self._find_node_by_rank(NodeType.WORKER, worker_rank)
        if node is not None:
            plan = self._worker_manager.remove_node(node.id)
            self._scaler.scale(plan)

    # -- scaling entry points (used by the auto-scaler) ----------------------

    def _fill_group_resource(self, node_type: str, group: NodeGroupResource):
        """Optimizer plans often carry only a count; inherit the per-node
        resource (cpu/memory/chips/topology) from the job spec so scale-up
        pods still request TPU chips."""
        import copy

        args = self._job_args.node_args.get(node_type)
        if args is None:
            return group
        base = args.group_resource.node_resource
        res = group.node_resource
        if res.cpu == 0 and res.memory == 0 and res.accelerator.chips == 0:
            return NodeGroupResource(
                count=group.count, node_resource=copy.deepcopy(base)
            )
        return group

    def execute_scale_plan(self, plan: ScalePlan):
        if plan.empty():
            return
        for node_type, group in list(plan.node_group_resources.items()):
            group = self._fill_group_resource(node_type, group)
            plan.node_group_resources[node_type] = group
            if node_type == NodeType.WORKER and group.count > 0:
                sub = self._worker_manager.adjust_worker(group)
                plan.launch_nodes.extend(sub.launch_nodes)
                plan.remove_nodes.extend(sub.remove_nodes)
            elif node_type == NodeType.PS and group.count > 0:
                sub = self._ps_manager.adjust_ps(group)
                plan.launch_nodes.extend(sub.launch_nodes)
                plan.remove_nodes.extend(sub.remove_nodes)
        if plan.migrate_nodes:
            sub = self._ps_manager.migrate_parameter_servers(plan.migrate_nodes)
            plan.launch_nodes.extend(sub.launch_nodes)
        plan.ps_addrs = self._ps_manager.get_ps_addrs()
        self._scaler.scale(plan)
