"""Periodic auto-scaling driver.

Role parity: ``dlrover/python/master/node/job_auto_scaler.py``
(``JobAutoScaler``) — a control-loop thread that, once training speed has
stabilized, asks the resource optimizer for a new plan and executes it
through the job manager. Strategy-specific subclasses mirror the
reference's PS vs allreduce split.

TPU-first: worker deltas are whole slices (the job manager's worker
manager rounds to ``node_unit``), and a scale event implies a new
rendezvous round + recompile, so the scaler is deliberately conservative
(stability window before acting).
"""

from __future__ import annotations

import threading
from typing import Optional

from dlrover_tpu.common.config import get_context
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.master.scaler.base_scaler import ScalePlan

logger = get_logger("node.auto_scaler")


class JobAutoScaler:
    def __init__(
        self,
        job_manager,
        job_optimizer,
        speed_monitor,
        interval_secs: Optional[float] = None,
    ):
        self._job_manager = job_manager
        self._job_optimizer = job_optimizer
        self._speed_monitor = speed_monitor
        ctx = get_context()
        self._interval = interval_secs or ctx.seconds_interval_to_optimize
        self._stopped = threading.Event()
        # out-of-band wakeup: a cleared diagnosis verdict (DIAG_RECOVERED
        # / verdict pop) schedules an IMMEDIATE re-evaluation instead of
        # waiting out the rest of the scaler period — recovery latency
        # must not be bounded by the periodic tick
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._last_plan_time = 0.0
        self.started = False
        # serving scale proposals from the SLO policy loop (bounded
        # trail — the operator's `tpurun serve slo` view reads the
        # policy's copy; this one drives execution)
        import collections

        self._serving_proposals: "collections.deque" = (
            collections.deque(maxlen=32))
        self._serving_apply = None

    def start_auto_scaling(self):
        if self.started:
            return
        self.started = True
        self._thread = threading.Thread(
            target=self._periodic_optimize, name="auto-scaler", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stopped.set()
        self._wake.set()  # unblock a loop parked mid-interval

    def request_immediate_evaluation(self):
        """Wake the control loop NOW (verdict recovery listener): the
        next optimize_once runs as soon as the loop services the event
        instead of after the remaining scaler period."""
        self._wake.set()

    # -- serving scale (the SLO policy loop's actuator) ----------------------

    def attach_serving_apply(self, fn):
        """The serving resize actuator: called with each proposal
        dict. Deployment-specific — a standalone job routes it to the
        serve worker's ``request_resize`` (the lease-holding live-
        resize path); a scheduled deployment builds a ScalePlan for
        the serving replica group."""
        self._serving_apply = fn

    def submit_serving_proposal(self, proposal: dict):
        """SLO-policy feed (``ServingScalePolicy``): record the
        proposal, wake the control loop, and execute through the
        attached serving actuator. The training optimize path is
        untouched — serving scale rides the serving live-resize
        mechanics, not a worker-count plan."""
        self._serving_proposals.append(dict(proposal))
        self.request_immediate_evaluation()
        if self._serving_apply is not None:
            try:
                self._serving_apply(dict(proposal))
            except Exception:  # noqa: BLE001 — a failed actuator is
                # the next SLO window's problem; the scaler loop and
                # the proposal trail must survive it
                logger.exception("serving scale apply failed")

    def serving_proposals(self) -> list:
        return [dict(p) for p in self._serving_proposals]

    def _periodic_optimize(self):
        while not self._stopped.is_set():
            self._wake.wait(self._interval)
            self._wake.clear()
            if self._stopped.is_set():
                return
            try:
                self.optimize_once()
            except Exception:  # noqa: BLE001 - control loop must survive
                logger.exception("auto-scale iteration failed")

    def optimize_once(self):
        """One optimize-and-execute step (also the unit-test entry)."""
        import time

        ctx = get_context()
        if not ctx.auto_scale_enabled:
            return
        if (
            self._last_plan_time
            and time.monotonic() - self._last_plan_time
            < ctx.seconds_between_scale_plans
        ):
            return  # cooling down after the previous scale event
        if not self._speed_monitor.worker_adjustment_finished():
            logger.info("waiting for worker count to stabilize")
            return
        # per-node diagnosis verdicts (straggler detector via the speed
        # monitor): an unhealthy node poisons the speed series, so a
        # resize judged on it would chase the symptom — recovery owns
        # the incident; the scaler resumes once the verdicts clear
        unhealthy = list(
            getattr(self._speed_monitor, "unhealthy_nodes", []) or []
        )
        if unhealthy:
            logger.info(
                "skipping speed-based optimization: diagnosis verdicts "
                "active on nodes %s", unhealthy,
            )
            return
        plan = self._job_optimizer.get_job_resource_plan()
        if plan is None or plan.empty():
            return
        self.execute_job_optimization_plan(plan)

    def execute_job_optimization_plan(self, plan: ScalePlan):
        import time

        from dlrover_tpu.telemetry import EventKind, emit_event

        if not plan.recovery and plan.resizes_world_only():
            # a pure world resize is survivable by every node the plan
            # keeps: stamp the live fast path so workers reshard in
            # place instead of restarting (docs/operations.md ladder)
            from dlrover_tpu.trainer.failover import RecoveryDecision

            plan.recovery = RecoveryDecision.LIVE_RESHARD
        logger.info("executing optimization plan: %s", plan.to_dict())
        self._speed_monitor.reset_running_speed_monitor()
        self._last_plan_time = time.monotonic()
        self._job_manager.execute_scale_plan(plan)
        emit_event(EventKind.SCALE_PLAN_APPLIED, plan=plan.to_dict())
