"""Base manager for a homogeneous group of training nodes.

Role parity: ``dlrover/python/master/node/training_node.py``
(``TrainingNodeManager``) — shared relaunch/scale-up/scale-down mechanics
per node type; subclasses add worker/PS-specific policy.
"""

from __future__ import annotations

import itertools
import threading
from typing import Callable, Dict, List, Optional

from dlrover_tpu.common.constants import NodeStatus
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.node import Node, NodeGroupResource
from dlrover_tpu.master.scaler.base_scaler import ScalePlan

logger = get_logger("node.manager")


class TrainingNodeManager:
    def __init__(
        self,
        nodes: Dict[int, Node],
        new_node_name_fn: Optional[Callable[[str, int], str]] = None,
    ):
        self._nodes = nodes
        self._lock = threading.Lock()
        self._new_node_name_fn = new_node_name_fn or (
            lambda node_type, node_id: f"{node_type}-{node_id}"
        )
        self._node_id_iter = itertools.count(
            max(nodes.keys(), default=-1) + 1
        )

    @property
    def cur_nodes(self) -> List[Node]:
        with self._lock:
            return list(self._nodes.values())

    def get_node(self, node_id: int) -> Optional[Node]:
        with self._lock:
            return self._nodes.get(node_id)

    def update_node(self, node: Node):
        with self._lock:
            self._nodes[node.id] = node

    def next_node_id(self) -> int:
        return next(self._node_id_iter)

    # -- relaunch ------------------------------------------------------------

    def relaunch_node(self, node: Node) -> ScalePlan:
        """Build the plan replacing a dead node (rank preserved)."""
        plan = ScalePlan()
        with self._lock:
            node.relaunchable = False
            node.is_released = True
            new_id = self.next_node_id()
            new_node = node.get_relaunch_node(new_id)
            new_node.name = self._new_node_name_fn(node.type, new_id)
            self._nodes[new_id] = new_node
        logger.info("relaunching %s as %s (attempt %d)",
                    node.name, new_node.name, new_node.relaunch_count)
        plan.launch_nodes.append(new_node)
        plan.remove_nodes.append(node)
        return plan

    # -- scale ---------------------------------------------------------------

    def adjust_node(self, group: NodeGroupResource, node_type: str) -> ScalePlan:
        """Scale this group up or down to ``group.count`` alive nodes."""
        plan = ScalePlan()
        plan.node_group_resources[node_type] = group
        alive = [n for n in self.cur_nodes
                 if not n.is_released and not n.exited()]
        delta = group.count - len(alive)
        if delta > 0:
            used_ranks = {n.rank_index for n in alive}
            next_rank = 0
            with self._lock:
                for _ in range(delta):
                    while next_rank in used_ranks:
                        next_rank += 1
                    used_ranks.add(next_rank)
                    new_id = self.next_node_id()
                    node = Node(
                        node_type=node_type,
                        node_id=new_id,
                        rank_index=next_rank,
                        name=self._new_node_name_fn(node_type, new_id),
                        config_resource=group.node_resource,
                    )
                    self._nodes[new_id] = node
                    plan.launch_nodes.append(node)
        elif delta < 0:
            # Remove highest ranks first so the surviving world is contiguous.
            for node in sorted(alive, key=lambda n: -n.rank_index)[: -delta]:
                node.relaunchable = False
                node.is_released = True
                plan.remove_nodes.append(node)
        return plan

    def remove_node(self, node_id: int) -> ScalePlan:
        plan = ScalePlan()
        node = self.get_node(node_id)
        if node is not None and not node.is_released:
            node.relaunchable = False
            node.is_released = True
            plan.remove_nodes.append(node)
        return plan

    def migrate_node(self, node_id: int, resource) -> ScalePlan:
        """Replace one node with a differently-sized one, same rank."""
        plan = ScalePlan()
        old = self.get_node(node_id)
        if old is None:
            return plan
        with self._lock:
            new_id = self.next_node_id()
            new_node = Node(
                node_type=old.type,
                node_id=new_id,
                rank_index=old.rank_index,
                name=self._new_node_name_fn(old.type, new_id),
                config_resource=resource,
            )
            self._nodes[new_id] = new_node
        old.migrated = True
        old.relaunchable = False
        plan.launch_nodes.append(new_node)
        plan.remove_nodes.append(old)
        return plan

    # -- queries -------------------------------------------------------------

    def all_nodes_exited(self) -> bool:
        alive = [n for n in self.cur_nodes if not n.is_released]
        return all(n.exited() for n in alive) if alive else True

    def all_nodes_succeeded(self) -> bool:
        alive = [n for n in self.cur_nodes if not n.is_released]
        return bool(alive) and all(
            n.status == NodeStatus.SUCCEEDED for n in alive
        )

    def has_failed_node(self) -> bool:
        return any(
            n.status == NodeStatus.FAILED and not n.is_released
            for n in self.cur_nodes
        )

    def running_nodes(self) -> List[Node]:
        return [
            n for n in self.cur_nodes
            if n.status == NodeStatus.RUNNING and not n.is_released
        ]

    def pending_nodes(self) -> List[Node]:
        return [
            n for n in self.cur_nodes
            if n.status in (NodeStatus.INITIAL, NodeStatus.PENDING)
            and not n.is_released
        ]
