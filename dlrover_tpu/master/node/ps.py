"""Parameter-server node manager with consistent cluster versioning.

Role parity: ``dlrover/python/master/node/ps.py``
(``ParameterServerManager``) — PS jobs need a *consistent* PS address list
across scale/migration: workers keep training against the current PS
cluster until every new PS is running, then the master announces the next
cluster (``get_next_training_ps_cluster``) and drops the old PSs only after
all workers have switched (``delete_running_ps`` after sync).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from dlrover_tpu.common.constants import NodeStatus, NodeType
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.node import Node, NodeGroupResource, NodeResource
from dlrover_tpu.master.node.training_node import TrainingNodeManager
from dlrover_tpu.master.scaler.base_scaler import ScalePlan

logger = get_logger("node.ps")


class ParameterServerManager(TrainingNodeManager):
    def __init__(self, nodes: Dict[int, Node], new_node_name_fn=None):
        super().__init__(nodes, new_node_name_fn)
        self._training_ps_cluster: List[Node] = []
        self._next_training_ps_cluster: Optional[List[Node]] = None
        self._migrated_ps_nodes: Dict[int, Node] = {}
        self._init_training_ps_cluster()

    def _init_training_ps_cluster(self):
        self._training_ps_cluster = [
            n for n in self.cur_nodes if not n.is_released
        ]

    # -- scale ---------------------------------------------------------------

    def adjust_ps(self, group: NodeGroupResource) -> ScalePlan:
        plan = self.adjust_node(group, NodeType.PS)
        if not plan.empty():
            self._next_training_ps_cluster = None  # recompute on next query
        return plan

    def scale_down_ps(self, down_num: int) -> ScalePlan:
        """Mark the highest-rank PSs for removal *after* workers migrate."""
        plan = ScalePlan()
        alive = [n for n in self.cur_nodes if not n.is_released and not n.exited()]
        for node in sorted(alive, key=lambda n: -n.rank_index)[:down_num]:
            node.relaunchable = False
            # NOT released yet: stays in the current training cluster until
            # workers pick up the next cluster version.
            node.migrated = True
        self._next_training_ps_cluster = None
        return plan

    def migrate_parameter_servers(
        self, ps_resources: Dict[str, NodeResource]
    ) -> ScalePlan:
        """Launch replacement PSs with new resources; old ones stay serving."""
        plan = ScalePlan()
        name_to_node = {n.name: n for n in self.cur_nodes}
        for name, resource in ps_resources.items():
            old = name_to_node.get(name)
            if old is None or old.id in self._migrated_ps_nodes:
                continue
            sub_plan = self.migrate_node(old.id, resource)
            # Keep the old PS serving until the new one is RUNNING.
            old.is_released = False
            plan.launch_nodes.extend(sub_plan.launch_nodes)
            self._migrated_ps_nodes[old.id] = sub_plan.launch_nodes[0]
        self._next_training_ps_cluster = None
        return plan

    # -- cluster versioning --------------------------------------------------

    def get_training_ps_cluster(self) -> List[Node]:
        """The PS set workers should currently be connected to."""
        if not self._training_ps_cluster:
            self._init_training_ps_cluster()
        return [
            n for n in self._training_ps_cluster
            if not n.is_released and n.status != NodeStatus.FAILED
        ]

    def get_next_training_ps_cluster(self) -> List[Node]:
        """The next consistent PS set; only advances when every incoming PS
        is RUNNING (reference: ps.py:198)."""
        if self._next_training_ps_cluster is not None:
            return self._next_training_ps_cluster
        candidates = [
            n for n in self.cur_nodes
            if not n.migrated and not n.is_released and not n.exited()
        ]
        # Migration replacements join once running.
        for old_id, new_node in list(self._migrated_ps_nodes.items()):
            if new_node.status == NodeStatus.RUNNING:
                old = self.get_node(old_id)
                if old is not None:
                    old.is_released = True
                del self._migrated_ps_nodes[old_id]
        if all(n.status == NodeStatus.RUNNING for n in candidates) and candidates:
            self._next_training_ps_cluster = sorted(
                candidates, key=lambda n: n.rank_index
            )
            self._training_ps_cluster = self._next_training_ps_cluster
            return self._next_training_ps_cluster
        return self.get_training_ps_cluster()

    def delete_running_ps(self) -> ScalePlan:
        """Release PSs that scale-down marked, after workers switched."""
        plan = ScalePlan()
        for node in self.cur_nodes:
            if node.migrated and not node.is_released and not node.relaunchable:
                node.is_released = True
                plan.remove_nodes.append(node)
        return plan

    def get_ps_addrs(self) -> List[str]:
        return [
            n.service_addr or n.name
            for n in sorted(self.get_training_ps_cluster(),
                            key=lambda n: n.rank_index)
        ]
