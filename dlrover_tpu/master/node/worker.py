"""Worker / chief / evaluator node managers.

Role parity: ``dlrover/python/master/node/worker.py`` (``WorkerManager``,
``ChiefManager``, ``EvaluatorManager``) — worker-specific policy on top of
``TrainingNodeManager``: elastic scale up/down, dropping workers that never
joined rendezvous, slice-aware removal.

TPU-first: scale deltas are rounded to whole slices (``node_unit`` hosts)
so the surviving world always maps onto complete TPU slices.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from dlrover_tpu.common.constants import NodeStatus, NodeType
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.node import Node, NodeGroupResource
from dlrover_tpu.master.node.training_node import TrainingNodeManager
from dlrover_tpu.master.scaler.base_scaler import ScalePlan

logger = get_logger("node.worker")


class WorkerManager(TrainingNodeManager):
    def __init__(
        self,
        nodes: Dict[int, Node],
        job_resource: Optional[NodeGroupResource] = None,
        new_node_name_fn=None,
        node_unit: int = 1,
    ):
        super().__init__(nodes, new_node_name_fn)
        self._job_resource = job_resource or NodeGroupResource()
        self._node_unit = max(node_unit, 1)

    def adjust_worker(self, group: NodeGroupResource) -> ScalePlan:
        """Scale workers, keeping the count a multiple of the slice size."""
        count = max(
            (group.count // self._node_unit) * self._node_unit,
            self._node_unit,
        )
        rounded = NodeGroupResource(
            count=count, node_resource=group.node_resource
        )
        logger.info("adjust workers -> %d (node_unit=%d)", count, self._node_unit)
        return self.adjust_node(rounded, NodeType.WORKER)

    def remove_not_joined_rdzv_workers(self, worker_ranks: List[int]) -> ScalePlan:
        """Remove running workers that never made it into rendezvous."""
        plan = ScalePlan()
        for node in self.cur_nodes:
            if node.rank_index in worker_ranks and not node.is_released:
                node.relaunchable = False
                node.is_released = True
                plan.remove_nodes.append(node)
        return plan

    def has_exited_worker(self) -> bool:
        return any(
            n.exited() and not n.is_released for n in self.cur_nodes
        )

    def wait_worker_restart(self, max_restart_count: int = 3) -> bool:
        """True if some failed worker still has relaunch budget."""
        return any(
            n.status == NodeStatus.FAILED
            and n.relaunch_count < max_restart_count
            for n in self.cur_nodes
        )


class ChiefManager(TrainingNodeManager):
    """Rank-0 ('chief') nodes of a PS job."""

    def is_chief_running(self) -> bool:
        return any(
            n.status == NodeStatus.RUNNING and not n.is_released
            for n in self.cur_nodes
        )


class EvaluatorManager(TrainingNodeManager):
    def is_evaluator_running(self) -> bool:
        return any(
            n.status == NodeStatus.RUNNING and not n.is_released
            for n in self.cur_nodes
        )
