"""Master-side rendezvous for elastic SPMD training.

Role parity: ``dlrover/python/master/elastic_training/rdzv_manager.py:52-388``
(ElasticTrainingRendezvousManager, NetworkCheckRendezvousManager). Semantics
preserved: a waiting pool completes a round when all max_nodes arrived, or
min_nodes arrived and the waiting timeout passed (rounded down to a multiple
of ``node_unit``); agents poll ``get_comm_world`` until their rank appears.

TPU-first differences:
  * The world handout includes a **jax.distributed coordinator address** (the
    host of the smallest participating rank) — workers bootstrap XLA's
    coordination service from it, in place of the reference handing out a
    torch c10d store.
  * ``node_unit`` is the number of hosts per TPU slice: worlds are trimmed to
    whole slices so every ICI domain is either fully in or fully out.
  * The network check is an ICI/DCN allgather probe; its 2-round paired
    diagnosis grouping (suspects paired with known-good nodes in round 1) is
    kept intact, as it is topology-agnostic.
"""

from __future__ import annotations

import math
import time
from abc import ABC, abstractmethod
from threading import Lock
from typing import Dict, List, Optional, Set, Tuple

from dlrover_tpu.common.config import get_context
from dlrover_tpu.common.constants import RendezvousName
from dlrover_tpu.common.log import get_logger

logger = get_logger("master.rdzv")

_ctx = get_context()


class RendezvousParameters:
    def __init__(self, min_nodes: int = 0, max_nodes: int = 0,
                 waiting_timeout: float = 30.0):
        self.min_nodes = min_nodes
        self.max_nodes = max_nodes
        self.waiting_timeout = waiting_timeout


class WaitingNode:
    """A node sitting in the rendezvous waiting pool."""

    def __init__(self, rank: int, local_world_size: int, node_id: int = -1,
                 addr: str = "", slice_index: int = 0):
        self.rank = rank
        self.local_world_size = local_world_size
        self.node_id = node_id
        self.addr = addr
        self.slice_index = slice_index


class RendezvousManager(ABC):
    def __init__(self):
        self._lock = Lock()
        self._name = ""
        self._alive_nodes: Set[int] = set()
        self._waiting_nodes: Dict[int, WaitingNode] = {}
        self._rdzv_nodes: Dict[int, WaitingNode] = {}
        self._latest_rdzv_ranks: List[int] = []
        self._rdzv_params = RendezvousParameters()
        self._node_unit = 1
        self._rdzv_round = 0
        self._lastcall_time = 0.0

    # -- node lifecycle hooks (called by the job manager) -------------------

    def add_alive_node(self, node_id: int):
        with self._lock:
            self._alive_nodes.add(node_id)

    def remove_alive_node(self, node_id: int):
        with self._lock:
            self._alive_nodes.discard(node_id)
            for rank, wn in list(self._waiting_nodes.items()):
                if wn.node_id == node_id:
                    self._waiting_nodes.pop(rank, None)

    def update_rdzv_params(self, min_nodes: int, max_nodes: int,
                           waiting_timeout: float, node_unit: int):
        with self._lock:
            self._rdzv_params.min_nodes = min_nodes
            self._rdzv_params.max_nodes = max_nodes
            self._rdzv_params.waiting_timeout = waiting_timeout
            self._node_unit = max(1, node_unit)
            logger.info(
                "%s rdzv params: min=%d max=%d timeout=%.1f node_unit=%d",
                self._name, min_nodes, max_nodes, waiting_timeout, node_unit,
            )

    def rdzv_params_set(self) -> bool:
        return self._rdzv_params.max_nodes > 0

    # -- the rendezvous protocol -------------------------------------------

    def join_rendezvous(self, rank: int, local_world_size: int,
                        node_id: int = -1, addr: str = "",
                        slice_index: int = 0) -> int:
        """Add a node to the waiting pool; returns the current round."""
        with self._lock:
            if rank not in self._waiting_nodes:
                self._waiting_nodes[rank] = WaitingNode(
                    rank, local_world_size, node_id, addr, slice_index
                )
                self._on_join()
                self._rdzv_nodes = {}
                self._lastcall_time = time.time()
                logger.info(
                    "%s: rank %d joined; waiting=%s", self._name, rank,
                    sorted(self._waiting_nodes),
                )
        return self._rdzv_round

    def _on_join(self):
        """Subclass hook invoked (under lock) when a new node joins."""

    def _check_rdzv_completed(self) -> bool:
        """Complete the round if possible; moves waiting -> rdzv nodes.

        Completion rule (reference ``_check_rdzv_completed:106``): everyone
        arrived, or >= min_nodes arrived and no new join for waiting_timeout.
        The admitted set is the lowest ``k*node_unit`` ranks so TPU slices
        stay whole.
        """
        waiting_num = len(self._waiting_nodes)
        if waiting_num == 0 or not self.rdzv_params_set():
            return False
        completed = False
        if waiting_num >= self._rdzv_params.max_nodes:
            completed = True
            waiting_num = self._rdzv_params.max_nodes
        else:
            elapsed = time.time() - self._lastcall_time
            if (
                waiting_num >= self._rdzv_params.min_nodes
                and elapsed >= self._rdzv_params.waiting_timeout
            ):
                completed = True
        if not completed:
            return False
        waiting_num = (waiting_num // self._node_unit) * self._node_unit
        if waiting_num < max(1, self._rdzv_params.min_nodes):
            return False
        admitted = sorted(self._waiting_nodes)[:waiting_num]
        self._rdzv_nodes = {r: self._waiting_nodes[r] for r in admitted}
        self._latest_rdzv_ranks = admitted
        for r in admitted:
            self._waiting_nodes.pop(r)
        self._lastcall_time = 0.0
        logger.info(
            "%s: round %d completed with ranks %s",
            self._name, self._rdzv_round, admitted,
        )
        return True

    def world_dict(self) -> Dict[int, int]:
        return {r: wn.local_world_size for r, wn in self._rdzv_nodes.items()}

    def coordinator_addr(self) -> str:
        """Host of the smallest rank in the completed world."""
        if not self._rdzv_nodes:
            return ""
        return self._rdzv_nodes[min(self._rdzv_nodes)].addr

    def num_nodes_waiting(self) -> int:
        """Nonzero tells agents to restart workers into a new world.

        A *re-joining* node (was in the last completed world) always forces
        a restart; brand-new nodes only once a whole node_unit (slice) of
        them is available (reference ``num_nodes_waiting:169``).
        """
        with self._lock:
            if any(
                r in self._latest_rdzv_ranks for r in self._waiting_nodes
            ):
                return len(self._waiting_nodes)
            if len(self._waiting_nodes) >= self._node_unit:
                return len(self._waiting_nodes)
            return 0

    def not_joined_rdzv_nodes(self) -> List[int]:
        with self._lock:
            if not self._rdzv_nodes:
                return []
            joined = {wn.node_id for wn in self._rdzv_nodes.values()}
            return [n for n in self._alive_nodes if n not in joined]

    @property
    def rdzv_round(self) -> int:
        return self._rdzv_round

    @abstractmethod
    def get_comm_world(self, rank: int) -> Tuple[int, int, Dict[int, int], str]:
        """Returns (round, group, world, coordinator_addr)."""

    @abstractmethod
    def report_network_check_result(self, rank: int, normal: bool,
                                    elapsed: float = 0.0):
        ...


class ElasticTrainingRendezvousManager(RendezvousManager):
    """The rendezvous agents use to (re)build the training world."""

    def __init__(self):
        super().__init__()
        self._name = RendezvousName.TRAINING

    def get_comm_world(self, rank: int) -> Tuple[int, int, Dict[int, int], str]:
        with self._lock:
            if not self._rdzv_nodes:
                if self._check_rdzv_completed():
                    self._rdzv_round += 1
            return (
                self._rdzv_round,
                0,
                self.world_dict(),
                self.coordinator_addr(),
            )

    def report_network_check_result(self, rank, normal, elapsed=0.0):
        pass


class NetworkCheckRendezvousManager(RendezvousManager):
    """Paired-allgather fault localization over ICI/DCN.

    Two probe rounds per check (reference ``NetworkCheckRendezvousManager``):
      round 0: nodes paired (i, i+1); each pair runs an allgather probe.
      round 1: each suspect from round 0 is re-paired with a known-good
               node; a node failing both rounds is the faulty one.
    """

    CHECK_ROUNDS = 2

    def __init__(self):
        super().__init__()
        self._name = RendezvousName.NETWORK_CHECK
        self._node_status: Dict[int, bool] = {}
        self._node_times: Dict[int, float] = {}
        self._reported_nodes: Set[int] = set()
        self._node_groups: List[Dict[int, int]] = []

    def _on_join(self):
        self._node_groups = []

    def get_comm_world(self, rank: int) -> Tuple[int, int, Dict[int, int], str]:
        with self._lock:
            if not self._node_groups:
                if self._check_rdzv_completed():
                    self._node_groups = self._group_nodes(self._rdzv_round)
                    logger.info(
                        "network-check round %d groups: %s",
                        self._rdzv_round, self._node_groups,
                    )
                    if self._rdzv_round % self.CHECK_ROUNDS == 0:
                        self._node_status = {}
                        self._node_times = {}
                    self._reported_nodes = set()
                    self._rdzv_round += 1
            for i, group in enumerate(self._node_groups):
                if rank in group:
                    addr = ""
                    if self._rdzv_nodes:
                        addr = self._rdzv_nodes[min(group)].addr
                    return self._rdzv_round, i, group, addr
            return self._rdzv_round, 0, self.world_dict(), self.coordinator_addr()

    def _group_nodes(self, rdzv_round: int) -> List[Dict[int, int]]:
        rdzv_round = rdzv_round % self.CHECK_ROUNDS
        groups: List[Dict[int, int]] = []
        world = self.world_dict()
        if rdzv_round == 0:
            group: Dict[int, int] = {}
            for r in sorted(world):
                group[r] = world[r]
                if len(group) == 2:
                    groups.append(group)
                    group = {}
            if group:
                if groups:
                    groups[-1].update(group)
                else:
                    groups.append(group)
        else:
            suspects = [r for r, ok in self._node_status.items() if not ok]
            normals = [r for r, ok in self._node_status.items() if ok]
            if len(suspects) > len(normals):
                # cannot pair every suspect with a good node; whole-fabric
                # problem — leave groups empty so the check fails loudly.
                logger.warning(
                    "network-check: %d suspects > %d normal nodes",
                    len(suspects), len(normals),
                )
                return groups
            for i, suspect in enumerate(suspects):
                groups.append({
                    suspect: world.get(suspect, 1),
                    normals[i]: world.get(normals[i], 1),
                })
            rest = {
                r: world.get(r, 1) for r in normals[len(suspects):]
            }
            if rest:
                groups.append(rest)
        return groups

    def join_rendezvous(self, rank, local_world_size, node_id=-1, addr="",
                        slice_index=0) -> int:
        return super().join_rendezvous(
            rank, local_world_size, node_id, addr, slice_index
        )

    def report_network_check_result(self, rank: int, normal: bool,
                                    elapsed: float = 0.0):
        with self._lock:
            if self._rdzv_nodes and rank not in self._rdzv_nodes:
                logger.warning(
                    "ignoring network-check report from rank %d outside "
                    "the current probe world %s", rank,
                    sorted(self._rdzv_nodes),
                )
                return
            self._reported_nodes.add(rank)
            self._node_status[rank] = self._node_status.get(rank, False) or normal
            if elapsed:
                self._node_times[rank] = elapsed
            if len(self._reported_nodes) == len(self._rdzv_nodes):
                logger.info(
                    "network-check statuses after round %d: %s",
                    self._rdzv_round, self._node_status,
                )

    def network_check_success(self) -> Tuple[bool, str]:
        """(success, reason); reason is WAITING_NODE while reports pending."""
        with self._lock:
            if len(self._reported_nodes) < len(self._rdzv_nodes) or not \
                    self._rdzv_nodes:
                return False, "waiting"
            success = bool(self._node_status) and all(
                self._node_status.values()
            )
            if success:
                # snap the round forward to a multiple of CHECK_ROUNDS so the
                # next check starts a fresh 2-round cycle.
                self._rdzv_round = (
                    math.ceil(self._rdzv_round / self.CHECK_ROUNDS)
                    * self.CHECK_ROUNDS
                )
                return True, ""
            return False, "node-failure"

    def abnormal_nodes(self) -> List[int]:
        with self._lock:
            return [r for r, ok in self._node_status.items() if not ok]

    def straggler_nodes(self, slow_factor: float = 2.0) -> List[int]:
        """Ranks whose probe time exceeds slow_factor x median."""
        with self._lock:
            if len(self._node_times) < 2:
                return []
            times = sorted(self._node_times.values())
            median = times[len(times) // 2]
            if median <= 0:
                return []
            return [
                r for r, t in self._node_times.items()
                if t > slow_factor * median
            ]
