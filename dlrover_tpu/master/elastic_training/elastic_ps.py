"""Elastic PS cluster-version handshake.

Role parity: ``dlrover/python/master/elastic_training/elastic_ps.py`` — for
parameter-server jobs, workers/PS negotiate a monotonically increasing
cluster version so every process agrees which PS membership it is running
against after a migration or scale event.
"""

from __future__ import annotations

import threading
from typing import Dict


class ElasticPsService:
    GLOBAL = "global"
    LOCAL = "local"
    RESTORED = "restored"

    def __init__(self):
        self._lock = threading.Lock()
        self._global_version = 0
        self._node_versions: Dict[str, Dict[int, Dict[str, int]]] = {}

    def inc_global_cluster_version(self):
        with self._lock:
            self._global_version += 1

    def get_cluster_version(self, version_type: str, task_type: str,
                            task_id: int) -> int:
        with self._lock:
            if version_type == self.GLOBAL:
                return self._global_version
            return (
                self._node_versions.get(task_type, {})
                .get(task_id, {})
                .get(version_type, 0)
            )

    def update_cluster_version(self, version_type: str, version: int,
                               task_type: str, task_id: int):
        with self._lock:
            if version_type == self.GLOBAL:
                self._global_version = version
                return
            self._node_versions.setdefault(task_type, {}).setdefault(
                task_id, {}
            )[version_type] = version
