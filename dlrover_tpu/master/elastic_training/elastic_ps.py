"""Elastic PS cluster-version handshake.

Role parity: ``dlrover/python/master/elastic_training/elastic_ps.py`` — for
parameter-server jobs, workers/PS negotiate a monotonically increasing
cluster version so every process agrees which PS membership it is running
against after a migration or scale event.
"""

from __future__ import annotations

import threading
from typing import Dict


class ElasticPsService:
    GLOBAL = "global"
    LOCAL = "local"
    RESTORED = "restored"

    def __init__(self):
        self._lock = threading.Lock()
        self._global_version = 0
        self._node_versions: Dict[str, Dict[int, Dict[str, int]]] = {}

    def inc_global_cluster_version(self):
        with self._lock:
            self._global_version += 1

    def get_cluster_version(self, version_type: str, task_type: str,
                            task_id: int) -> int:
        with self._lock:
            if version_type == self.GLOBAL:
                return self._global_version
            return (
                self._node_versions.get(task_type, {})
                .get(task_id, {})
                .get(version_type, 0)
            )

    def update_cluster_version(self, version_type: str, version: int,
                               task_type: str, task_id: int,
                               expected: int = -1) -> bool:
        """Set a version; with ``expected >= 0`` this is an atomic
        compare-and-set (applied only while the current value equals
        ``expected``), so concurrent workers bumping GLOBAL cannot
        clobber each other's read-modify-write."""
        with self._lock:
            if version_type == self.GLOBAL:
                if expected >= 0 and self._global_version != expected:
                    return False
                self._global_version = version
                return True
            node = self._node_versions.setdefault(task_type, {}).setdefault(
                task_id, {}
            )
            if expected >= 0 and node.get(version_type, 0) != expected:
                return False
            node[version_type] = version
            return True
