"""Named barriers/joins across workers.

Role parity: ``dlrover/python/master/elastic_training/sync_service.py`` —
used by failover flows that need all live workers to reach a point before
the job proceeds (e.g. PS cluster refresh, coordinated restart).
"""

from __future__ import annotations

import threading
from typing import Dict, Set

from dlrover_tpu.common.log import get_logger

logger = get_logger("master.sync")


class SyncService:
    def __init__(self):
        self._lock = threading.Lock()
        self._syncs: Dict[str, Set[int]] = {}
        self._finished_syncs: Set[str] = set()
        self._barriers: Set[str] = set()
        self._expected_count = 0

    def set_expected_count(self, count: int):
        with self._lock:
            self._expected_count = count

    def join_sync(self, sync_name: str, node_rank: int) -> bool:
        """A worker joins a named sync point; True once all have joined."""
        with self._lock:
            members = self._syncs.setdefault(sync_name, set())
            members.add(node_rank)
            if self._expected_count and len(members) >= self._expected_count:
                self._finished_syncs.add(sync_name)
            return sync_name in self._finished_syncs

    def sync_finished(self, sync_name: str) -> bool:
        with self._lock:
            return sync_name in self._finished_syncs

    def force_finish(self, sync_name: str):
        with self._lock:
            self._finished_syncs.add(sync_name)

    def notify_barrier(self, barrier_name: str):
        with self._lock:
            self._barriers.add(barrier_name)

    def barrier_reached(self, barrier_name: str) -> bool:
        with self._lock:
            return barrier_name in self._barriers

    def remove_exited_worker(self, node_rank: int):
        with self._lock:
            for members in self._syncs.values():
                members.discard(node_rank)
