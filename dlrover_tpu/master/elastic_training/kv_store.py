"""Master-hosted KV store.

Role parity: ``dlrover/python/master/elastic_training/kv_store_service.py``.
Agents use it as a tiny coordination store scoped per rendezvous round
(prefix keys); training processes bootstrap jax.distributed from the
coordinator address instead, so this store stays off the hot path.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional


class KVStoreService:
    def __init__(self):
        self._lock = threading.Lock()
        self._store: Dict[str, str] = {}

    def set(self, key: str, value: str):
        with self._lock:
            self._store[key] = value

    def get(self, key: str) -> Optional[str]:
        with self._lock:
            return self._store.get(key)

    def add(self, key: str, amount: int) -> int:
        """Atomic counter add; returns the new value."""
        with self._lock:
            val = int(self._store.get(key, "0")) + amount
            self._store[key] = str(val)
            return val

    def delete(self, key: str):
        with self._lock:
            self._store.pop(key, None)

    def clear(self, prefix: str = ""):
        with self._lock:
            if not prefix:
                self._store.clear()
            else:
                for k in [k for k in self._store if k.startswith(prefix)]:
                    del self._store[k]
