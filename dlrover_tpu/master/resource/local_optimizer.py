"""Single-job resource optimizer from locally-collected stats.

Role parity: ``dlrover/python/master/resource/local_optimizer.py``
(``PSLocalOptimizer``) — heuristics over the LocalStatsReporter's runtime
samples: initial plans, worker count from PS-CPU headroom, hot-PS
migration, OOM memory growth.

TPU-first addition: an SPMD optimizer whose unit of scaling is a whole
slice and whose signal is step-speed trend rather than PS utilization.
"""

from __future__ import annotations

import statistics
from abc import ABC, abstractmethod
from typing import List, Optional

from dlrover_tpu.common.config import get_context
from dlrover_tpu.common.constants import NodeType
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.node import NodeGroupResource, NodeResource
from dlrover_tpu.master.resource.plan import ResourcePlan
from dlrover_tpu.master.stats.reporter import LocalStatsReporter, StatsReporter
from dlrover_tpu.master.stats.training_metrics import RuntimeMetric

logger = get_logger("resource.local_optimizer")

_WORKER_DEFAULT = NodeResource(cpu=4, memory=8192)
_PS_DEFAULT = NodeResource(cpu=8, memory=16384)


class ResourceOptimizer(ABC):
    """Backend interface (local heuristics here; brain RPC in brain/)."""

    @abstractmethod
    def generate_opt_plan(self, stage: str = "") -> Optional[ResourcePlan]:
        ...

    def update_job_uuid(self, job_uuid: str):
        ...


class PSLocalOptimizer(ResourceOptimizer):
    """PS-strategy heuristics (reference: PSLocalOptimizer)."""

    def __init__(self, job_name: str, resource_limits=None):
        self._stats: LocalStatsReporter = StatsReporter.new_stats_reporter(job_name)
        self._limits = resource_limits
        self._ctx = get_context()

    # -- plans ---------------------------------------------------------------

    def generate_job_create_resource(self) -> ResourcePlan:
        plan = ResourcePlan()
        plan.node_group_resources[NodeType.PS] = NodeGroupResource(
            count=1, node_resource=_PS_DEFAULT
        )
        plan.node_group_resources[NodeType.WORKER] = NodeGroupResource(
            count=1, node_resource=_WORKER_DEFAULT
        )
        return plan

    def generate_ps_initial_resource(self) -> ResourcePlan:
        """Size the PS group from dataset/model stats once they exist."""
        plan = ResourcePlan()
        model = self._stats.model_metric
        ps_count = 1
        memory = _PS_DEFAULT.memory
        if model is not None and model.param_count > 0:
            # 4 bytes/param + optimizer slots ≈ 16 bytes/param, split over PSs.
            total_mb = int(model.param_count * 16 / (1024 * 1024)) + 2048
            ps_count = max(1, min(8, total_mb // _PS_DEFAULT.memory + 1))
            memory = max(_PS_DEFAULT.memory, total_mb // ps_count)
        plan.node_group_resources[NodeType.PS] = NodeGroupResource(
            count=ps_count,
            node_resource=NodeResource(cpu=_PS_DEFAULT.cpu, memory=memory),
        )
        return plan

    def generate_worker_resource(self) -> ResourcePlan:
        """Grow workers while PS CPU has headroom (reference :187-229)."""
        plan = ResourcePlan()
        samples = self._recent_samples(8)
        if len(samples) < 2:
            return plan
        ps_util = self._max_ps_cpu_util(samples)
        cur_workers = self._running_count(samples[-1], NodeType.WORKER)
        if cur_workers == 0 or ps_util <= 0:
            return plan
        threshold = self._ctx.optimize_worker_cpu_threshold
        if ps_util >= threshold:
            # PS saturated: adding workers only adds contention.
            return plan
        # Linear model: PS load scales with worker count. Grow to the
        # worker count that would bring the hottest PS to the threshold.
        target = int(cur_workers * threshold / max(ps_util, 1e-6))
        target = max(cur_workers + 1, min(target, cur_workers * 2))
        if self._limits is not None and self._limits.cpu:
            sample = samples[-1]
            per_worker_cpu = self._group_cpu(sample, NodeType.WORKER) / cur_workers
            max_workers = int(self._limits.cpu // max(per_worker_cpu, 0.1))
            target = min(target, max_workers)
        if target > cur_workers:
            plan.node_group_resources[NodeType.WORKER] = NodeGroupResource(
                count=target, node_resource=NodeResource()
            )
        return plan

    def generate_hot_ps_migration(self) -> ResourcePlan:
        """Migrate PSs whose CPU runs at >90% of request to 2× CPU."""
        plan = ResourcePlan()
        samples = self._recent_samples(4)
        if not samples:
            return plan
        latest = samples[-1]
        for entry in latest.running_nodes.get(NodeType.PS, []):
            req = max(entry.get("cpu", 0), 0.1)
            used = entry.get("used_cpu", 0)
            if used / req > 0.9:
                name = entry.get("name", f"ps-{entry['id']}")
                plan.node_resources[name] = NodeResource(
                    cpu=req * 2, memory=entry.get("memory", _PS_DEFAULT.memory)
                )
        return plan

    def generate_oom_recovery_plan(
        self, node_name: str, current: NodeResource
    ) -> NodeResource:
        factor = self._ctx.oom_memory_factor
        return NodeResource(cpu=current.cpu, memory=int(current.memory * factor))

    def generate_opt_plan(self, stage: str = "") -> Optional[ResourcePlan]:
        from dlrover_tpu.common.constants import JobStage

        if stage == JobStage.CREATE:
            return self.generate_job_create_resource()
        if stage == JobStage.WORKER_INITIAL:
            return self.generate_ps_initial_resource()
        plan = self.generate_worker_resource()
        hot = self.generate_hot_ps_migration()
        plan.node_resources.update(hot.node_resources)
        return plan

    # -- helpers -------------------------------------------------------------

    def _recent_samples(self, n: int) -> List[RuntimeMetric]:
        return self._stats.runtime_stats[-n:]

    @staticmethod
    def _running_count(sample: RuntimeMetric, node_type: str) -> int:
        return len(sample.running_nodes.get(node_type, []))

    @staticmethod
    def _group_cpu(sample: RuntimeMetric, node_type: str) -> float:
        return sum(
            e.get("cpu", 0) for e in sample.running_nodes.get(node_type, [])
        )

    @staticmethod
    def _max_ps_cpu_util(samples: List[RuntimeMetric]) -> float:
        utils = []
        for s in samples:
            for e in s.running_nodes.get(NodeType.PS, []):
                req = max(e.get("cpu", 0), 0.1)
                utils.append(e.get("used_cpu", 0) / req)
        return max(utils) if utils else 0.0


class SpmdLocalOptimizer(ResourceOptimizer):
    """Allreduce/SPMD-strategy optimizer (reference:
    AllreduceJobResourceOptimizer, re-thought for TPU slices).

    The only lever is the number of worker hosts (in whole slices); the
    signal is whether per-step speed still improves when workers are added,
    read from the runtime-sample history.
    """

    def __init__(self, job_name: str, node_unit: int = 1, max_workers: int = 0):
        self._stats: LocalStatsReporter = StatsReporter.new_stats_reporter(job_name)
        self._node_unit = max(node_unit, 1)
        self._max_workers = max_workers

    def generate_opt_plan(self, stage: str = "") -> Optional[ResourcePlan]:
        plan = ResourcePlan()
        samples = self._stats.runtime_stats[-12:]
        if len(samples) < 4:
            return plan
        cur_workers = len(samples[-1].running_nodes.get(NodeType.WORKER, []))
        if cur_workers == 0:
            return plan
        # Per-worker efficiency trend: speed / workers over the window.
        half = len(samples) // 2
        # The judged tail must be entirely at the CURRENT membership —
        # judging stale pre-scale samples right after a scale event would
        # propose another scale-up before the new world produced a single
        # post-scale sample (observed in the e2e loop: 1 -> 2 -> 3).
        tail_counts = {
            len(s.running_nodes.get(NodeType.WORKER, []))
            for s in samples[half:]
        }
        if tail_counts != {cur_workers}:
            return plan
        older = [s for s in samples[:half] if s.speed > 0]
        newer = [s for s in samples[half:] if s.speed > 0]
        if not older or not newer:
            return plan
        eff_old = statistics.mean(
            s.speed / max(len(s.running_nodes.get(NodeType.WORKER, [])), 1)
            for s in older
        )
        eff_new = statistics.mean(
            s.speed / max(len(s.running_nodes.get(NodeType.WORKER, [])), 1)
            for s in newer
        )
        # Scaling still pays off if per-worker efficiency held up (>90%).
        if eff_new >= 0.9 * eff_old:
            target = cur_workers + self._node_unit
            if self._max_workers and target > self._max_workers:
                return plan
            plan.node_group_resources[NodeType.WORKER] = NodeGroupResource(
                count=target, node_resource=NodeResource()
            )
        return plan
