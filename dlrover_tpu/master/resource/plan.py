"""Resource plans produced by optimizers.

Role parity: ``dlrover/python/common/resource``-style plan objects the
reference passes between optimizer and job manager (``ResourcePlan`` with
per-type ``NodeGroupResource`` plus per-node migrations).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict

from dlrover_tpu.common.node import NodeGroupResource, NodeResource
from dlrover_tpu.master.scaler.base_scaler import ScalePlan


@dataclass
class ResourcePlan:
    # Target group sizes per node type.
    node_group_resources: Dict[str, NodeGroupResource] = field(default_factory=dict)
    # name -> new resource, for in-place migrations (hot PS).
    node_resources: Dict[str, NodeResource] = field(default_factory=dict)

    def empty(self) -> bool:
        return not self.node_group_resources and not self.node_resources

    def to_scale_plan(self) -> ScalePlan:
        plan = ScalePlan()
        plan.node_group_resources.update(self.node_group_resources)
        plan.migrate_nodes.update(self.node_resources)
        return plan
