"""Stage machine driving resource optimization across the job lifecycle.

Role parity: ``dlrover/python/master/resource/job.py``
(``JobResourceOptimizer`` with CREATE → WORKER_INITIAL → RUNNING stages;
``PSJobResourceOptimizer`` / ``AllreduceJobResourceOptimizer``) — decides
*when* to consult the optimizer backend and merges its plan into the job's
group resources.
"""

from __future__ import annotations

from typing import Optional

from dlrover_tpu.common.constants import DistributionStrategy, JobStage, NodeType
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.master.resource.local_optimizer import (
    PSLocalOptimizer,
    ResourceOptimizer,
    SpmdLocalOptimizer,
)
from dlrover_tpu.master.resource.plan import ResourcePlan
from dlrover_tpu.master.scaler.base_scaler import ScalePlan
from dlrover_tpu.scheduler.job import JobArgs

logger = get_logger("resource.job_optimizer")


def new_resource_optimizer(
    optimize_mode: str, job_args: JobArgs
) -> ResourceOptimizer:
    if optimize_mode == "cluster":
        # Cluster mode delegates to the brain service when configured;
        # constructed lazily so the master runs without it.
        try:
            from dlrover_tpu.brain.client import BrainResourceOptimizer

            return BrainResourceOptimizer(job_args.job_name)
        except Exception:  # noqa: BLE001
            logger.warning("brain unavailable; falling back to local optimizer")
    if job_args.distribution_strategy == DistributionStrategy.PS:
        return PSLocalOptimizer(job_args.job_name, job_args.resource_limits)
    worker_args = job_args.worker_args()
    max_workers = 0
    if job_args.resource_limits.chips and worker_args is not None:
        per_host = worker_args.group_resource.node_resource.accelerator.chips
        if per_host > 0:
            max_workers = job_args.resource_limits.chips // per_host
    return SpmdLocalOptimizer(
        job_args.job_name, node_unit=job_args.node_unit, max_workers=max_workers
    )


class JobResourceOptimizer:
    def __init__(self, job_args: JobArgs, optimizer: Optional[ResourceOptimizer] = None):
        self._job_args = job_args
        self._optimizer = optimizer or new_resource_optimizer(
            job_args.optimize_mode, job_args
        )
        self._stage = JobStage.CREATE
        self._job_uuid = ""

    @property
    def stage(self) -> str:
        return self._stage

    def update_job_uuid(self, job_uuid: str):
        self._job_uuid = job_uuid
        self._optimizer.update_job_uuid(job_uuid)

    def init_job_resource(self, plan: ScalePlan):
        """CREATE stage: fill in group resources the user left at zero."""
        if self._job_args.optimize_mode == "manual":
            self._stage = JobStage.RUNNING
            return
        opt = self._optimizer.generate_opt_plan(JobStage.CREATE)
        if opt is not None:
            for node_type, group in opt.node_group_resources.items():
                cur = plan.node_group_resources.get(node_type)
                if cur is None:
                    continue
                if cur.count == 0:
                    cur.count = group.count
                if cur.node_resource.cpu == 0:
                    cur.node_resource.cpu = group.node_resource.cpu
                if cur.node_resource.memory == 0:
                    cur.node_resource.memory = group.node_resource.memory
        self._stage = JobStage.WORKER_INITIAL

    def get_job_resource_plan(self) -> Optional[ScalePlan]:
        """RUNNING-stage plan for the auto-scaler."""
        if self._job_args.optimize_mode == "manual":
            return None
        if self._stage == JobStage.WORKER_INITIAL:
            self._stage = JobStage.RUNNING
        opt = self._optimizer.generate_opt_plan(self._stage)
        if opt is None or opt.empty():
            return None
        plan = opt.to_scale_plan()
        # In-place migrations ride along as resource updates; the job
        # manager's migrate path handles names.
        return plan
