"""Master-side replica directory: who holds whose snapshot regions.

The worker side (``checkpoint.replication``) pushes and serves bytes;
this directory owns the two decisions that must be cluster-consistent:

1. **Assignment** — each owner's k replica peers, chosen by rendezvous
   (HRW) hashing over the registered group: every (owner, peer) pair
   gets a stable hash rank, so a node joining or leaving only remaps
   the pairs that involve it. A resize does NOT reshuffle the whole
   assignment — replicas that survived the change stay valid, which is
   what makes the plan "rendezvous-stable" across elasticity.
2. **Admission** — the replica budget is priced against the hosts'
   declared DRAM budgets (the PR 8 host-accounting posture) BEFORE a
   plan ships: with k replicas each holder carries k × (snapshot /
   group) bytes of peer state; if any holder's declared budget cannot
   fit its share, k degrades until the plan fits (terminally to 0,
   plane off) with a logged verdict — an infeasible plan ships fewer
   replicas, it never OOMs a worker.

On a node-loss verdict (the PR 6 diagnosis plane's hang verdicts, or a
hard failure report through the servicer/job manager), the lost node is
excluded from holder lists, and ``recovery_plan`` maps every owner's
regions to the surviving holders a rebuilding worker should stream
from — owner first when alive (its own store has its freshest regions),
then its HRW peers.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Any, Dict, List, Optional

from dlrover_tpu.common.log import get_logger

logger = get_logger("master.replication")


def hrw_peers(owner: int, group: List[int], k: int) -> List[int]:
    """Highest-random-weight peer ranking: deterministic, stable under
    membership changes (a departed node drops out of the ranking
    without permuting the survivors' relative order)."""
    others = [n for n in sorted(set(group)) if n != owner]

    def weight(peer: int) -> str:
        return hashlib.md5(f"{owner}|{peer}".encode()).hexdigest()

    return sorted(others, key=weight)[:max(0, k)]


class ReplicaDirectory:
    """Registered replica endpoints + the assignment/admission logic."""

    def __init__(self, liveness_secs: float = 600.0):
        self._lock = threading.Lock()
        self._liveness = float(liveness_secs)
        # node_id -> {"addr", "budget_mb", "snapshot_mb", "step", "ts"}
        self._nodes: Dict[int, Dict[str, Any]] = {}
        self._failed: set = set()
        self._last_degraded: Optional[int] = None

    # -- ingest --------------------------------------------------------------

    def register(self, node_id: int, addr: str, budget_mb: float,
                 snapshot_mb: float, step: int,
                 ts: Optional[float] = None,
                 push_seconds: float = 0.0, push_bytes: float = 0.0):
        with self._lock:
            self._nodes[int(node_id)] = {
                "addr": addr, "budget_mb": float(budget_mb),
                "snapshot_mb": float(snapshot_mb), "step": int(step),
                "ts": float(ts if ts is not None else time.time()),
                # last completed push cycle's wall/bytes: the readiness
                # auditor's continuous calibration of the rebuild
                # transfer path (a push frames+streams the same bytes a
                # rebuild fetches back, over the same RPC path)
                "push_seconds": float(push_seconds),
                "push_bytes": float(push_bytes),
            }
            # a re-registering node is alive again, whatever we thought
            self._failed.discard(int(node_id))

    def mark_failed(self, node_id: int):
        """Exclude a node from holder lists (hard failure report or a
        diagnosis hang verdict): its DRAM is gone or unreachable, so a
        recovery plan must not send fetchers there first."""
        with self._lock:
            if int(node_id) in self._nodes:
                self._failed.add(int(node_id))

    def on_verdict(self, node_id: int, verdict: str):
        """StragglerDetector verdict listener: a node-hang verdict is
        the diagnosis plane's node-loss signal; recovery ("healthy")
        restores the node to the holder pool."""
        from dlrover_tpu.master.monitor.straggler import (
            VERDICT_HEALTHY,
            VERDICT_HUNG,
        )

        if verdict == VERDICT_HUNG:
            self.mark_failed(node_id)
        elif verdict == VERDICT_HEALTHY:
            with self._lock:
                self._failed.discard(int(node_id))

    # -- views ---------------------------------------------------------------

    def _live(self) -> List[int]:
        """Endpoints alive right now."""
        now = time.time()
        return sorted(
            n for n, info in self._nodes.items()
            if n not in self._failed
            and now - info["ts"] <= self._liveness
        )

    def _lends_dram(self, node_id: int) -> bool:
        """A node with a NEGATIVE declared budget lends no DRAM to
        peers: never a PEER-replica holder (it still serves its own
        regions — self commits are budget-exempt on the store)."""
        return self._nodes[node_id]["budget_mb"] >= 0

    def _owners(self) -> List[int]:
        """Nodes that own snapshot regions (they declared a snapshot
        size). A store-only endpoint — a peer lending DRAM without
        training state of its own — is a holder candidate but never
        part of the byte partition: a partition that counted it would
        wait forever for regions it will never push."""
        return sorted(
            n for n, info in self._nodes.items()
            if info["snapshot_mb"] > 0
        )

    def admitted_replicas(self, requested: int) -> Dict[str, Any]:
        """Price the replica budget BEFORE admitting a plan: degrade k
        until every holder's declared DRAM budget fits its share."""
        with self._lock:
            live = self._live()
            lenders = [n for n in live if self._lends_dram(n)]
            owners = [n for n in self._owners() if n in set(live)]
            group = owners or live
            if len(live) < 2 or not lenders:
                return {"replicas": 0, "requested": requested,
                        "group": group, "live": lenders,
                        "degraded": requested > 0,
                        "reason": "fewer than 2 live replica endpoints"}
            share_mb = {
                n: self._nodes[n]["snapshot_mb"] / max(1, len(group))
                for n in group
            }
            k = min(int(requested), max(0, len(lenders) - 1),
                    len(live) - 1)
            reason = ""
            load = {n: 0.0 for n in lenders}
            assignments: Dict[int, List[int]] = {}
            while k > 0:
                load = {n: 0.0 for n in lenders}
                assignments = {
                    owner: hrw_peers(owner, lenders, k)
                    for owner in group
                }
                for owner in group:
                    for peer in assignments[owner]:
                        load[peer] += share_mb.get(owner, 0.0)
                over = [
                    n for n in lenders
                    if self._nodes[n]["budget_mb"] > 0
                    and load[n] > self._nodes[n]["budget_mb"]
                ]
                if not over:
                    break
                worst = max(over, key=lambda n: load[n])
                reason = (
                    f"holder {worst} budget "
                    f"{self._nodes[worst]['budget_mb']:.0f} MB < "
                    f"assigned {load[worst]:.0f} MB at k={k}"
                )
                k -= 1
            if k == 0:
                assignments = {owner: [] for owner in group}
                load = {n: 0.0 for n in lenders}
            degraded = k < int(requested)
            # "live" is the PEER-holder candidate pool: only nodes
            # that lend DRAM (plan_for draws assignments from it).
            # "assignments"/"load"/"headroom_mb" are the ADMITTED
            # plan's facts — what the readiness gauges and the
            # durability audit sweep against.
            return {"replicas": k, "requested": int(requested),
                    "group": group, "live": lenders,
                    "degraded": degraded,
                    "reason": reason if degraded else "",
                    "assignments": assignments,
                    "load": load,
                    "headroom_mb": {
                        n: self._nodes[n]["budget_mb"] - load[n]
                        for n in lenders
                        if self._nodes[n]["budget_mb"] > 0
                    }}

    def plan_for(self, node_id: int, requested: int) -> Dict[str, Any]:
        admitted = self.admitted_replicas(requested)
        k = admitted["replicas"]
        group = sorted(set(admitted["group"]) | {int(node_id)})
        with self._lock:
            peers = [
                {"node_id": p, "addr": self._nodes[p]["addr"]}
                for p in hrw_peers(
                    int(node_id), admitted.get("live", []), k)
                if p in self._nodes
            ]
        if admitted["degraded"] and self._last_degraded != k:
            self._last_degraded = k
            logger.warning(
                "replica plan degraded to k=%d (requested %d): %s",
                k, requested, admitted["reason"] or "not enough peers",
            )
        return {**admitted, "owner": int(node_id), "group": group,
                "peers": peers}

    def recovery_plan(self, requested: int,
                      for_node: int = -1) -> Dict[str, Any]:
        """Owner -> ordered live holder endpoints. Order per owner: the
        owner itself when alive (its own store holds its freshest
        regions), then its HRW peers — failed/dead nodes excluded, so a
        fetcher walks exactly the fallback ladder the assignment
        promised. Owners include DEAD nodes: the lost node's regions
        are precisely what a rebuild needs, served by its surviving
        peers."""
        with self._lock:
            now = time.time()
            live = set(
                n for n, info in self._nodes.items()
                if n not in self._failed
                and now - info["ts"] <= self._liveness
            )
            owner_ids = sorted(
                n for n, info in self._nodes.items()
                if info["snapshot_mb"] > 0
            )
            # peer candidates must LEND DRAM; the owner itself is a
            # valid holder regardless (its own regions are budget-
            # exempt on its store)
            holder_pool = sorted(
                n for n in self._nodes if self._lends_dram(n))
            k = min(int(requested), max(0, len(holder_pool) - 1))
            owners: Dict[str, List[Dict[str, Any]]] = {}
            for owner in owner_ids:
                # the FULL HRW ranking, not top-k: pushes were assigned
                # over the live set AT PUSH TIME, which may differ from
                # today's pool (a node failed before the push reshapes
                # the top-k) — truncating here could omit the one peer
                # that actually holds the data. Listing every live node
                # costs the fetcher only cheap inventory RPCs; the
                # inventory sweep picks the holders that really carry
                # the step.
                candidates = [owner] + hrw_peers(
                    owner, holder_pool, len(holder_pool))
                owners[str(owner)] = [
                    {"node_id": c, "addr": self._nodes[c]["addr"]}
                    for c in candidates if c in live
                ]
            return {
                "owners": owners,
                "replicas": k,
                "group": owner_ids,
                "live": sorted(live),
                "failed": sorted(self._failed),
                "for_node": int(for_node),
            }

    def to_report(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "nodes": {
                    str(n): {k: v for k, v in info.items()}
                    for n, info in self._nodes.items()
                },
                "failed": sorted(self._failed),
            }
