"""ElasticJob / ScalePlan custom-resource types.

Role parity: ``dlrover/go/operator/api/v1alpha1/elasticjob_types.go:29-100``
and ``scaleplan_types.go:29-80``. CRs are plain dicts on the wire (what
the k8s API returns); these helpers give them a typed veneer the
reconcilers use, plus the phase constants of the Go ``commonv1`` package.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from dlrover_tpu.scheduler.kubernetes import (
    ELASTICJOB_GROUP,
    ELASTICJOB_VERSION,
)

API_VERSION = f"{ELASTICJOB_GROUP}/{ELASTICJOB_VERSION}"


class JobPhase:
    CREATED = "Created"
    PENDING = "Pending"
    RUNNING = "Running"
    SCALING = "Scaling"
    SUCCEEDED = "Succeeded"
    FAILED = "Failed"


@dataclass
class ReplicaSpec:
    """Per-replica-type spec (reference: ReplicaSpec with RestartCount/
    AutoScale/Priority)."""

    replicas: int = 0
    cpu: float = 1.0
    memory_mb: int = 1024
    tpu_chips: int = 0
    tpu_topology: str = ""
    tpu_accelerator: str = ""
    image: str = ""
    command: List[str] = field(default_factory=list)
    restart_count: int = 3
    auto_scale: bool = True
    priority: str = ""

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ReplicaSpec":
        res = d.get("resources", {})
        return cls(
            replicas=int(d.get("replicas", 0)),
            cpu=float(res.get("cpu", 1)),
            memory_mb=int(res.get("memory", 1024)),
            tpu_chips=int(res.get("tpu", 0)),
            tpu_topology=d.get("tpuTopology", ""),
            tpu_accelerator=d.get("tpuAccelerator", ""),
            image=d.get("image", ""),
            command=list(d.get("command", [])),
            restart_count=int(d.get("restartCount", 3)),
            auto_scale=bool(d.get("autoScale", True)),
            priority=d.get("priority", ""),
        )


@dataclass
class ElasticJob:
    name: str
    namespace: str = "default"
    # k8s metadata.uid: durable per job INSTANCE — a deleted-and-
    # recreated job gets a new one, the provenance token for
    # checkpoint staging (NodeEnv.RUN_ID)
    uid: str = ""
    distribution_strategy: str = "spmd"
    optimize_mode: str = "single-job"
    enable_dynamic_sharding: bool = True
    enable_elastic_scheduling: bool = True
    node_unit: int = 1
    envs: Dict[str, str] = field(default_factory=dict)
    replica_specs: Dict[str, ReplicaSpec] = field(default_factory=dict)
    resource_limits: Dict[str, float] = field(default_factory=dict)
    phase: str = JobPhase.CREATED
    raw: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, cr: Dict[str, Any]) -> "ElasticJob":
        meta = cr.get("metadata", {})
        spec = cr.get("spec", {})
        status = cr.get("status", {})
        return cls(
            name=meta.get("name", ""),
            namespace=meta.get("namespace", "default"),
            uid=meta.get("uid", ""),
            distribution_strategy=spec.get("distributionStrategy", "spmd"),
            optimize_mode=spec.get("optimizeMode", "single-job"),
            enable_dynamic_sharding=spec.get("enableDynamicSharding", True),
            enable_elastic_scheduling=spec.get(
                "enableElasticScheduling", True
            ),
            node_unit=int(spec.get("nodeUnit", 1)),
            envs=dict(spec.get("envs", {})),
            replica_specs={
                t: ReplicaSpec.from_dict(s)
                for t, s in spec.get("replicaSpecs", {}).items()
            },
            resource_limits=dict(spec.get("resourceLimits", {})),
            phase=status.get("phase", JobPhase.CREATED) or JobPhase.CREATED,
            raw=cr,
        )


@dataclass
class ScalePlan:
    name: str
    owner_job: str = ""
    replica_resource_specs: Dict[str, Dict[str, Any]] = field(
        default_factory=dict
    )
    create_pods: List[Dict[str, Any]] = field(default_factory=list)
    remove_pods: List[str] = field(default_factory=list)
    migrate_pods: List[Dict[str, Any]] = field(default_factory=list)
    ps_hosts: List[str] = field(default_factory=list)
    phase: str = JobPhase.PENDING
    raw: Dict[str, Any] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, cr: Dict[str, Any]) -> "ScalePlan":
        meta = cr.get("metadata", {})
        spec = cr.get("spec", {})
        status = cr.get("status", {})
        return cls(
            name=meta.get("name", ""),
            owner_job=spec.get("ownerJob", ""),
            replica_resource_specs=dict(spec.get("replicaResourceSpecs", {})),
            create_pods=list(spec.get("createPods", [])),
            remove_pods=list(spec.get("removePods", [])),
            migrate_pods=list(spec.get("migratePods", [])),
            ps_hosts=list(spec.get("psHosts", [])),
            phase=status.get("phase", JobPhase.PENDING) or JobPhase.PENDING,
            raw=cr,
        )


def elastic_job_cr(
    name: str,
    replica_specs: Dict[str, Dict[str, Any]],
    namespace: str = "default",
    distribution_strategy: str = "spmd",
    optimize_mode: str = "single-job",
    node_unit: int = 1,
    envs: Optional[Dict[str, str]] = None,
) -> Dict[str, Any]:
    """Author an ElasticJob CR body (what a user would kubectl-apply)."""
    return {
        "apiVersion": API_VERSION,
        "kind": "ElasticJob",
        "metadata": {"name": name, "namespace": namespace},
        "spec": {
            "distributionStrategy": distribution_strategy,
            "optimizeMode": optimize_mode,
            "nodeUnit": node_unit,
            "envs": envs or {},
            "replicaSpecs": replica_specs,
        },
        "status": {"phase": JobPhase.CREATED},
    }
