"""ElasticJob + ScalePlan reconcilers.

Role parity: ``dlrover/go/operator/pkg/controllers/
elasticjob_controller.go:47-284`` (phase switch reconciler) and
``scaleplan_controller.go``; master pod/service construction parity with
``controllers/master/master.go:53,145``.

The architecture is master-centric exactly like the reference: the
operator only bootstraps one master pod + service per job and relays
user-authored ScalePlans; the master does node lifecycle itself. The
reconcilers are pure logic over an injectable API client, so they run
against the real kubernetes package or the test fake identically.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.operator.types import ElasticJob, JobPhase, ScalePlan
from dlrover_tpu.scheduler.kubernetes import (
    ELASTICJOB_PLURAL,
    SCALEPLAN_PLURAL,
    build_pod_spec,
)

logger = get_logger("operator.controller")

MASTER_PORT = 50001


def master_pod_name(job_name: str) -> str:
    return f"elasticjob-{job_name}-master"


def master_service_name(job_name: str) -> str:
    return f"elasticjob-{job_name}-master"


def master_addr(job_name: str, namespace: str) -> str:
    return (
        f"{master_service_name(job_name)}.{namespace}.svc:{MASTER_PORT}"
    )


def build_master_pod(job: ElasticJob, master_image: str) -> Dict[str, Any]:
    """The per-job DLRover master pod (reference master.go:53
    ``newJobMaster``)."""
    node_num = sum(s.replicas for s in job.replica_specs.values())
    pod = build_pod_spec(
        job_name=job.name,
        pod_name=master_pod_name(job.name),
        node_type="master",
        node_id=0,
        rank_index=0,
        image=master_image,
        command=[
            "python", "-m", "dlrover_tpu.master.main",
            "--platform", "k8s",
            "--job_name", job.name,
            "--namespace", job.namespace,
            "--port", str(MASTER_PORT),
            "--node_num", str(node_num),
        ],
        cpu=2,
        memory_mb=4096,
        env={
            **job.envs,
            "DLROVER_JOB_NAME": job.name,
            # job-UID-based fence, inherited by the master's Scaler and
            # re-issued to every worker: stable across master restarts
            # within this job instance, rotates when the job is deleted
            # and recreated (checkpoint staging provenance)
            **({NodeEnv.RUN_ID: f"{job.name}-{job.uid}"}
               if job.uid else {}),
        },
    )
    pod["metadata"]["labels"]["elasticjob-role"] = "master"
    return pod


def build_master_service(job: ElasticJob) -> Dict[str, Any]:
    """ClusterIP service fronting the master (reference master.go:145)."""
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": master_service_name(job.name),
            "namespace": job.namespace,
            "labels": {"elasticjob-name": job.name},
        },
        "spec": {
            "selector": {
                "elasticjob-name": job.name,
                "elasticjob-role": "master",
            },
            "ports": [{"port": MASTER_PORT, "targetPort": MASTER_PORT}],
        },
    }


class ElasticJobReconciler:
    def __init__(self, client, master_image: str = "dlrover-tpu:latest"):
        self._client = client
        self._master_image = master_image

    # -- reconcile entry (reference :108 reconcileJobs) ---------------------

    def reconcile(self, cr: Dict[str, Any]) -> None:
        job = ElasticJob.from_dict(cr)
        if job.phase in ("", JobPhase.CREATED):
            self._initialize_job(job)
        elif job.phase in (JobPhase.PENDING, JobPhase.RUNNING):
            self._handle_fault_master(job)
            # a user-authored Pending ScalePlan moves the job to Scaling
            if self._execute_pending_scaleplans(job):
                self._set_job_phase(job, JobPhase.SCALING)
            else:
                self._sync_job_state(job)
        elif job.phase == JobPhase.SCALING:
            self._execute_pending_scaleplans(job)
            if not self._has_active_scaleplans(job):
                # all plans terminal: fall back to tracking the master pod
                self._set_job_phase(job, JobPhase.RUNNING)
            self._sync_job_state(job)
        elif job.phase in (JobPhase.SUCCEEDED, JobPhase.FAILED):
            self._stop_running_pods(job)
        else:
            logger.warning("job %s unknown phase %s", job.name, job.phase)

    # -- phase handlers -----------------------------------------------------

    def _initialize_job(self, job: ElasticJob) -> None:
        """Created: bootstrap the master pod + service, move to Pending."""
        pods = self._job_pods(job.name)
        if not any(self._is_master(p) for p in pods):
            self._client.create_pod(build_master_pod(job, self._master_image))
            self._client.create_service(build_master_service(job))
            logger.info("job %s: created master pod + service", job.name)
        self._set_job_phase(job, JobPhase.PENDING)

    def _sync_job_state(self, job: ElasticJob) -> None:
        """Pending/Running: job phase tracks the master pod phase
        (reference: SyncJobState via master pod conditions)."""
        master = self._master_pod(job.name)
        if master is None:
            return
        pod_phase = master.get("status", {}).get("phase", "")
        next_phase = {
            "Running": JobPhase.RUNNING,
            "Succeeded": JobPhase.SUCCEEDED,
            "Failed": JobPhase.FAILED,
        }.get(pod_phase)
        if next_phase and next_phase != job.phase:
            self._set_job_phase(job, next_phase)

    def _handle_fault_master(self, job: ElasticJob) -> None:
        """Recreate a dead master pod (reference: HandleFaultPods)."""
        master = self._master_pod(job.name)
        if master is None or master.get("status", {}).get("phase") == "Failed":
            if master is not None:
                self._client.delete_pod(master_pod_name(job.name))
            self._client.create_pod(build_master_pod(job, self._master_image))
            logger.info("job %s: relaunched master pod", job.name)

    def _execute_pending_scaleplans(self, job: ElasticJob) -> int:
        """Relay Pending plans; returns how many were moved to Scaling."""
        relayed = 0
        for cr in self._client.list_custom_resources(SCALEPLAN_PLURAL):
            plan = ScalePlan.from_dict(cr)
            if plan.owner_job != job.name or plan.phase != JobPhase.PENDING:
                continue
            # mark Scaling; the master's scale-plan watcher acts on it and
            # the reconciler marks it Succeeded once replicas match
            self._set_scaleplan_phase(plan, JobPhase.SCALING)
            logger.info("job %s: scaleplan %s -> Scaling", job.name,
                        plan.name)
            relayed += 1
        return relayed

    def _has_active_scaleplans(self, job: ElasticJob) -> bool:
        for cr in self._client.list_custom_resources(SCALEPLAN_PLURAL):
            plan = ScalePlan.from_dict(cr)
            if plan.owner_job == job.name and plan.phase in (
                JobPhase.PENDING, JobPhase.SCALING
            ):
                return True
        return False

    def _stop_running_pods(self, job: ElasticJob) -> None:
        for pod in self._job_pods(job.name):
            phase = pod.get("status", {}).get("phase", "")
            if phase in ("Pending", "Running"):
                self._client.delete_pod(pod["metadata"]["name"])

    # -- helpers ------------------------------------------------------------

    @staticmethod
    def _is_master(pod: Dict[str, Any]) -> bool:
        return pod.get("metadata", {}).get("labels", {}).get(
            "elasticjob-role"
        ) == "master"

    def _job_pods(self, job_name: str) -> List[Dict[str, Any]]:
        return self._client.list_pods(
            label_selector=f"elasticjob-name={job_name}"
        ) or []

    def _master_pod(self, job_name: str) -> Optional[Dict[str, Any]]:
        for pod in self._job_pods(job_name):
            if self._is_master(pod):
                return pod
        return None

    def _set_job_phase(self, job: ElasticJob, phase: str) -> None:
        job.raw.setdefault("status", {})["phase"] = phase
        job.raw["status"]["lastTransitionTime"] = time.time()
        self._client.update_custom_resource_status(
            ELASTICJOB_PLURAL, job.name, job.raw
        )
        job.phase = phase

    def _set_scaleplan_phase(self, plan: ScalePlan, phase: str) -> None:
        plan.raw.setdefault("status", {})["phase"] = phase
        self._client.update_custom_resource_status(
            SCALEPLAN_PLURAL, plan.name, plan.raw
        )
        plan.phase = phase


class ScalePlanReconciler:
    """Marks relayed ScalePlans terminal (reference
    ``scaleplan_controller.go``): a Scaling plan whose owner job's
    replica counts match the plan is Succeeded."""

    def __init__(self, client):
        self._client = client

    def reconcile(self, cr: Dict[str, Any]) -> None:
        plan = ScalePlan.from_dict(cr)
        if plan.phase != JobPhase.SCALING:
            return
        pods = self._client.list_pods(
            label_selector=f"elasticjob-name={plan.owner_job}"
        ) or []
        by_type: Dict[str, int] = {}
        for pod in pods:
            labels = pod.get("metadata", {}).get("labels", {})
            if labels.get("elasticjob-role") == "master":
                continue
            phase = pod.get("status", {}).get("phase", "")
            if phase in ("Pending", "Running"):
                t = labels.get("replica-type", "worker")
                by_type[t] = by_type.get(t, 0) + 1
        wanted = {
            t: int(spec.get("replicas", 0))
            for t, spec in plan.replica_resource_specs.items()
        }
        if all(by_type.get(t, 0) >= n for t, n in wanted.items()):
            plan.raw.setdefault("status", {})["phase"] = JobPhase.SUCCEEDED
            self._client.update_custom_resource_status(
                SCALEPLAN_PLURAL, plan.name, plan.raw
            )


def run_operator(
    client,
    master_image: str = "dlrover-tpu:latest",
    poll_interval: float = 3.0,
    max_rounds: int = 0,
) -> None:
    """Poll-based control loop over both CR kinds. With a real client this
    would hang off watch events; polling keeps the logic identical for
    the test fake (``max_rounds`` bounds it for tests)."""
    job_rec = ElasticJobReconciler(client, master_image)
    plan_rec = ScalePlanReconciler(client)
    rounds = 0
    while True:
        for cr in client.list_custom_resources(ELASTICJOB_PLURAL) or []:
            try:
                job_rec.reconcile(cr)
            except Exception:  # noqa: BLE001 — one bad CR must not stop all
                logger.exception("reconcile failed for %s",
                                 cr.get("metadata", {}).get("name"))
        for cr in client.list_custom_resources(SCALEPLAN_PLURAL) or []:
            try:
                plan_rec.reconcile(cr)
            except Exception:  # noqa: BLE001
                logger.exception("scaleplan reconcile failed")
        rounds += 1
        if max_rounds and rounds >= max_rounds:
            return
        time.sleep(poll_interval)
