"""MTTR + recovery-count reports derived from the event timeline.

Replaces hand-assembled artifacts: instead of a bench script timing one
staged kill, recovery time is *derived* from the same JSONL the
production components emit at every lifecycle edge. Each failure-edge
event is paired with the first later recovery-edge event of a
compatible kind:

  failure edge            recovery edge            scenario
  ---------------------   ----------------------   ----------------------
  worker_failed           workers_started          crash/SIGKILL relaunch
  hang_detected           workers_started          hang relaunch
  nonfinite_step          rollback_restored        NaN rollback
  preempt_notice          preempt_drain_done       preemption drain
  live_reshard_begin      live_reshard_done        in-process reshard
  optimizer_apply_begin   optimizer_apply_done     live re-plan apply

Durations use the monotonic clock when both events came from the same
process (exact), else wall clocks (cross-process, e.g. agent-side
relaunch edges vs worker-side failure edges). Multiple failure edges
before one recovery edge collapse into ONE incident (a burst of
per-rank failure reports is one recovery), anchored at the first edge.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from dlrover_tpu.telemetry.names import EventKind

# failure kind -> {recovery kinds}, with a scenario label for the report
_PAIRINGS = {
    EventKind.WORKER_FAILED: (
        {EventKind.WORKERS_STARTED}, "worker_failure"),
    EventKind.HANG_DETECTED: (
        {EventKind.WORKERS_STARTED}, "hang"),
    EventKind.NONFINITE_STEP: (
        {EventKind.ROLLBACK_RESTORED}, "nonfinite_rollback"),
    EventKind.PREEMPT_NOTICE: (
        {EventKind.PREEMPT_DRAIN_DONE}, "preemption_drain"),
    EventKind.LIVE_RESHARD_BEGIN: (
        {EventKind.LIVE_RESHARD_DONE}, "live_reshard"),
    # checkpoint-free recovery: a rebuilding worker streaming its state
    # out of surviving peers' DRAM instead of an Orbax restore (the
    # recovery-ladder rung between live reshard and storage restore).
    # FALLBACK also closes the incident: a mid-transfer terminal
    # failure degrades to the storage rung — the rebuild attempt is
    # over either way, and an open incident would wrongly flag a
    # by-design degradation as unrecovered.
    EventKind.PEER_REBUILD_BEGIN: (
        {EventKind.PEER_REBUILD_DONE, EventKind.PEER_REBUILD_FALLBACK},
        "peer_rebuild"),
    # a runtime-optimizer plan applying live (drain -> retune/reshard ->
    # resume): not a failure, but downtime the loop chose to spend — the
    # ledger and the recovery report must both see it
    EventKind.OPTIMIZER_APPLY_BEGIN: (
        {EventKind.OPTIMIZER_APPLY_DONE}, "replan"),
    # the serving world resizing live (drain decode window -> snapshot
    # params+KV pages -> reshard): requests are HELD across it, so
    # this interval is exactly the per-request latency bump a resize
    # costs — the serving tier's recovery scenario
    EventKind.SERVE_RESIZE_BEGIN: (
        {EventKind.SERVE_RESIZE_DONE}, "serving_resize"),
    # a confirmed serving SLO violation -> its recovery: the interval
    # the SLO-driven scale policy is judged on (detection latency +
    # proposal + resize + burn-down), distinct from the resize pause
    # itself (serving_resize) which it usually contains
    EventKind.SERVE_SLO_VIOLATION: (
        {EventKind.SERVE_SLO_RECOVERED}, "serving_scale"),
    # the durability audit's cluster posture edge: some node's owner
    # regions at risk (coverage / staleness / budget) -> all clear.
    # Degraded-but-alive like serving_scale — training continues, so
    # goodput surfaces it as an overlap COLUMN, never a wall bucket —
    # but the interval is exactly the exposure window an operator is
    # judged on, so the recovery report prices it like any incident.
    EventKind.READINESS_DEGRADED: (
        {EventKind.READINESS_RESTORED}, "durability_at_risk"),
}


def _delta_seconds(failure: Dict, recovery: Dict) -> float:
    if (
        failure.get("pid") == recovery.get("pid")
        and "mono" in failure and "mono" in recovery
    ):
        return max(0.0, recovery["mono"] - failure["mono"])
    return max(0.0, recovery.get("ts", 0.0) - failure.get("ts", 0.0))


def derive_incidents(events: List[Dict]) -> List[Dict]:
    """Pair failure edges with recovery edges into incident records."""
    ordered = sorted(events, key=lambda r: r.get("ts", 0.0))
    incidents: List[Dict] = []
    open_incident: Dict[str, Optional[Dict]] = {
        scenario: None for _, (_r, scenario) in _PAIRINGS.items()
    }
    for rec in ordered:
        kind = rec.get("kind", "")
        pairing = _PAIRINGS.get(kind)
        if pairing is not None:
            _, scenario = pairing
            # a burst of failure edges before recovery = ONE incident,
            # anchored at the FIRST edge (that is when downtime began)
            if open_incident.get(scenario) is None:
                open_incident[scenario] = rec
            continue
        for scenario, failure in list(open_incident.items()):
            if failure is None:
                continue
            recovery_kinds = next(
                rk for fk, (rk, sc) in _PAIRINGS.items() if sc == scenario
            )
            if kind in recovery_kinds:
                incidents.append({
                    "scenario": scenario,
                    "failure_kind": failure.get("kind"),
                    "recovery_kind": kind,
                    "error_code": failure.get("error_code", ""),
                    "node": failure.get("node", ""),
                    "started_ts": failure.get("ts"),
                    "recovered_ts": rec.get("ts"),
                    "recovery_seconds": round(
                        _delta_seconds(failure, rec), 3),
                })
                open_incident[scenario] = None
    # unrecovered failures are reported too — a dashboard that hides
    # the incident still in progress is worse than none
    for scenario, failure in open_incident.items():
        if failure is not None:
            incidents.append({
                "scenario": scenario,
                "failure_kind": failure.get("kind"),
                "recovery_kind": None,
                "error_code": failure.get("error_code", ""),
                "node": failure.get("node", ""),
                "started_ts": failure.get("ts"),
                "recovered_ts": None,
                "recovery_seconds": None,
            })
    incidents.sort(key=lambda i: i.get("started_ts") or 0.0)
    return incidents


def mttr_report(events: List[Dict], target_s: float = 90.0) -> Dict:
    """The machine-verifiable recovery artifact, derived."""
    incidents = derive_incidents(events)
    recovered = [
        i for i in incidents if i["recovery_seconds"] is not None
    ]
    durations = [i["recovery_seconds"] for i in recovered]
    by_scenario: Dict[str, Dict] = {}
    for inc in recovered:
        s = by_scenario.setdefault(
            inc["scenario"], {"count": 0, "total_s": 0.0, "max_s": 0.0}
        )
        s["count"] += 1
        s["total_s"] += inc["recovery_seconds"]
        s["max_s"] = max(s["max_s"], inc["recovery_seconds"])
    for s in by_scenario.values():
        s["mean_s"] = round(s["total_s"] / s["count"], 3)
        s["total_s"] = round(s["total_s"], 3)
    value = (
        round(sum(durations) / len(durations), 3) if durations else 0.0
    )
    report = {
        "metric": "recovery_mttr_s",
        "value": value,
        "unit": "s",
        "vs_baseline": round(value / target_s, 3) if durations else 0.0,
        "detail": {
            "incidents": len(incidents),
            "recovered": len(recovered),
            "unrecovered": len(incidents) - len(recovered),
            "max_s": round(max(durations), 3) if durations else 0.0,
            "by_scenario": by_scenario,
            "source": "event_timeline",
        },
    }
    if len(incidents) > len(recovered):
        report["error"] = (
            f"{len(incidents) - len(recovered)} incident(s) without a "
            f"recovery edge in the timeline"
        )
    return report
