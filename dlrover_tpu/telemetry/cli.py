"""``python -m dlrover_tpu.telemetry`` — the observability CLI.

  mttr     derive the MTTR / recovery-count report from an event
           timeline (replaces hand-maintained MTTR.json artifacts)
  events   pretty-print a timeline (newest last)
  metrics  dump Prometheus exposition: a live endpoint via --addr, or
           this process's registry (useful under ``tpurun metrics``)
  trace    export the current process's span ring as Chrome/Perfetto
           trace JSON
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m dlrover_tpu.telemetry",
        description="dlrover_tpu observability: MTTR derivation, event "
                    "timeline, metrics exposition, trace export",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    mttr = sub.add_parser(
        "mttr", help="derive MTTR from an event timeline JSONL")
    mttr.add_argument("--events", default="",
                      help="timeline path (default: the configured "
                           "DLROVER_TPU_EVENTS_FILE sink)")
    mttr.add_argument("--out", default="",
                      help="also write the JSON report to this path")
    mttr.add_argument("--target", type=float, default=90.0,
                      help="MTTR target seconds for vs_baseline "
                           "(default 90)")

    ev = sub.add_parser("events", help="print a timeline")
    ev.add_argument("--events", default="", help="timeline path")
    ev.add_argument("--tail", type=int, default=0,
                    help="only the last N records")
    ev.add_argument("--kind", default="",
                    help="filter to one event kind")

    met = sub.add_parser("metrics", help="dump Prometheus exposition")
    met.add_argument("--addr", default="",
                     help="scrape a live exporter at host:port instead "
                          "of dumping this process's registry")

    tr = sub.add_parser("trace", help="export span ring as Chrome JSON")
    tr.add_argument("--out", default="trace.json")

    cache = sub.add_parser(
        "cache", help="persistent XLA compile-cache stats (dir, entry "
                      "count, this process's hit/miss traffic)")
    cache.add_argument("--dir", default=None,
                       help="un-fingerprinted cache root (default: the "
                            "active/env-configured one)")
    return p


def _resolve_events_path(arg: str) -> Optional[str]:
    from dlrover_tpu.telemetry import events as events_mod

    return arg or events_mod.default_events_path()


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.cmd == "mttr":
        from dlrover_tpu.telemetry import events as events_mod
        from dlrover_tpu.telemetry.mttr import mttr_report

        path = _resolve_events_path(args.events)
        if not path:
            print("mttr: no timeline (pass --events or set "
                  "DLROVER_TPU_EVENTS_FILE)", file=sys.stderr)
            return 2
        records = events_mod.read_events(path)
        report = mttr_report(records, target_s=args.target)
        line = json.dumps(report)
        print(line)
        if args.out:
            with open(args.out, "w") as fh:
                fh.write(line + "\n")
        return 1 if report.get("error") else 0

    if args.cmd == "events":
        from dlrover_tpu.telemetry import events as events_mod

        path = _resolve_events_path(args.events)
        records = (
            events_mod.read_events(path) if path
            else events_mod.recent_events()
        )
        if args.kind:
            records = [r for r in records if r.get("kind") == args.kind]
        if args.tail:
            records = records[-args.tail:]
        for rec in records:
            print(json.dumps(rec, sort_keys=True))
        return 0

    if args.cmd == "metrics":
        if args.addr:
            from dlrover_tpu.telemetry.exporter import fetch_metrics

            try:
                status, body = fetch_metrics(args.addr)
            except OSError as e:
                print(f"metrics: scrape of {args.addr} failed: {e}",
                      file=sys.stderr)
                return 2
            sys.stdout.write(body)
            return 0 if status == 200 else 1
        from dlrover_tpu.telemetry.metrics import process_registry

        sys.stdout.write(process_registry().render_prometheus())
        return 0

    if args.cmd == "trace":
        from dlrover_tpu.telemetry import tracing

        n = tracing.export_chrome_trace(args.out)
        print(f"wrote {n} span(s) to {args.out}")
        return 0

    if args.cmd == "cache":
        from dlrover_tpu.utils.compile_cache import cache_stats

        stats = cache_stats(args.dir)
        print(json.dumps(stats))
        return 0 if stats["configured"] else 1

    return 2


if __name__ == "__main__":
    sys.exit(main())
