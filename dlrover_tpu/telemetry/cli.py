"""``python -m dlrover_tpu.telemetry`` — the observability CLI.

  mttr     derive the MTTR / recovery-count report from an event
           timeline (replaces hand-maintained MTTR.json artifacts)
  goodput  derive the goodput/badput wall-clock ledger from an event
           timeline (productive / compile / reshard / restart /
           checkpoint / rendezvous / idle buckets)
  diagnose cluster diagnosis: straggler/hang verdicts + node series —
           live from a master (--addr) or forensically from a
           timeline (--events)
  plan     the runtime optimizer's decision trail: running config,
           calibration factors, candidate table, chosen/rejected
           plans, predicted-vs-realized speedups — live (--addr) or
           forensically from a timeline (--events)
  attribution
           the performance-attribution plane: per-node derived MFU /
           exposed-comm-fraction / HBM gauges and the optimizer's
           memory-gate rejections — live (--addr), forensically from
           a timeline (--events), or measured device-time buckets
           from a jax.profiler trace (--trace)
  data     the shard-dispatch & input-pipeline ledger: per-dataset
           todo/doing/done queues, epoch progress + ETA, timeout
           recoveries, per-node consumption rates — live (--addr,
           DataShardRequest RPC) or forensically from a timeline's
           DATA_* events (--events)
  readiness
           the recovery-readiness plane: cluster posture, per-node
           durability verdicts (coverage / staleness / budget), and
           the priced recovery ladder (predicted MTTR per rung) —
           live (--addr, ReadinessRequest RPC) or forensically from
           a timeline's DIAG_DURABILITY / READINESS_* events
           (--events)
  events   pretty-print a timeline (newest last)
  metrics  dump Prometheus exposition: a live endpoint via --addr, or
           this process's registry (useful under ``tpurun metrics``)
  trace    export the current process's span ring as Chrome/Perfetto
           trace JSON; with --events, merge a multi-process event
           timeline into ONE Perfetto view (incident spans + trace-id
           flows across master/agent/workers)
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m dlrover_tpu.telemetry",
        description="dlrover_tpu observability: MTTR derivation, event "
                    "timeline, metrics exposition, trace export",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    mttr = sub.add_parser(
        "mttr", help="derive MTTR from an event timeline JSONL")
    mttr.add_argument("--events", default="",
                      help="timeline path (default: the configured "
                           "DLROVER_TPU_EVENTS_FILE sink)")
    mttr.add_argument("--out", default="",
                      help="also write the JSON report to this path")
    mttr.add_argument("--target", type=float, default=90.0,
                      help="MTTR target seconds for vs_baseline "
                           "(default 90)")
    mttr.add_argument("--predict", action="store_true",
                      help="per-incident predicted-vs-realized MTTR "
                           "columns (recovery events stamped by the "
                           "priced ladder) instead of the aggregate "
                           "report")

    gp = sub.add_parser(
        "goodput", help="derive the goodput/badput ledger from an "
                        "event timeline JSONL")
    gp.add_argument("--events", default="",
                    help="timeline path (default: the configured "
                         "DLROVER_TPU_EVENTS_FILE sink)")
    gp.add_argument("--out", default="",
                    help="also write the JSON ledger to this path")

    dg = sub.add_parser(
        "diagnose", help="cluster diagnosis: node series + "
                         "straggler/hang verdicts")
    dg.add_argument("--addr", default="",
                    help="query a live master at host:port")
    dg.add_argument("--events", default="",
                    help="derive forensically from a timeline JSONL "
                         "(default: the configured events sink)")
    dg.add_argument("--json", action="store_true",
                    help="machine-readable output")

    pl = sub.add_parser(
        "plan", help="runtime-optimizer decision trail: candidate "
                     "table, chosen/rejected plans, calibration")
    pl.add_argument("--addr", default="",
                    help="query a live master at host:port")
    pl.add_argument("--events", default="",
                    help="derive forensically from a timeline JSONL "
                         "(default: the configured events sink)")
    pl.add_argument("--limit", type=int, default=0,
                    help="only the last N decisions")
    pl.add_argument("--json", action="store_true",
                    help="machine-readable output")

    at = sub.add_parser(
        "attribution", help="performance attribution: derived MFU / "
                            "exposed-comm / HBM accounting")
    at.add_argument("--addr", default="",
                    help="query a live master at host:port")
    at.add_argument("--events", default="",
                    help="derive forensically from a timeline JSONL "
                         "(default: the configured events sink)")
    at.add_argument("--trace", default="",
                    help="parse a jax.profiler Chrome trace "
                         "(*.trace.json[.gz] file or a profile dump "
                         "dir) into device-time buckets instead")
    at.add_argument("--limit", type=int, default=0,
                    help="only the last N memory-gate rejections")
    at.add_argument("--json", action="store_true",
                    help="machine-readable output")

    dt = sub.add_parser(
        "data", help="shard-dispatch & input-pipeline ledger: "
                     "todo/doing/done queues, epoch progress, "
                     "per-node consumption, timeout recoveries")
    dt.add_argument("--addr", default="",
                    help="query a live master at host:port")
    dt.add_argument("--events", default="",
                    help="derive forensically from a timeline JSONL "
                         "(default: the configured events sink)")
    dt.add_argument("--dataset", default="",
                    help="only this dataset ('' = all)")
    dt.add_argument("--json", action="store_true",
                    help="machine-readable output")

    rd = sub.add_parser(
        "readiness", help="recovery-readiness plane: posture, "
                          "per-node durability verdicts, priced "
                          "recovery ladder")
    rd.add_argument("--addr", default="",
                    help="query a live master at host:port")
    rd.add_argument("--events", default="",
                    help="derive forensically from a timeline JSONL "
                         "(default: the configured events sink)")
    rd.add_argument("--node", type=int, default=-1,
                    help="only this node's blast radius (live view)")
    rd.add_argument("--json", action="store_true",
                    help="machine-readable output")

    ev = sub.add_parser("events", help="print a timeline")
    ev.add_argument("--events", default="", help="timeline path")
    ev.add_argument("--tail", type=int, default=0,
                    help="only the last N records")
    ev.add_argument("--kind", default="",
                    help="filter to one event kind")

    met = sub.add_parser("metrics", help="dump Prometheus exposition")
    met.add_argument("--addr", default="",
                     help="scrape a live exporter at host:port instead "
                          "of dumping this process's registry")

    tr = sub.add_parser("trace", help="export span ring as Chrome JSON")
    tr.add_argument("--out", default="trace.json")
    tr.add_argument("--events", default=None,
                    help="merge THIS event timeline (all processes) "
                         "into one Perfetto view instead of exporting "
                         "the local span ring")

    cache = sub.add_parser(
        "cache", help="persistent XLA compile-cache stats (dir, entry "
                      "count, this process's hit/miss traffic)")
    cache.add_argument("--dir", default=None,
                       help="un-fingerprinted cache root (default: the "
                            "active/env-configured one)")
    return p


def _resolve_events_path(arg: str) -> Optional[str]:
    from dlrover_tpu.telemetry import events as events_mod

    return arg or events_mod.default_events_path()


def _cmd_diagnose(args) -> int:
    """Live (master RPC) or forensic (timeline) cluster diagnosis."""
    if args.addr:
        from dlrover_tpu.agent.master_client import MasterClient

        client = MasterClient(args.addr)
        try:
            report = client.get_diagnosis()
        finally:
            client.close()
        report["source"] = args.addr
    else:
        from dlrover_tpu.telemetry import events as events_mod
        from dlrover_tpu.telemetry.names import EventKind

        path = _resolve_events_path(args.events)
        if not path:
            print("diagnose: no master --addr and no timeline (pass "
                  "--events or set DLROVER_TPU_EVENTS_FILE)",
                  file=sys.stderr)
            return 2
        records = events_mod.read_events(path)
        diag_kinds = {EventKind.DIAG_STRAGGLER: "straggler",
                      EventKind.DIAG_NODE_HANG: "hung"}
        verdicts = {}
        incidents = []
        for rec in records:
            kind = rec.get("kind", "")
            if kind in diag_kinds:
                node = rec.get("diag_node")
                verdicts[str(node)] = {
                    "node_id": node,
                    "verdict": diag_kinds[kind],
                    "since_ts": rec.get("ts"),
                    "trace_id": rec.get("trace_id", ""),
                    "evidence": {
                        k: v for k, v in rec.items()
                        if k not in ("kind", "ts", "mono", "pid",
                                     "node", "seq", "trace_id",
                                     "diag_node")
                    },
                }
                incidents.append(verdicts[str(node)])
            elif kind == EventKind.DIAG_RECOVERED:
                verdicts.pop(str(rec.get("diag_node")), None)
        report = {
            "source": path,
            "events": len(records),
            "verdicts": verdicts,
            "stragglers": sorted(
                v["node_id"] for v in verdicts.values()
                if v["verdict"] == "straggler"),
            "hung": sorted(
                v["node_id"] for v in verdicts.values()
                if v["verdict"] == "hung"),
            "incident_history": incidents,
        }
    if args.json:
        print(json.dumps(report))
        return 0
    stragglers = report.get("stragglers") or []
    hung = report.get("hung") or []
    nodes = report.get("nodes") or {}
    for node_id, sample in sorted(nodes.items()):
        if not sample:
            continue
        p50 = sample.get("step_p50")
        print(
            f"node {node_id}: step={sample.get('step')} "
            f"p50={p50 if p50 is not None else '-'}s "
            f"rss={sample.get('rss_mb')}MB "
            f"age={sample.get('report_age_s')}s"
        )
    for v in (report.get("verdicts") or {}).values():
        print(f"VERDICT node {v.get('node_id')}: {v.get('verdict')} "
              f"[{v.get('trace_id', '')}] evidence={v.get('evidence')}")
    if not stragglers and not hung:
        print("diagnosis: all reporting nodes healthy"
              + ("" if nodes or report.get("verdicts")
                 else " (no diagnosis records)"))
    return 0


def _print_exposed_comm(ec) -> None:
    """The predicted-vs-measured exposed-comm line shared by ``tpurun
    plan`` and ``tpurun attribution``: side by side, so an operator can
    see whether the overlap the planner paid for actually materialized
    (measured is an upper bound — far above predicted means the
    exchange is still serial)."""
    if not ec:
        return
    pred = ec.get("predicted")
    meas = ec.get("measured")
    print(f"exposed comm: predicted="
          f"{pred if pred is not None else '-'} "
          f"measured={meas if meas is not None else '-'} "
          f"(C={ec.get('dispatch_chunks')}, "
          f"{ec.get('nodes_measured', 0)} node(s) measured)")


def _cmd_plan(args) -> int:
    """Live (master RPC) or forensic (timeline) optimizer trail."""
    from dlrover_tpu.telemetry.names import EventKind
    if args.addr:
        from dlrover_tpu.agent.master_client import MasterClient

        client = MasterClient(args.addr)
        try:
            report = client.get_plan(limit=args.limit)
        finally:
            client.close()
        report["source"] = args.addr
    else:
        from dlrover_tpu.master.optimizer import (
            decision_trail_from_events,
        )
        from dlrover_tpu.telemetry import events as events_mod

        path = _resolve_events_path(args.events)
        if not path:
            print("plan: no master --addr and no timeline (pass "
                  "--events or set DLROVER_TPU_EVENTS_FILE)",
                  file=sys.stderr)
            return 2
        report = decision_trail_from_events(events_mod.read_events(path))
        report["source"] = path
        if args.limit:
            report["plans"] = report["plans"][-args.limit:]
    if args.json:
        print(json.dumps(report))
        return 0
    running = report.get("running")
    if running:
        line = (f"running: mesh={running.get('mesh')} "
                f"window={running.get('train_window')} "
                f"K={running.get('steps_per_call')} "
                f"world={running.get('world')}")
        if running.get("dispatch_chunks"):
            line += f" C={running.get('dispatch_chunks')}"
        # the wire precisions, shown only when they deviate from the
        # bf16 default (the interesting case)
        if (running.get("moe_precision") or "bf16") != "bf16":
            line += f" p={running.get('moe_precision')}"
        if (running.get("fsdp_precision") or "bf16") != "bf16":
            line += f" fp={running.get('fsdp_precision')}"
        print(line)
    _print_exposed_comm(report.get("exposed_comm"))
    corr = report.get("corrections")
    if corr:
        print(f"calibration: compute x{corr.get('compute')} "
              f"comm x{corr.get('comm')} "
              f"dispatch x{corr.get('dispatch')} "
              f"({corr.get('samples')} passes)")
    # live view: full decision records; forensic view: per-plan rows
    for d in report.get("decisions") or []:
        line = (f"[{d.get('trace_id', '')}] {d.get('trigger')}: "
                f"{d.get('outcome')}")
        if d.get("outcome") == "chosen":
            c = d.get("chosen") or {}
            line += (f" plan={d.get('plan_id')} -> "
                     f"K={c.get('steps_per_call')} "
                     f"window={c.get('train_window')} "
                     f"mesh={c.get('mesh')} ")
            if c.get("dispatch_chunks"):
                line += f"C={c.get('dispatch_chunks')} "
            if (c.get("moe_precision") or "bf16") != "bf16":
                line += f"p={c.get('moe_precision')} "
            if (c.get("fsdp_precision") or "bf16") != "bf16":
                line += f"fp={c.get('fsdp_precision')} "
            line += f"predicted {d.get('predicted_speedup')}x"
            if d.get("applied"):
                line += (f" (applied, realized "
                         f"{d.get('realized_speedup')}x)")
        else:
            line += f" ({d.get('reason')})"
        print(line)
        for c in (d.get("candidates") or [])[:4]:
            chunk = (f" C={c.get('dispatch_chunks')}"
                     if c.get("dispatch_chunks") else "")
            print(f"    candidate K={c.get('steps_per_call')} "
                  f"window={c.get('train_window')} mesh={c.get('mesh')}"
                  f"{chunk}"
                  f" -> {c.get('predicted_step_s')}s/step "
                  f"({c.get('speedup')}x)")
        for m in d.get("memory_rejected") or []:
            print(f"    MEMORY-REJECTED mesh={m.get('mesh')}: "
                  f"predicted {m.get('predicted_hbm_bytes')} B > "
                  f"budget {m.get('budget_bytes')} B")
    for p in report.get("plans") or []:
        line = (f"plan {p.get('plan_id')} [{p.get('trigger', '')}]: "
                f"K={p.get('steps_per_call')} "
                f"window={p.get('train_window')} "
                f"predicted {p.get('predicted_speedup')}x")
        if "apply_seconds" in p:
            line += (f", applied in {p.get('apply_seconds')}s "
                     f"(recompiled={p.get('recompiled')})")
        if p.get("apply_error"):
            line += f", FAILED ({p['apply_error']})"
        if p.get("realized_speedup") is not None:
            line += f", realized {p.get('realized_speedup')}x"
        print(line)
    # forensic view: rejected passes carry no plan id, so they never
    # join the per-plan rows — but a rejection IS a decision (the
    # input-bound/memory gates exist to be read), so render the trail's
    # rejection records too
    rejected = [
        r for r in (report.get("trail") or [])
        if r.get("kind") == EventKind.OPTIMIZER_PLAN_REJECTED
    ]
    for r in rejected:
        line = (f"[{r.get('trace_id', '')}] {r.get('trigger', '')}: "
                f"rejected ({r.get('reason')})")
        if r.get("input_bound_node") is not None:
            line += (f" node={r.get('input_bound_node')} "
                     f"input_wait={r.get('input_wait_frac')}")
            if r.get("peer_median_input_wait_frac") is not None:
                line += (" vs peer median "
                         f"{r.get('peer_median_input_wait_frac')}")
        print(line)
    if not (report.get("decisions") or report.get("plans")
            or rejected):
        print("plan: no optimizer decisions recorded")
    return 0


def _cmd_attribution(args) -> int:
    """Live (master RPC), forensic (timeline), or measured (trace
    parse) performance attribution."""
    if args.trace:
        from dlrover_tpu.telemetry.attribution import parse_trace_path

        try:
            buckets = parse_trace_path(args.trace)
        except (OSError, ValueError) as e:
            print(f"attribution: trace parse of {args.trace} failed: "
                  f"{e}", file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(buckets))
            return 0
        print(f"device-time buckets over {buckets['events']} trace "
              f"event(s) ({args.trace}):")
        for key in ("wall_s", "busy_s", "idle_s", "collective_s",
                    "compute_s", "infeed_s", "other_s"):
            print(f"  {key:14s} {buckets[key]}")
        print(f"measured comm fraction (collective over categorized "
              f"device-op time): {buckets['measured_comm_frac']}")
        return 0
    if args.addr:
        from dlrover_tpu.agent.master_client import MasterClient

        client = MasterClient(args.addr)
        try:
            report = client.get_attribution(limit=args.limit)
        finally:
            client.close()
        report["source"] = args.addr
    else:
        from dlrover_tpu.telemetry import events as events_mod
        from dlrover_tpu.telemetry.names import EventKind

        path = _resolve_events_path(args.events)
        if not path:
            print("attribution: no master --addr and no timeline "
                  "(pass --events or set DLROVER_TPU_EVENTS_FILE)",
                  file=sys.stderr)
            return 2
        records = events_mod.read_events(path)
        # newest ATTRIBUTION_CAPTURED per worker (node, pid)
        captured = {}
        for rec in records:
            if rec.get("kind") == EventKind.ATTRIBUTION_CAPTURED:
                captured[(rec.get("node"), rec.get("pid"))] = {
                    k: v for k, v in rec.items()
                    if k not in ("kind", "mono", "seq")
                }
        rejections = [
            {k: v for k, v in rec.items() if k not in ("mono", "seq")}
            for rec in records
            if rec.get("kind") == EventKind.OPTIMIZER_PLAN_REJECTED
            and str(rec.get("reason", "")).startswith("memory")
        ]
        if args.limit:
            rejections = rejections[-args.limit:]
        report = {
            "source": path,
            "events": len(records),
            "records": list(captured.values()),
            "memory_rejected": rejections,
        }
    if args.json:
        print(json.dumps(report))
        return 0
    _print_exposed_comm(report.get("exposed_comm"))
    for node_id, sample in sorted((report.get("nodes") or {}).items()):
        if not sample:
            continue
        mfu = sample.get("mfu")
        frac = sample.get("exposed_comm_frac")
        print(
            f"node {node_id}: step={sample.get('step')} "
            f"mfu={round(mfu, 4) if mfu is not None else '-'} "
            f"exposed_comm="
            f"{round(frac, 4) if frac is not None else '-'} "
            f"flops/step={sample.get('flops_per_step') or '-'} "
            f"peak_hbm={sample.get('peak_hbm_mb') or '-'}MB "
            f"headroom={sample.get('hbm_headroom_mb') or '-'}MB"
        )
    for rec in report.get("records") or []:
        print(f"record node={rec.get('node')} pid={rec.get('pid')}: "
              f"flops/step={rec.get('flops_per_step')} "
              f"intensity={rec.get('arithmetic_intensity')} "
              f"peak_hbm={rec.get('peak_hbm_mb')}MB "
              f"comm_s={rec.get('predicted_comm_total_s')} "
              f"source={rec.get('source')}")
    for rej in report.get("memory_rejected") or []:
        print(f"MEMORY-REJECTED mesh={rej.get('mesh')} "
              f"needs {rej.get('predicted_hbm_mb', rej.get('predicted_hbm_bytes'))}"
              f" > budget {rej.get('budget_mb', rej.get('budget_bytes'))}"
              f" [{rej.get('trigger', rej.get('reason', ''))}]")
    if not (report.get("nodes") or report.get("records")
            or report.get("memory_rejected")):
        print("attribution: no records (telemetry off, or no "
              "attribution capture has run)")
    return 0


def _cmd_data(args) -> int:
    """Live (master RPC) or forensic (timeline DATA_* events) shard
    ledger. Both views quote the same shard counts — the tier-1 CLI
    gate pins their agreement on a completed dataset."""
    if args.addr:
        from dlrover_tpu.agent.master_client import MasterClient

        client = MasterClient(args.addr)
        try:
            report = client.get_data_report(dataset_name=args.dataset)
        finally:
            client.close()
        report["source"] = args.addr
    else:
        from dlrover_tpu.telemetry import events as events_mod
        from dlrover_tpu.telemetry.names import EventKind

        path = _resolve_events_path(args.events)
        if not path:
            print("data: no master --addr and no timeline (pass "
                  "--events or set DLROVER_TPU_EVENTS_FILE)",
                  file=sys.stderr)
            return 2
        records = events_mod.read_events(path)
        # the newest DATA_EPOCH_END per dataset carries the cumulative
        # accounting; timeout events accumulate per dataset
        datasets = {}
        timeouts = []
        for rec in records:
            kind = rec.get("kind", "")
            name = rec.get("dataset", "")
            if args.dataset and name != args.dataset:
                continue
            if kind == EventKind.DATA_EPOCH_END:
                datasets[name] = {
                    "shards_done": rec.get("shards_done"),
                    "records_done": rec.get("records_done"),
                    "epoch": rec.get("epoch"),
                    "timeout_recovered": rec.get(
                        "timeout_recovered", 0),
                    "completed": bool(rec.get("final")),
                    "ts": rec.get("ts"),
                }
            elif kind == EventKind.DATA_SHARD_TIMEOUT:
                timeouts.append({
                    "dataset": name, "ts": rec.get("ts"),
                    "count": rec.get("count"),
                    "task_ids": rec.get("task_ids"),
                    "trace_id": rec.get("trace_id", ""),
                })
        report = {
            "source": path,
            "events": len(records),
            "datasets": datasets,
            "timeouts": timeouts,
        }
    if args.json:
        print(json.dumps(report))
        return 0
    for name, d in sorted((report.get("datasets") or {}).items()):
        line = (f"dataset {name}: todo={d.get('todo', '-')} "
                f"doing={d.get('doing', '-')} "
                f"done={d.get('shards_done')} shards "
                f"({d.get('records_done')} records) "
                f"epoch={d.get('epoch')}")
        if d.get("epoch_progress") is not None:
            line += f" progress={round(d['epoch_progress'] * 100, 1)}%"
        if d.get("eta_s") is not None:
            line += f" eta={d['eta_s']}s"
        if d.get("timeout_recovered"):
            line += f" timeout_recovered={d['timeout_recovered']}"
        if d.get("completed"):
            line += " COMPLETED"
        print(line)
    def _node_order(item):
        # node ids arrive as strings over JSON: sort numerically so a
        # 10+-node cluster doesn't print 0, 1, 10, 11, 2, ...
        try:
            return (0, int(item[0]))
        except (TypeError, ValueError):
            return (1, item[0])

    for node_id, stats in sorted((report.get("nodes") or {}).items(),
                                 key=_node_order):
        rate = stats.get("records_per_s")
        print(f"node {node_id}: shards={stats.get('shards_completed')} "
              f"records={stats.get('records_done')} "
              f"rate={rate if rate is not None else '-'}/s")
    for t in report.get("timeouts") or []:
        print(f"TIMEOUT dataset={t.get('dataset')}: "
              f"{t.get('count')} shard(s) requeued "
              f"(tasks {t.get('task_ids')}) [{t.get('trace_id', '')}]")
    if not (report.get("datasets") or report.get("nodes")
            or report.get("timeouts")):
        print("data: no shard-dispatch records (no dataset registered, "
              "or no DATA_* events in the timeline)")
    return 0


def _cmd_readiness(args) -> int:
    """Live (ReadinessRequest RPC) or forensic (timeline replay)
    readiness report. Both views quote the same posture and at-risk
    node set — the tier-1 CLI gate pins their agreement across a
    flag -> clear cycle."""
    if args.addr:
        from dlrover_tpu.agent.master_client import MasterClient

        client = MasterClient(args.addr)
        try:
            report = client.get_readiness(node_id=args.node)
        finally:
            client.close()
        report["source"] = args.addr
    else:
        from dlrover_tpu.telemetry import events as events_mod
        from dlrover_tpu.telemetry.readiness import readiness_view

        path = _resolve_events_path(args.events)
        if not path:
            print("readiness: no master --addr and no timeline (pass "
                  "--events or set DLROVER_TPU_EVENTS_FILE)",
                  file=sys.stderr)
            return 2
        report = readiness_view(events_mod.read_events(path))
        report["source"] = path
    if args.json:
        print(json.dumps(report))
        return 0
    posture = report.get("posture", "ready")
    at_risk = report.get("at_risk") or {}
    print(f"posture: {posture.upper()}"
          + (f" ({len(at_risk)} node(s) at risk)" if at_risk else ""))
    for node, v in sorted(at_risk.items()):
        print(f"AT RISK node {node}: {v.get('error_code', '')} "
              f"[{v.get('trace_id', '')}] evidence={v.get('evidence')}")
    # live view extras: per-node blast radius + calibration
    for node, d in sorted((report.get("nodes") or {}).items()):
        if not d.get("owner"):
            continue
        table = d.get("predicted_mttr") or {}
        rungs = " ".join(
            f"{r}={table[r]}s" for r in
            ("live_reshard", "peer_rebuild", "storage_restore", "init")
            if r in table)
        print(f"node {node}: regions={d.get('regions_mb')}MB "
              f"holders={d.get('holders')} "
              f"coverage={'ok' if d.get('coverage_ok') else 'LOST'} "
              f"staleness={d.get('staleness_steps')} "
              f"best_rung={d.get('best_rung')} {rungs}")
    admitted = report.get("admitted") or {}
    if admitted.get("requested"):
        print(f"replicas: admitted k={admitted.get('replicas')} of "
              f"requested {admitted.get('requested')}"
              + (f" ({admitted.get('reason')})"
                 if admitted.get("reason") else ""))
    cal = report.get("calibration") or {}
    if cal:
        print(f"calibration: link_bw={cal.get('link_bw_bytes_per_s')} "
              f"put_bw={cal.get('put_bw_bytes_per_s')} "
              f"observations={cal.get('observations')}")
    sweep = report.get("last_sweep")
    if sweep:
        print(f"last sweep: {sweep}")
    if not at_risk:
        print("durability: every owner's regions covered"
              + ("" if report.get("nodes") or report.get("sweep_events")
                 else " (no readiness records)"))
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    if args.cmd == "plan":
        return _cmd_plan(args)

    if args.cmd == "data":
        return _cmd_data(args)

    if args.cmd == "attribution":
        return _cmd_attribution(args)

    if args.cmd == "readiness":
        return _cmd_readiness(args)

    if args.cmd == "mttr":
        from dlrover_tpu.telemetry import events as events_mod
        from dlrover_tpu.telemetry.mttr import mttr_report

        path = _resolve_events_path(args.events)
        if not path:
            print("mttr: no timeline (pass --events or set "
                  "DLROVER_TPU_EVENTS_FILE)", file=sys.stderr)
            return 2
        records = events_mod.read_events(path)
        if args.predict:
            from dlrover_tpu.telemetry.readiness import predict_report

            report = predict_report(records)
        else:
            report = mttr_report(records, target_s=args.target)
        line = json.dumps(report)
        print(line)
        if args.out:
            with open(args.out, "w") as fh:
                fh.write(line + "\n")
        return 1 if report.get("error") else 0

    if args.cmd == "goodput":
        from dlrover_tpu.telemetry import events as events_mod
        from dlrover_tpu.telemetry.goodput import derive_goodput

        path = _resolve_events_path(args.events)
        if not path:
            print("goodput: no timeline (pass --events or set "
                  "DLROVER_TPU_EVENTS_FILE)", file=sys.stderr)
            return 2
        report = derive_goodput(events_mod.read_events(path))
        line = json.dumps(report)
        print(line)
        if args.out:
            with open(args.out, "w") as fh:
                fh.write(line + "\n")
        return 1 if report.get("error") else 0

    if args.cmd == "diagnose":
        return _cmd_diagnose(args)

    if args.cmd == "events":
        from dlrover_tpu.telemetry import events as events_mod

        path = _resolve_events_path(args.events)
        records = (
            events_mod.read_events(path) if path
            else events_mod.recent_events()
        )
        if args.kind:
            records = [r for r in records if r.get("kind") == args.kind]
        if args.tail:
            records = records[-args.tail:]
        for rec in records:
            print(json.dumps(rec, sort_keys=True))
        return 0

    if args.cmd == "metrics":
        if args.addr:
            from dlrover_tpu.telemetry.exporter import fetch_metrics

            try:
                status, body = fetch_metrics(args.addr)
            except OSError as e:
                print(f"metrics: scrape of {args.addr} failed: {e}",
                      file=sys.stderr)
                return 2
            sys.stdout.write(body)
            return 0 if status == 200 else 1
        from dlrover_tpu.telemetry.metrics import process_registry

        sys.stdout.write(process_registry().render_prometheus())
        return 0

    if args.cmd == "trace":
        if args.events is not None:
            from dlrover_tpu.telemetry import events as events_mod
            from dlrover_tpu.telemetry.correlate import (
                export_merged_trace,
            )

            records = events_mod.read_events(args.events)
            n = export_merged_trace(records, args.out)
            print(f"merged {len(records)} event(s) into {n} trace "
                  f"event(s) at {args.out}")
            return 0 if records else 1
        from dlrover_tpu.telemetry import tracing

        n = tracing.export_chrome_trace(args.out)
        print(f"wrote {n} span(s) to {args.out}")
        return 0

    if args.cmd == "cache":
        from dlrover_tpu.utils.compile_cache import cache_stats

        stats = cache_stats(args.dir)
        print(json.dumps(stats))
        return 0 if stats["configured"] else 1

    return 2


if __name__ == "__main__":
    sys.exit(main())
