from dlrover_tpu.telemetry.cli import main

if __name__ == "__main__":
    import sys

    sys.exit(main())
