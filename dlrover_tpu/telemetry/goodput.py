"""Goodput/badput ledger derived from the event timeline.

Where did the job's wall-clock go? The 100k-GPU HSDP line of work
(PAPERS.md) treats this accounting as the precondition for fault-
tolerant training at scale: a job that recovers but spends 30% of its
life rendezvousing is still a broken job. Like ``mttr``, the ledger is
DERIVED from the JSONL timeline the production components already emit
— no bench script assembles it by hand.

Wall time (first event → last event) is partitioned into buckets by an
interval sweep:

  restart          failure edge (worker death / hang) → workers running
  reshard          live in-process reshard (begin → done)
  replan           runtime-optimizer plan applying live (apply begin →
                   done: the drain + retune/reshard the loop chose)
  rollback         non-finite step → checkpoint rollback restored
  preempt_drain    preemption notice → drain done
  rendezvous       join → completed world (``wait_seconds`` on the
                   complete/timeout records)
  checkpoint       save staging + restore wall time (the async mirror
                   overlaps training and is deliberately NOT counted)
  compile          TRAIN_START → first materialized step
                   (``compile_first_step.seconds``)
  productive_step  time inside a TRAIN_START→TRAIN_END span not claimed
                   by any bucket above
  idle             everything else (setup gaps, time between a worker's
                   death and its failure edge, post-training teardown)

Overlapping claims resolve by the order above (downtime wins over a
train span that brackets it), so the buckets PARTITION the wall clock:
they sum to job wall-time by construction — the acceptance gate's
"≥99%" allows only for rounding.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from dlrover_tpu.telemetry.mttr import derive_incidents
from dlrover_tpu.telemetry.names import EventKind

# highest priority first: an instant of wall time goes to the FIRST
# bucket that claims it. serving_scale sits LAST: an SLO violation is
# degraded-but-alive operation, not downtime — it claims only time no
# training/recovery bucket owns (on a pure serving timeline, the
# otherwise-idle window between violation and recovery).
BUCKET_PRIORITY = (
    "restart",
    "reshard",
    "peer_rebuild",
    "replan",
    "rollback",
    "preempt_drain",
    "rendezvous",
    "checkpoint",
    "compile",
    "productive_step",
    "serving_scale",
)
IDLE = "idle"

_SCENARIO_BUCKET = {
    "worker_failure": "restart",
    "hang": "restart",
    "live_reshard": "reshard",
    # the serving world's live resize is reshard-class downtime: the
    # decode stream pauses while params+KV pages move meshes
    "serving_resize": "reshard",
    # checkpoint-free recovery: peer-fetch + device_put time of a
    # rebuilding worker (its own bucket — it runs AFTER the restart
    # incident closes at workers_started, so restart never claims it)
    "peer_rebuild": "peer_rebuild",
    # a runtime-optimizer plan applying live (drain -> retune -> resume)
    "replan": "replan",
    "nonfinite_rollback": "rollback",
    "preemption_drain": "preempt_drain",
    # a serving SLO violation burning until its recovery (degraded-
    # but-alive; lowest priority — see BUCKET_PRIORITY)
    "serving_scale": "serving_scale",
}

_FAILURE_EDGES = {EventKind.WORKER_FAILED, EventKind.HANG_DETECTED}

# (kind, duration-field) pairs whose records carry their own wall cost
_DURATION_EVENTS = {
    EventKind.RDZV_COMPLETE: ("wait_seconds", "rendezvous"),
    EventKind.RDZV_TIMEOUT: ("timeout_seconds", "rendezvous"),
    EventKind.CKPT_SAVE: ("stage_seconds", "checkpoint"),
    EventKind.CKPT_RESTORE: ("restore_seconds", "checkpoint"),
    EventKind.COMPILE_FIRST_STEP: ("seconds", "compile"),
}


def _train_spans(ordered: List[Dict], t_end: float) -> List[
        Tuple[float, float]]:
    """Per-worker TRAIN_START→TRAIN_END spans, keyed by (node, pid) —
    containerized workers on different hosts routinely share a pid
    (often 1), and pairing on pid alone would cross-close spans between
    nodes. A re-entered TRAIN_START on the same worker closes the
    previous span at the new start. An unclosed span (the worker died
    mid-training) ends at the next observed failure edge — the moment
    the cluster learned the training stopped — or at the timeline's end
    when no failure edge follows."""
    spans: List[Tuple[float, float]] = []
    open_starts: Dict[Tuple[str, int], float] = {}
    failure_ts = [r.get("ts", 0.0) for r in ordered
                  if r.get("kind") in _FAILURE_EDGES]
    for rec in ordered:
        kind = rec.get("kind")
        key = (str(rec.get("node", "")), rec.get("pid", 0))
        ts = rec.get("ts", 0.0)
        if kind == EventKind.TRAIN_START:
            prev = open_starts.get(key)
            if prev is not None:
                spans.append((prev, ts))
            open_starts[key] = ts
        elif kind == EventKind.TRAIN_END and key in open_starts:
            spans.append((open_starts.pop(key), ts))
    for _key, start in open_starts.items():
        later_failures = [t for t in failure_ts if t > start]
        spans.append((start, min(later_failures) if later_failures
                      else t_end))
    return spans


def _model_flops_column(ordered: List[Dict],
                        productive_s: float) -> Optional[Dict]:
    """The model-FLOPs goodput column (the 100k-GPU HSDP position:
    production health is model FLOPs delivered, not steps survived).

    Integrated PER ATTRIBUTION RECORD: an elastic job re-captures after
    every program/world change, so each record's whole-mesh FLOPs/step
    is charged only for the steps executed while THAT record was
    current (step progress read from the max-step envelope of the
    surrounding events — rollback rewinds never subtract). Steps before
    the first record are charged at the first record's rate. None when
    no record was ever captured."""
    captures: List[Tuple[float, float]] = []  # (ts, whole-mesh f/step)
    for rec in ordered:
        if rec.get("kind") != EventKind.ATTRIBUTION_CAPTURED:
            continue
        try:
            per_step = float(rec.get("flops_per_step", 0.0)) * max(
                1, int(rec.get("n_devices", 1)))
        except (TypeError, ValueError):
            continue
        captures.append((rec.get("ts", 0.0), per_step))
    if not captures:
        return None

    def max_step_before(t: float) -> int:
        best = 0
        for r in ordered:
            if r.get("ts", 0.0) >= t:
                break
            s = r.get("step")
            if s is not None:
                try:
                    best = max(best, int(s))
                except (TypeError, ValueError):
                    pass
        return best

    end_ts = float("inf")
    total = 0.0
    steps_total = 0
    # the first record also covers the steps before its capture ts
    # (the record describes the program those steps ran)
    marks = [0.0] + [ts for ts, _ in captures[1:]] + [end_ts]
    for (ts, per_step), lo, hi in zip(captures, marks, marks[1:]):
        start = max_step_before(lo) if lo else 0
        end = max(max_step_before(hi), start)
        total += per_step * (end - start)
        steps_total += end - start
    return {
        # the newest record's rate, for reference
        "flops_per_step": captures[-1][1],
        "steps": steps_total,
        "total": total,
        "records": len(captures),
        "per_productive_second": (
            round(total / productive_s, 3) if productive_s > 0 else 0.0
        ),
    }


def _durability_column(ordered: List[Dict], t1: float) -> Optional[Dict]:
    """The durability-at-risk goodput column: wall seconds the cluster
    ran with an owner's replica coverage degraded (the readiness
    auditor's READINESS_DEGRADED -> READINESS_RESTORED spans). A
    COLUMN, not a wall bucket — the job keeps training while at risk
    (no downtime to charge), so it reports how much of the wall clock
    was spent one failure away from a slow rung rather than
    re-partitioning it. None when no degraded edge exists (the plane
    off, or never at risk)."""
    total = 0.0
    spells = 0
    open_ts: Optional[float] = None
    seen = False
    for rec in ordered:
        kind = rec.get("kind")
        ts = rec.get("ts")
        if ts is None:
            continue
        if kind == EventKind.READINESS_DEGRADED:
            seen = True
            if open_ts is None:
                open_ts = float(ts)
        elif kind == EventKind.READINESS_RESTORED and open_ts is not None:
            total += max(0.0, float(ts) - open_ts)
            spells += 1
            open_ts = None
    if not seen:
        return None
    if open_ts is not None:
        # still degraded at the end of the timeline: at-risk until t1
        total += max(0.0, t1 - open_ts)
        spells += 1
    return {"seconds": round(total, 3), "spells": spells}


def _input_wait_column(ordered: List[Dict],
                       productive_s: float) -> Optional[Dict]:
    """The input-wait goodput column: host seconds the workers spent
    blocked waiting for the next batch, summed from the ``input_wait_s``
    field executors stamp on TRAIN_END. A COLUMN, not a wall bucket —
    the wait overlaps the productive train span (the device sits idle
    inside a step window), so it reports how much of the productive
    time was hollow rather than re-partitioning the wall clock. None
    when no record carries the field (old timelines, telemetry off)."""
    total = 0.0
    workers = set()
    seen = False
    for rec in ordered:
        if rec.get("kind") != EventKind.TRAIN_END:
            continue
        wait = rec.get("input_wait_s")
        if wait is None:
            continue
        try:
            total += float(wait)
        except (TypeError, ValueError):
            continue
        seen = True
        workers.add((str(rec.get("node", "")), rec.get("pid", 0)))
    if not seen:
        return None
    return {
        "seconds": round(total, 3),
        "workers": len(workers),
        "fraction_of_productive": (
            round(total / productive_s, 4) if productive_s > 0 else 0.0
        ),
    }


# slot-ledger classes in display order (the serving analog of
# BUCKET_PRIORITY — the executor charges every slot-second to exactly
# one of these, so they sum to slots x wall by construction)
SLOT_LEDGER_CLASSES = (
    "decode", "prefill", "admitted_idle", "vacant", "resize_frozen",
)


def derive_slot_ledger(events: List[Dict]) -> Dict:
    """The serving slot-seconds partition, derived from the
    cumulative ledger each serve run stamps on its SERVE_END event
    (the goodput-ledger discipline: the artifact is DERIVED from the
    production timeline, never hand-assembled). Aggregates across
    every serve run in the timeline; ``coverage`` quotes
    sum(classes)/slot_seconds, which is 1.0 up to float rounding by
    construction."""
    runs = []
    for rec in sorted(events, key=lambda r: r.get("ts", 0.0)):
        if rec.get("kind") != EventKind.SERVE_END:
            continue
        ledger = rec.get("slot_ledger")
        if not isinstance(ledger, dict):
            continue  # pre-SLO-plane timelines carry no ledger
        runs.append(rec)
    if not runs:
        return {
            "metric": "serve_slot_seconds",
            "runs": 0,
            "slot_seconds": 0.0,
            "buckets": {},
            "error": "no SERVE_END ledger records in the timeline",
        }
    seconds = {k: 0.0 for k in SLOT_LEDGER_CLASSES}
    slot_seconds = 0.0
    # ledgers are CUMULATIVE per executor (serve_seq identifies one
    # executor's loop within a process): the last SERVE_END of each
    # executor supersedes its earlier ones; distinct executors sum
    latest: Dict = {}
    for rec in runs:
        latest[(str(rec.get("node", "")), rec.get("pid", 0),
                rec.get("serve_seq", 0))] = rec
    for rec in latest.values():
        for k, v in rec["slot_ledger"].items():
            if k in seconds:
                try:
                    seconds[k] += float(v)
                except (TypeError, ValueError):
                    continue
        try:
            slot_seconds += float(rec.get("slot_seconds", 0.0) or 0.0)
        except (TypeError, ValueError):
            pass
    covered = sum(seconds.values())
    # the prefix-cache columns ride the same SERVE_END records (the
    # engine stamps its pool stats beside the slot ledger): sum the
    # superseding record of each executor, absent on pre-prefix
    # timelines and when the pool is off
    prefix = {"hits": 0, "misses": 0, "evictions": 0,
              "saved_prefill_tokens": 0}
    prefix_runs = 0
    for rec in latest.values():
        stats = rec.get("prefix")
        if not isinstance(stats, dict):
            continue
        prefix_runs += 1
        for src, dst in (("hits", "hits"), ("misses", "misses"),
                         ("evictions", "evictions"),
                         ("saved_tokens", "saved_prefill_tokens")):
            try:
                prefix[dst] += int(stats.get(src, 0) or 0)
            except (TypeError, ValueError):
                continue
    return {
        "metric": "serve_slot_seconds",
        "runs": len(latest),
        "prefix": prefix if prefix_runs else None,
        "slot_seconds": round(slot_seconds, 3),
        "buckets": {
            k: {
                "seconds": round(v, 3),
                "fraction": (round(v / slot_seconds, 4)
                             if slot_seconds > 0 else 0.0),
            }
            for k, v in seconds.items()
        },
        "coverage": (round(covered / slot_seconds, 4)
                     if slot_seconds > 0 else 0.0),
        "source": "event_timeline",
    }


def derive_goodput(events: List[Dict]) -> Dict:
    """The ledger: bucket seconds + fractions over the timeline's wall
    clock (empty report when fewer than two timestamped events)."""
    ordered = sorted(events, key=lambda r: r.get("ts", 0.0))
    stamps = [r["ts"] for r in ordered if r.get("ts") is not None]
    if len(stamps) < 2 or stamps[-1] <= stamps[0]:
        return {
            "metric": "goodput_fraction",
            "value": 0.0,
            "unit": "fraction",
            "error": "timeline too short to derive a ledger",
            "detail": {"wall_s": 0.0, "events": len(events),
                       "buckets": {}},
        }
    t0, t1 = stamps[0], stamps[-1]
    wall = t1 - t0

    intervals: List[Tuple[float, float, str]] = []

    # incident downtime: reuse the MTTR pairing (bursts collapse, edges
    # pair per scenario); unrecovered incidents cost until the end
    for inc in derive_incidents(ordered):
        bucket = _SCENARIO_BUCKET.get(inc["scenario"])
        if bucket is None or inc["started_ts"] is None:
            continue
        end = inc["recovered_ts"] if inc["recovered_ts"] is not None else t1
        intervals.append((inc["started_ts"], end, bucket))

    # self-costed records (the emitting component measured its own wall)
    for rec in ordered:
        spec = _DURATION_EVENTS.get(rec.get("kind", ""))
        if spec is None:
            continue
        field_name, bucket = spec
        try:
            dur = float(rec.get(field_name, 0.0) or 0.0)
        except (TypeError, ValueError):
            continue
        if dur > 0:
            ts = rec.get("ts", 0.0)
            intervals.append((ts - dur, ts, bucket))

    for start, end in _train_spans(ordered, t1):
        intervals.append((start, end, "productive_step"))

    # clip to the wall window and sweep: per boundary point, per-rank
    # open-interval deltas; each segment between consecutive points is
    # charged to the highest-priority bucket active over it. O(n log n)
    # in the interval count — a per-segment scan of all intervals would
    # go quadratic on a long retained timeline.
    clipped = [
        (max(s, t0), min(e, t1), b)
        for s, e, b in intervals if min(e, t1) > max(s, t0)
    ]
    rank = {b: i for i, b in enumerate(BUCKET_PRIORITY)}
    deltas: Dict[float, List[int]] = {}
    for s, e, bucket in clipped:
        r = rank[bucket]
        deltas.setdefault(s, [0] * len(BUCKET_PRIORITY))[r] += 1
        deltas.setdefault(e, [0] * len(BUCKET_PRIORITY))[r] -= 1
    points = sorted({t0, t1, *deltas})
    seconds: Dict[str, float] = {b: 0.0 for b in BUCKET_PRIORITY}
    seconds[IDLE] = 0.0
    active = [0] * len(BUCKET_PRIORITY)
    for a, b in zip(points, points[1:]):
        d = deltas.get(a)
        if d is not None:
            active = [n + dn for n, dn in zip(active, d)]
        best: Optional[str] = next(
            (name for name, n in zip(BUCKET_PRIORITY, active) if n > 0),
            None)
        seconds[best if best is not None else IDLE] += b - a

    buckets = {
        name: {
            "seconds": round(secs, 3),
            "fraction": round(secs / wall, 4),
        }
        for name, secs in seconds.items()
    }
    covered = sum(s for s in seconds.values())
    productive = seconds["productive_step"]
    detail = {
        "wall_s": round(wall, 3),
        "buckets": buckets,
        # buckets partition the wall by construction; quoted so the
        # acceptance gate (≥0.99) is checkable from the artifact
        "coverage": round(covered / wall, 4),
        "badput_s": round(wall - productive - seconds[IDLE], 3),
        "events": len(ordered),
        "source": "event_timeline",
    }
    # model-FLOPs column: only when an attribution record exists —
    # a ledger must never invent a zero-FLOPs job
    model_flops = _model_flops_column(ordered, productive)
    if model_flops is not None:
        detail["model_flops"] = model_flops
    # input-wait column: only when a TRAIN_END carried the measurement
    # (absent-not-zero, like the columns above)
    input_wait = _input_wait_column(ordered, productive)
    if input_wait is not None:
        detail["input_wait"] = input_wait
    # durability-at-risk column: only when a degraded edge exists
    # (absent-not-zero; overlaps the productive span, never a bucket)
    durability = _durability_column(ordered, t1)
    if durability is not None:
        detail["durability_at_risk"] = durability
    return {
        "metric": "goodput_fraction",
        "value": round(productive / wall, 4),
        "unit": "fraction",
        "detail": detail,
    }
