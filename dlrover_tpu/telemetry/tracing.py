"""Cheap host-side span tracing, exportable as Chrome/Perfetto JSON.

``span(name)`` is a context manager costing two ``perf_counter_ns``
reads and one deque append (~1µs) when telemetry is on, and a single
attribute test when off — safe around every step dispatch. Spans land
in a bounded ring; ``export_chrome_trace`` writes the ring in the
Trace Event Format (``ph: "X"`` complete events, microsecond units)
that ``chrome://tracing`` and https://ui.perfetto.dev open directly.

This is the *host* half of the tracing story: device-side profiles
come from the executor's ``jax.profiler.trace`` window (bounded step
range via conf, or on demand via the ``profile_signal`` knob — see
docs/observability.md).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Deque, Dict, List, Tuple

from dlrover_tpu.common.config import get_context

_SPAN_CAP = 16384

# (name, category, ts_us, dur_us, tid, args-or-None)
_spans: Deque[Tuple] = collections.deque(maxlen=_SPAN_CAP)
_lock = threading.Lock()
# perf_counter origin -> epoch mapping fixed once per process so span
# timestamps stay comparable to event-timeline wall clocks
_EPOCH_OFFSET_US = int(
    (time.time() - time.perf_counter()) * 1e6
)


def _enabled() -> bool:
    return bool(getattr(get_context(), "telemetry_enabled", True))


@contextmanager
def span(name: str, category: str = "host", **args):
    """Record one complete span around the with-body."""
    if not _enabled():
        yield
        return
    t0 = time.perf_counter_ns()
    try:
        yield
    finally:
        dur_us = (time.perf_counter_ns() - t0) // 1000
        _spans.append((
            name, category, t0 // 1000 + _EPOCH_OFFSET_US, dur_us,
            threading.get_ident() & 0xFFFFFFFF, args or None,
        ))


def add_instant(name: str, category: str = "host", **args) -> None:
    """Zero-duration marker (rendered as an instant event)."""
    if not _enabled():
        return
    _spans.append((
        name, category,
        time.perf_counter_ns() // 1000 + _EPOCH_OFFSET_US, 0,
        threading.get_ident() & 0xFFFFFFFF, args or None,
    ))


def snapshot() -> List[Tuple]:
    with _lock:
        return list(_spans)


def clear() -> None:
    with _lock:
        _spans.clear()


def export_chrome_trace(path: str) -> int:
    """Write the span ring as Trace Event Format JSON; returns the
    number of events written."""
    pid = os.getpid()
    trace_events: List[Dict] = []
    for name, cat, ts_us, dur_us, tid, args in snapshot():
        ev: Dict = {
            "name": name, "cat": cat, "ph": "X",
            "ts": ts_us, "dur": dur_us, "pid": pid, "tid": tid,
        }
        if args:
            ev["args"] = args
        trace_events.append(ev)
    payload = {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
        "otherData": {"exporter": "dlrover_tpu.telemetry"},
    }
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh)
    return len(trace_events)
