"""Performance attribution: device-time & HBM accounting per compiled
program.

Step time is one opaque number until something says where the device
time and the HBM went. This module captures an **attribution record**
per compiled train-step program — exact FLOPs and bytes-accessed from
``compiled.cost_analysis()``, compiled peak HBM from
``memory_analysis()`` (the same AOT artifacts the G106 graph lint
reads), per-collective bytes parsed from the optimized HLO, and
predicted per-collective seconds (the planner's
``predicted_collective_bytes`` formula when a ModelSpec is known, the
HLO-measured bytes over link bandwidth otherwise). At runtime the
executor fuses the record with measured step times into derived gauges:

  live MFU             compiled FLOPs/step over (measured step seconds
                       x device peak) — ``utils/prof.derived_mfu``, ONE
                       formula shared with the one-shot profiler
  arithmetic intensity FLOPs / bytes-accessed (HBM-bound when low)
  exposed-comm frac    clamped (1 - ideal compute s / measured step s):
                       an UPPER bound on un-overlapped communication
  HBM headroom         device bytes_limit - bytes_in_use where the
                       backend exposes memory stats

A second, optional source — a ``jax.profiler`` trace in Chrome
trace-event format (the ``*.trace.json(.gz)`` files a profile dump
contains) — is parsed into per-op-category device-time buckets
(collective vs compute vs infeed vs idle), giving *measured* overlap
where traces exist; committed fixtures keep the parser tested without
backend trace support.

Capture cost: one ``lower()`` (tracing is shared with the call path)
plus one XLA compile that the persistent compile cache typically serves
warm — ~0.1-0.2s on the CPU mesh, paid once per (topology, knob)
program-cache entry, never per step.
"""

from __future__ import annotations

import gzip
import json
import os
import re
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from dlrover_tpu.common.config import get_context
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.telemetry.events import emit_event
from dlrover_tpu.telemetry.names import EventKind
from dlrover_tpu.utils.prof import derived_mfu

logger = get_logger("telemetry.attribution")

_MB = 1024 * 1024


def attribution_enabled() -> bool:
    """The capture gate: the attribution knob AND the telemetry master
    switch (a capture whose gauges land in the null registry would be
    pure compile cost)."""
    ctx = get_context()
    return bool(getattr(ctx, "attribution_enabled", True)) and bool(
        getattr(ctx, "telemetry_enabled", True)
    )


def resolve_device_spec():
    """The planner ``DeviceSpec`` for the ambient accelerator: sniffed
    from the device kind against ``planner.TPU_SPECS``; CPU (and any
    unknown kind) falls back to the v5e datasheet so derived quantities
    stay defined — set ``Context.device_peak_flops`` for meaningful
    numbers on non-TPU backends."""
    from dlrover_tpu.parallel import planner

    kind = ""
    try:
        import jax

        devices = jax.devices()
        if devices:
            kind = str(getattr(devices[0], "device_kind", "")).lower()
    except Exception:  # noqa: BLE001 — no backend at all
        logger.debug("device kind sniff failed", exc_info=True)
    for marker, gen in (("v6", "v6e"), ("v5p", "v5p"),
                        ("v5 lite", "v5e"), ("v5e", "v5e"),
                        ("v4", "v4")):
        if marker in kind:
            return planner.TPU_SPECS[gen]
    return planner.TPU_SPECS["v5e"]


def resolve_peak_flops(device_spec=None) -> float:
    """Per-device peak FLOPs/s for the MFU denominator:
    ``Context.device_peak_flops`` when set, else the device spec."""
    ctx_peak = float(getattr(get_context(), "device_peak_flops", 0.0))
    if ctx_peak > 0:
        return ctx_peak
    spec = device_spec or resolve_device_spec()
    return float(spec.flops_per_s)


def resolve_hbm_budget(device_spec=None) -> float:
    """Per-device HBM budget in bytes for G107 / the optimizer's
    memory gate: ``Context.device_hbm_budget_bytes`` when set, else the
    device spec's capacity."""
    ctx_budget = float(
        getattr(get_context(), "device_hbm_budget_bytes", 0.0))
    if ctx_budget > 0:
        return ctx_budget
    spec = device_spec or resolve_device_spec()
    return float(spec.hbm_bytes)


@dataclass
class AttributionRecord:
    """One compiled program's cost facts (all per DEVICE, per optimizer
    STEP — multi-step programs are normalized by ``steps_per_call``)."""

    flops_per_step: float = 0.0  # executed FLOPs (XLA cost model)
    bytes_accessed_per_step: float = 0.0  # HBM traffic
    peak_hbm_bytes: int = 0  # compiled residency (args+temps+out-alias)
    # per-collective-kind bytes parsed from the optimized HLO
    # (trip-count-weighted, per step — the G106 measured side)
    collective_bytes: Dict[str, float] = field(default_factory=dict)
    # per-family predicted collective seconds; keys are planner
    # families ("tp", "fsdp", ...) when source == "planner", HLO kinds
    # ("all-gather", ...) when source == "hlo"
    predicted_comm_s: Dict[str, float] = field(default_factory=dict)
    predicted_comm_total_s: float = 0.0
    # ideal compute seconds: flops_per_step / peak — the subtrahend of
    # the exposed-comm bound
    predicted_compute_s: float = 0.0
    peak_flops_per_s: float = 0.0
    hbm_budget_bytes: float = 0.0
    n_devices: int = 1
    steps_per_call: int = 1
    source: str = "hlo"  # comm-bytes provenance: "planner" | "hlo"
    capture_seconds: float = 0.0

    @property
    def arithmetic_intensity(self) -> float:
        if self.bytes_accessed_per_step <= 0:
            return 0.0
        return self.flops_per_step / self.bytes_accessed_per_step

    def mfu(self, step_time_s: float) -> float:
        """Live MFU for one measured step time (shared formula)."""
        return derived_mfu(self.flops_per_step, step_time_s,
                           self.peak_flops_per_s)

    def exposed_comm_fraction(self, step_time_s: float) -> float:
        """Clamped (measured - ideal compute) / measured: the share of
        the step NOT explained by compute at peak — an upper bound on
        un-overlapped communication (plus every other inefficiency,
        which is why it is a bound, not a measurement)."""
        if step_time_s <= 0:
            return 0.0
        frac = 1.0 - self.predicted_compute_s / step_time_s
        return min(max(frac, 0.0), 1.0)

    def hbm_headroom_bytes(self) -> Optional[float]:
        """Budget minus compiled peak (static headroom); None when no
        budget is known."""
        if self.hbm_budget_bytes <= 0:
            return None
        return self.hbm_budget_bytes - self.peak_hbm_bytes

    def to_dict(self) -> Dict[str, Any]:
        return {
            "flops_per_step": self.flops_per_step,
            "bytes_accessed_per_step": self.bytes_accessed_per_step,
            "arithmetic_intensity": round(self.arithmetic_intensity, 4),
            "peak_hbm_mb": round(self.peak_hbm_bytes / _MB, 3),
            "collective_bytes": dict(self.collective_bytes),
            "predicted_comm_s": {
                k: round(v, 6) for k, v in self.predicted_comm_s.items()
            },
            "predicted_comm_total_s": round(
                self.predicted_comm_total_s, 6),
            "predicted_compute_s": round(self.predicted_compute_s, 9),
            "peak_flops_per_s": self.peak_flops_per_s,
            "hbm_budget_bytes": self.hbm_budget_bytes,
            "n_devices": self.n_devices,
            "steps_per_call": self.steps_per_call,
            "source": self.source,
            "capture_seconds": round(self.capture_seconds, 3),
        }


def capture_attribution(
    result,
    steps_per_call: int = 1,
    example_batch: Any = None,
    model_spec=None,
    device_spec=None,
    mesh_plan=None,
    emit: bool = True,
) -> AttributionRecord:
    """Build the attribution record for an ``AccelerateResult``'s
    compiled step program through the AOT path (the same lower+compile
    the G106 audit reads — tracing is shared with the call path and the
    persistent compile cache serves the XLA compile warm).

    ``model_spec``/``mesh_plan``: when both are known (the aot CLI, a
    trainer constructed with one) the per-collective comm seconds come
    from the planner's ``predicted_collective_bytes`` formula — the one
    set of formulas the G106 audit also prices. Without a ModelSpec the
    comm profile falls back to the compiled HLO's OWN collective bytes
    over link bandwidth (``source="hlo"``).
    """
    import jax
    import jax.numpy as jnp

    from dlrover_tpu.analysis.graph_lint import collective_bytes_by_kind
    from dlrover_tpu.utils.prof import (
        compiled_peak_bytes,
        cost_analysis_dict,
    )

    if example_batch is None:
        raise ValueError("capture_attribution needs the example batch "
                         "to rebuild the step's abstract signature")
    spec = device_spec or resolve_device_spec()
    peak_flops = resolve_peak_flops(spec)
    budget = resolve_hbm_budget(spec)
    k = max(1, int(steps_per_call))

    t0 = time.monotonic()
    abstract_state = jax.eval_shape(
        lambda r: result.init_fn(r), jax.random.PRNGKey(0)
    )
    if k > 1 and result.train_step_multi is not None:
        abstract_batch = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct((k,) + x.shape, x.dtype),
            example_batch,
        )
        key = jax.ShapeDtypeStruct((k, 2), jnp.uint32)
        step_fn = result.train_step_multi
    else:
        k = 1
        abstract_batch = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
            example_batch,
        )
        key = jax.ShapeDtypeStruct((2,), jnp.uint32)
        step_fn = result.train_step
    compiled = step_fn.lower(abstract_state, abstract_batch, key).compile()

    cost = cost_analysis_dict(compiled)
    # NB: XLA's cost model counts loop bodies ONCE (no trip-count
    # multiply — the aot.py caveat), so the K-step scan's FLOPs already
    # read per-step; the HLO collective parse DOES weight by
    # known_trip_count, so those bytes normalize by K
    flops = float(cost.get("flops", 0.0))
    bytes_accessed = float(cost.get("bytes accessed", 0.0))
    peak_hbm = compiled_peak_bytes(compiled)
    try:
        coll = collective_bytes_by_kind(compiled.as_text())
    except Exception:  # noqa: BLE001 — text dump is backend-dependent
        logger.debug("collective parse failed", exc_info=True)
        coll = {}
    coll_per_step = {name: v / k for name, v in coll.items()}

    mesh_plan = mesh_plan if mesh_plan is not None else getattr(
        getattr(result, "strategy", None), "mesh", None)
    source = "hlo"
    if model_spec is not None and mesh_plan is not None:
        from dlrover_tpu.parallel import planner

        predicted = planner.predicted_collective_bytes(
            mesh_plan, model_spec, spec)
        comm_s = {
            fam: b / (spec.dcn_bw if fam == "pipe" else spec.ici_bw)
            for fam, b in predicted.items() if b > 0
        }
        source = "planner"
    else:
        comm_s = {name: b / spec.ici_bw
                  for name, b in coll_per_step.items() if b > 0}

    mesh = getattr(result, "mesh", None)
    n_devices = int(mesh.devices.size) if mesh is not None else 1
    record = AttributionRecord(
        flops_per_step=flops,
        bytes_accessed_per_step=bytes_accessed,
        peak_hbm_bytes=peak_hbm,
        collective_bytes=coll_per_step,
        predicted_comm_s=comm_s,
        predicted_comm_total_s=sum(comm_s.values()),
        predicted_compute_s=(flops / peak_flops if peak_flops > 0
                             else 0.0),
        peak_flops_per_s=peak_flops,
        hbm_budget_bytes=budget,
        n_devices=n_devices,
        steps_per_call=k,
        source=source,
        capture_seconds=time.monotonic() - t0,
    )
    if emit:
        emit_event(
            EventKind.ATTRIBUTION_CAPTURED,
            flops_per_step=record.flops_per_step,
            bytes_accessed_per_step=record.bytes_accessed_per_step,
            arithmetic_intensity=round(record.arithmetic_intensity, 4),
            peak_hbm_mb=round(record.peak_hbm_bytes / _MB, 3),
            predicted_comm_total_s=round(
                record.predicted_comm_total_s, 6),
            predicted_compute_s=round(record.predicted_compute_s, 9),
            peak_flops_per_s=record.peak_flops_per_s,
            n_devices=record.n_devices,
            steps_per_call=record.steps_per_call,
            source=record.source,
            capture_seconds=round(record.capture_seconds, 3),
        )
    logger.info(
        "attribution captured: %.3g flops/step, %.3g bytes, peak HBM "
        "%.1f MB, comm %s (%.2fs, source=%s)",
        record.flops_per_step, record.bytes_accessed_per_step,
        record.peak_hbm_bytes / _MB,
        {n: f"{b / 1e6:.2f}MB" for n, b in coll_per_step.items()},
        record.capture_seconds, source,
    )
    return record


# -- measured overlap: jax.profiler trace -> device-time buckets --------------

# op-name patterns per category; first match wins. Collectives before
# compute: a fused op named "fusion.all-reduce..." is traffic.
_CATEGORY_PATTERNS: Tuple[Tuple[str, re.Pattern], ...] = (
    ("collective", re.compile(
        r"all-reduce|all-gather|reduce-scatter|all-to-all|"
        r"collective-permute|collective_permute|send\b|recv\b|"
        r"cross_replica", re.IGNORECASE)),
    ("infeed", re.compile(r"infeed|outfeed|host-to-device|"
                          r"device-to-host|transfer", re.IGNORECASE)),
    ("compute", re.compile(
        r"fusion|dot|conv|matmul|gemm|scatter|gather|reduce|"
        r"select|iota|broadcast|transpose|copy|sort|rng|custom-call",
        re.IGNORECASE)),
)


def categorize_op(name: str) -> str:
    """Trace-event op name -> device-time category
    (collective / infeed / compute / other)."""
    for category, pat in _CATEGORY_PATTERNS:
        if pat.search(name or ""):
            return category
    return "other"


def load_trace(path: str) -> List[Dict]:
    """Read a Chrome trace-event file (``.json`` or ``.json.gz``,
    either a bare event list or the ``{"traceEvents": [...]}``
    envelope) — the format ``jax.profiler`` dumps as
    ``*.trace.json.gz`` under a profile directory."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as fh:
        data = json.load(fh)
    if isinstance(data, dict):
        data = data.get("traceEvents", [])
    return [e for e in data if isinstance(e, dict)]


def find_trace_files(profile_dir: str) -> List[str]:
    """Every ``*.trace.json[.gz]`` under a profiler dump directory."""
    out: List[str] = []
    for root, _dirs, files in os.walk(profile_dir):
        for name in files:
            if name.endswith((".trace.json", ".trace.json.gz")):
                out.append(os.path.join(root, name))
    return sorted(out)


def parse_trace_events(records: List[Dict]) -> Dict[str, Any]:
    """Partition a trace's complete ('ph' == 'X') events into
    per-category seconds. Real profiler dumps hold MANY lanes (device
    cores, host threads) whose events overlap in time, so the sums are
    lane-aware:

      * category seconds (``collective_s`` …) sum over every lane;
      * ``busy_s`` is the busiest single (pid, tid) lane's busy time —
        the device cannot be busier than its busiest lane, and a host
        TraceMe lane must not double-count the wall;
      * ``idle_s`` is the wall envelope minus that busiest lane;
      * ``measured_comm_frac`` is collective over the CATEGORIZED
        device-op time (collective + compute + infeed) — uncategorized
        host-side lanes cannot dilute the communication share this
        exists to measure (the *measured* counterpart of the derived
        exposed-comm upper bound)."""
    per_cat: Dict[str, float] = {}
    per_track: Dict[Tuple, float] = {}
    t_min = float("inf")
    t_max = float("-inf")
    n_events = 0
    for e in records:
        if e.get("ph") != "X":
            continue
        try:
            start = float(e.get("ts", 0.0))
            dur = float(e.get("dur", 0.0))
        except (TypeError, ValueError):
            continue
        if dur <= 0:
            continue
        n_events += 1
        cat = categorize_op(str(e.get("name", "")))
        per_cat[cat] = per_cat.get(cat, 0.0) + dur
        track = (e.get("pid"), e.get("tid"))
        per_track[track] = per_track.get(track, 0.0) + dur
        t_min = min(t_min, start)
        t_max = max(t_max, start + dur)
    # trace timestamps are microseconds
    wall = max(0.0, (t_max - t_min)) / 1e6 if n_events else 0.0
    seconds = {cat: v / 1e6 for cat, v in per_cat.items()}
    busy_s = max(per_track.values()) / 1e6 if per_track else 0.0
    collective_s = seconds.get("collective", 0.0)
    categorized_s = (collective_s + seconds.get("compute", 0.0)
                     + seconds.get("infeed", 0.0))
    return {
        "events": n_events,
        "wall_s": round(wall, 6),
        "busy_s": round(busy_s, 6),
        "idle_s": round(max(0.0, wall - busy_s), 6),
        "collective_s": round(collective_s, 6),
        "compute_s": round(seconds.get("compute", 0.0), 6),
        "infeed_s": round(seconds.get("infeed", 0.0), 6),
        "other_s": round(seconds.get("other", 0.0), 6),
        "measured_comm_frac": round(
            collective_s / categorized_s, 4
        ) if categorized_s > 0 else 0.0,
    }


def parse_trace_path(path: str) -> Dict[str, Any]:
    """``parse_trace_events`` over one file or every trace under a
    profiler dump directory (events merge into one bucket set)."""
    if os.path.isdir(path):
        files = find_trace_files(path)
        if not files:
            raise FileNotFoundError(
                f"no *.trace.json[.gz] under {path}")
        records: List[Dict] = []
        for f in files:
            records.extend(load_trace(f))
        report = parse_trace_events(records)
        report["source_files"] = len(files)
        return report
    return parse_trace_events(load_trace(path))
