"""dlrover_tpu.telemetry — unified observability substrate.

Three planes, one package (see docs/observability.md):

  metrics   lock-light registry (counters/gauges/histograms) with
            Prometheus text exposition (``exporter``)
  events    append-only JSONL lifecycle timeline; MTTR and recovery
            counts are DERIVED from it (``mttr``, the CLI)
  tracing   cheap host spans -> Chrome/Perfetto JSON, plus the
            executor's on-demand ``jax.profiler`` window

All metric/event/span names live in ``names`` (enforced by lint rule
DLR007).
"""

from dlrover_tpu.telemetry import names
from dlrover_tpu.telemetry.events import (
    emit_event,
    read_events,
    recent_events,
)
from dlrover_tpu.telemetry.metrics import (
    DURATION_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    NullRegistry,
    get_registry,
    process_registry,
)
from dlrover_tpu.telemetry.correlate import (
    export_merged_trace,
    incident_records,
)
from dlrover_tpu.telemetry.goodput import derive_goodput
from dlrover_tpu.telemetry.mttr import derive_incidents, mttr_report
from dlrover_tpu.telemetry.names import EventKind, SpanName
from dlrover_tpu.telemetry.trace_context import (
    current_trace_id,
    new_trace_id,
    trace_scope,
)
from dlrover_tpu.telemetry.tracing import (
    add_instant,
    export_chrome_trace,
    span,
)

__all__ = [
    "names",
    "EventKind",
    "SpanName",
    "emit_event",
    "read_events",
    "recent_events",
    "DURATION_BUCKETS",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "NullRegistry",
    "get_registry",
    "process_registry",
    "derive_incidents",
    "mttr_report",
    "derive_goodput",
    "export_merged_trace",
    "incident_records",
    "current_trace_id",
    "new_trace_id",
    "trace_scope",
    "add_instant",
    "export_chrome_trace",
    "span",
]
