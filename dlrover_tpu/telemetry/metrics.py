"""Lock-light metrics registry: counters, gauges, fixed-bucket histograms.

Design parity: the reference DLRover feeds runtime stats through a
master-side reporter into Brain; ElasWave-class elastic systems
(PAPERS.md) additionally need *worker-local* low-overhead series (step
time, window occupancy, recovery counters) scrapeable without touching
the hot loop. This registry is that substrate.

Lock discipline: the registry lock guards metric *creation* only. The
per-sample paths (``inc``/``set``/``observe``) are plain attribute
updates — under CPython's GIL a concurrent race can at worst lose an
increment, which is an acceptable error for monitoring series and keeps
the hot-loop cost to ~1µs. Nothing on the sample path allocates, locks,
or syscalls.

Enable/disable: ``get_registry()`` consults the Context knob
``telemetry_enabled`` and hands back a null registry when off — call
sites hold metric handles with an identical API either way, so
instrumentation carries zero branches.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple

# Default duration buckets (seconds): 0.5ms .. 60s, roughly log-spaced.
# Chosen to straddle both the CPU-mesh tiny-model regime (tier-1, ~ms
# steps) and real TPU steps (~100ms-10s).
DURATION_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

# Serving-latency buckets (seconds): 50µs .. 30s. Decode steps and
# TPOT sit at sub-ms to ~10ms — on DURATION_BUCKETS everything below
# 0.5ms collapses into the first bucket and the interpolated
# percentiles are fiction at exactly the scale an SLO judges.
LATENCY_BUCKETS: Tuple[float, ...] = (
    0.00005, 0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
    0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
)

# Count-valued buckets (tokens per request, items per batch): a COUNT
# observed into seconds-scale buckets lands every real value in the
# overflow tail — the bucket-resolution trap `histogram()` guards
# against below.
COUNT_BUCKETS: Tuple[float, ...] = (
    1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096, 16384,
)


def _label_suffix(labels: Optional[Dict[str, str]]) -> str:
    """Prometheus-style sorted label block ('' when unlabeled)."""
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{v}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


class Counter:
    """Monotonically increasing count."""

    kind = "counter"

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels) if labels else None
        self.value = 0.0

    def inc(self, v: float = 1.0) -> None:
        self.value += v


class Gauge:
    """A value that goes up and down."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels) if labels else None
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def inc(self, v: float = 1.0) -> None:
        self.value += v

    def dec(self, v: float = 1.0) -> None:
        self.value -= v


def percentile_from_counts(bounds: Sequence[float],
                           counts: Sequence[int],
                           q: float,
                           with_overflow: bool = False):
    """Approximate quantile (0 < q <= 1) over per-bucket counts
    (``len(counts) == len(bounds) + 1``, +Inf bucket last), with linear
    interpolation inside the winning bucket; None when empty. Taking
    counts explicitly lets callers diff two snapshots and quote the
    quantiles of just the last window (the executor's speed log).

    A quantile landing in the +Inf bucket can only be reported as the
    last finite bound — a silent clamp that would understate a pathology
    precisely when it is worst. ``with_overflow=True`` returns
    ``(value, overflow)`` instead, with ``overflow=True`` on a clamped
    tail, so consumers (the straggler detector, diagnosis verdicts)
    can treat the value as a LOWER bound rather than a measurement."""
    total = sum(counts)
    if total <= 0:
        return (None, False) if with_overflow else None
    rank = q * total
    cum = 0
    lo = 0.0
    for i, bound in enumerate(bounds):
        prev = cum
        cum += counts[i]
        if cum >= rank:
            frac = (rank - prev) / max(counts[i], 1)
            value = lo + (bound - lo) * min(max(frac, 0.0), 1.0)
            return (value, False) if with_overflow else value
        lo = bound
    # landed in the +Inf bucket: the last finite bound is a CLAMP
    return (bounds[-1], True) if with_overflow else bounds[-1]


class Histogram:
    """Fixed-bucket histogram (cumulative exposition, Prometheus-style).

    ``percentile(q)`` interpolates inside the winning bucket — exact
    enough for operator-facing p50/p95 step-time lines; observations
    landing in the +Inf bucket report the largest finite bound.
    """

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = DURATION_BUCKETS,
                 labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels) if labels else None
        bounds = sorted(float(b) for b in buckets)
        if not bounds:
            raise ValueError(f"histogram {name}: empty bucket list")
        self.bounds: Tuple[float, ...] = tuple(bounds)
        # per-bucket (non-cumulative) counts; the +Inf bucket is last
        self.counts: List[int] = [0] * (len(bounds) + 1)
        self.count = 0
        self.sum = 0.0

    def observe(self, v: float) -> None:
        self.count += 1
        self.sum += v
        # linear scan: bucket lists are short (<= ~16) and the common
        # case (sub-ms host ops) exits in the first few probes
        for i, bound in enumerate(self.bounds):
            if v <= bound:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def percentile(self, q: float, with_overflow: bool = False):
        """Approximate quantile (0 < q <= 1); None when empty. With
        ``with_overflow=True`` returns ``(value, overflow)`` — overflow
        marks a +Inf-bucket clamp (value is a lower bound)."""
        return percentile_from_counts(
            self.bounds, self.counts, q, with_overflow=with_overflow)

    def snapshot_counts(self) -> Tuple[int, ...]:
        """Point-in-time copy of the per-bucket counts — diff two of
        these (``percentile_from_counts``) for windowed quantiles."""
        return tuple(self.counts)


class _NullMetric:
    """No-op stand-in with the union of the real APIs."""

    kind = "null"
    name = ""
    value = 0.0
    count = 0
    sum = 0.0

    def inc(self, v: float = 1.0) -> None:
        pass

    def dec(self, v: float = 1.0) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass

    def percentile(self, q: float, with_overflow: bool = False):
        return (None, False) if with_overflow else None

    def snapshot_counts(self) -> None:
        return None


_NULL_METRIC = _NullMetric()


class MetricsRegistry:
    """Name -> metric; creation is idempotent and thread-safe.

    A metric may carry a label set (``labels={"node": "3"}``) — each
    distinct (name, labels) pair is its own series (the per-node runtime
    series the master exposes), rendered Prometheus-style as
    ``name{node="3"}``. The NAME must still be a ``telemetry.names``
    constant (DLR007); labels carry the per-entity dimension."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}
        # family name -> kind: one exposition family must hold ONE
        # metric kind, or the rendered TYPE header lies for every
        # labeled sibling (scrapers reject the whole family)
        self._family_kinds: Dict[str, str] = {}

    def _get_or_create(self, cls, name: str, help: str,
                       labels: Optional[Dict[str, str]] = None, **kwargs):
        key = name + _label_suffix(labels)
        metric = self._metrics.get(key)
        if metric is None:
            with self._lock:
                metric = self._metrics.get(key)
                if metric is None:
                    family_kind = self._family_kinds.get(name)
                    if family_kind is not None and family_kind != cls.kind:
                        raise ValueError(
                            f"metric family {name!r} already registered "
                            f"as {family_kind}, requested {cls.kind}"
                        )
                    metric = cls(name, help=help, labels=labels, **kwargs)
                    self._metrics[key] = metric
                    self._family_kinds[name] = cls.kind
        if not isinstance(metric, cls):
            raise ValueError(
                f"metric {key!r} already registered as {metric.kind}, "
                f"requested {cls.__name__.lower()}"
            )
        return metric

    def counter(self, name: str, help: str = "",
                labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._get_or_create(Counter, name, help, labels=labels)

    def gauge(self, name: str, help: str = "",
              labels: Optional[Dict[str, str]] = None) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels=labels)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DURATION_BUCKETS,
                  labels: Optional[Dict[str, str]] = None) -> Histogram:
        # the bucket-resolution trap: DURATION_BUCKETS (0.5ms..60s,
        # seconds) under a histogram that does not measure seconds
        # (its name must say so — Prometheus unit-suffix convention)
        # puts every real observation in one bucket or the overflow
        # tail, and the interpolated percentiles become fiction. A
        # count/size histogram must declare its own scale explicitly
        # (COUNT_BUCKETS, or a domain-specific list).
        if tuple(buckets) == DURATION_BUCKETS and not name.endswith(
                "_seconds"):
            raise ValueError(
                f"histogram {name!r} uses the seconds-scale "
                "DURATION_BUCKETS but is not named *_seconds — a "
                "non-duration value would land entirely in one "
                "bucket/overflow; pass explicit buckets "
                "(e.g. metrics.COUNT_BUCKETS)"
            )
        return self._get_or_create(Histogram, name, help, labels=labels,
                                   buckets=buckets)

    def get(self, name: str, labels: Optional[Dict[str, str]] = None):
        return self._metrics.get(name + _label_suffix(labels))

    def remove(self, name: str,
               labels: Optional[Dict[str, str]] = None) -> None:
        """Retract one series from the exposition (the per-node
        attribution gauges use this when a stat becomes ABSENT — a
        stale last value must not keep exporting as if it were live).
        The family's kind registration survives for later re-creation."""
        with self._lock:
            self._metrics.pop(name + _label_suffix(labels), None)

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return dict(self._metrics)

    def reset(self) -> None:
        """Drop every metric (tests / bench A-B runs)."""
        with self._lock:
            self._metrics.clear()
            self._family_kinds.clear()

    # -- exposition ----------------------------------------------------------

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4. Series sharing a
        family name (labeled variants) render under ONE HELP/TYPE
        header, each line carrying its label block."""
        lines: List[str] = []
        families: Dict[str, List] = {}
        for key in sorted(self.snapshot()):
            m = self._metrics.get(key)
            if m is not None:
                families.setdefault(m.name, []).append(m)
        for name in sorted(families):
            series = families[name]
            help_text = next((m.help for m in series if m.help), "")
            if help_text:
                lines.append(f"# HELP {name} {help_text}")
            lines.append(f"# TYPE {name} {series[0].kind}")
            for m in series:
                base = dict(m.labels or {})
                lbl = _label_suffix(base)
                if isinstance(m, Histogram):
                    cum = 0
                    for i, bound in enumerate(m.bounds):
                        cum += m.counts[i]
                        le = _label_suffix({**base, "le": _fmt(bound)})
                        lines.append(f"{name}_bucket{le} {cum}")
                    le = _label_suffix({**base, "le": "+Inf"})
                    lines.append(f"{name}_bucket{le} {m.count}")
                    lines.append(f"{name}_sum{lbl} {_fmt(m.sum)}")
                    lines.append(f"{name}_count{lbl} {m.count}")
                else:
                    lines.append(f"{name}{lbl} {_fmt(m.value)}")
        return "\n".join(lines) + ("\n" if lines else "")


def _fmt(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


class NullRegistry:
    """API-compatible black hole handed out when telemetry is off."""

    def counter(self, name: str, help: str = "",
                labels: Optional[Dict[str, str]] = None) -> _NullMetric:
        return _NULL_METRIC

    def gauge(self, name: str, help: str = "",
              labels: Optional[Dict[str, str]] = None) -> _NullMetric:
        return _NULL_METRIC

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DURATION_BUCKETS,
                  labels: Optional[Dict[str, str]] = None) -> _NullMetric:
        return _NULL_METRIC

    def get(self, name: str, labels: Optional[Dict[str, str]] = None):
        return None

    def remove(self, name: str,
               labels: Optional[Dict[str, str]] = None) -> None:
        pass

    def snapshot(self) -> Dict[str, object]:
        return {}

    def reset(self) -> None:
        pass

    def render_prometheus(self) -> str:
        return ""


_REGISTRY = MetricsRegistry()
_NULL_REGISTRY = NullRegistry()


def get_registry():
    """The process registry — or the null registry when the Context
    knob ``telemetry_enabled`` is off. Call sites fetch handles once
    (at construction), so toggling the knob affects components built
    AFTER the toggle; the bench's A/B runs rely on exactly that."""
    from dlrover_tpu.common.config import get_context

    if not getattr(get_context(), "telemetry_enabled", True):
        return _NULL_REGISTRY
    return _REGISTRY


def process_registry() -> MetricsRegistry:
    """The real registry regardless of the enable knob (exposition/CLI
    paths, which must dump whatever was recorded)."""
    return _REGISTRY
