"""Lock-light metrics registry: counters, gauges, fixed-bucket histograms.

Design parity: the reference DLRover feeds runtime stats through a
master-side reporter into Brain; ElasWave-class elastic systems
(PAPERS.md) additionally need *worker-local* low-overhead series (step
time, window occupancy, recovery counters) scrapeable without touching
the hot loop. This registry is that substrate.

Lock discipline: the registry lock guards metric *creation* only. The
per-sample paths (``inc``/``set``/``observe``) are plain attribute
updates — under CPython's GIL a concurrent race can at worst lose an
increment, which is an acceptable error for monitoring series and keeps
the hot-loop cost to ~1µs. Nothing on the sample path allocates, locks,
or syscalls.

Enable/disable: ``get_registry()`` consults the Context knob
``telemetry_enabled`` and hands back a null registry when off — call
sites hold metric handles with an identical API either way, so
instrumentation carries zero branches.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple

# Default duration buckets (seconds): 0.5ms .. 60s, roughly log-spaced.
# Chosen to straddle both the CPU-mesh tiny-model regime (tier-1, ~ms
# steps) and real TPU steps (~100ms-10s).
DURATION_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


class Counter:
    """Monotonically increasing count."""

    kind = "counter"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0.0

    def inc(self, v: float = 1.0) -> None:
        self.value += v


class Gauge:
    """A value that goes up and down."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)

    def inc(self, v: float = 1.0) -> None:
        self.value += v

    def dec(self, v: float = 1.0) -> None:
        self.value -= v


def percentile_from_counts(bounds: Sequence[float],
                           counts: Sequence[int],
                           q: float) -> Optional[float]:
    """Approximate quantile (0 < q <= 1) over per-bucket counts
    (``len(counts) == len(bounds) + 1``, +Inf bucket last), with linear
    interpolation inside the winning bucket; None when empty. Taking
    counts explicitly lets callers diff two snapshots and quote the
    quantiles of just the last window (the executor's speed log)."""
    total = sum(counts)
    if total <= 0:
        return None
    rank = q * total
    cum = 0
    lo = 0.0
    for i, bound in enumerate(bounds):
        prev = cum
        cum += counts[i]
        if cum >= rank:
            frac = (rank - prev) / max(counts[i], 1)
            return lo + (bound - lo) * min(max(frac, 0.0), 1.0)
        lo = bound
    return bounds[-1]  # landed in the +Inf bucket


class Histogram:
    """Fixed-bucket histogram (cumulative exposition, Prometheus-style).

    ``percentile(q)`` interpolates inside the winning bucket — exact
    enough for operator-facing p50/p95 step-time lines; observations
    landing in the +Inf bucket report the largest finite bound.
    """

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = DURATION_BUCKETS):
        self.name = name
        self.help = help
        bounds = sorted(float(b) for b in buckets)
        if not bounds:
            raise ValueError(f"histogram {name}: empty bucket list")
        self.bounds: Tuple[float, ...] = tuple(bounds)
        # per-bucket (non-cumulative) counts; the +Inf bucket is last
        self.counts: List[int] = [0] * (len(bounds) + 1)
        self.count = 0
        self.sum = 0.0

    def observe(self, v: float) -> None:
        self.count += 1
        self.sum += v
        # linear scan: bucket lists are short (<= ~16) and the common
        # case (sub-ms host ops) exits in the first few probes
        for i, bound in enumerate(self.bounds):
            if v <= bound:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def percentile(self, q: float) -> Optional[float]:
        """Approximate quantile (0 < q <= 1); None when empty."""
        return percentile_from_counts(self.bounds, self.counts, q)

    def snapshot_counts(self) -> Tuple[int, ...]:
        """Point-in-time copy of the per-bucket counts — diff two of
        these (``percentile_from_counts``) for windowed quantiles."""
        return tuple(self.counts)


class _NullMetric:
    """No-op stand-in with the union of the real APIs."""

    kind = "null"
    name = ""
    value = 0.0
    count = 0
    sum = 0.0

    def inc(self, v: float = 1.0) -> None:
        pass

    def dec(self, v: float = 1.0) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass

    def percentile(self, q: float) -> Optional[float]:
        return None

    def snapshot_counts(self) -> None:
        return None


_NULL_METRIC = _NullMetric()


class MetricsRegistry:
    """Name -> metric; creation is idempotent and thread-safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get_or_create(self, cls, name: str, help: str, **kwargs):
        metric = self._metrics.get(name)
        if metric is None:
            with self._lock:
                metric = self._metrics.get(name)
                if metric is None:
                    metric = cls(name, help=help, **kwargs)
                    self._metrics[name] = metric
        if not isinstance(metric, cls):
            raise ValueError(
                f"metric {name!r} already registered as {metric.kind}, "
                f"requested {cls.__name__.lower()}"
            )
        return metric

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DURATION_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def get(self, name: str):
        return self._metrics.get(name)

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return dict(self._metrics)

    def reset(self) -> None:
        """Drop every metric (tests / bench A-B runs)."""
        with self._lock:
            self._metrics.clear()

    # -- exposition ----------------------------------------------------------

    def render_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        lines: List[str] = []
        for name in sorted(self.snapshot()):
            m = self._metrics.get(name)
            if m is None:
                continue
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            lines.append(f"# TYPE {name} {m.kind}")
            if isinstance(m, Histogram):
                cum = 0
                for i, bound in enumerate(m.bounds):
                    cum += m.counts[i]
                    lines.append(
                        f'{name}_bucket{{le="{_fmt(bound)}"}} {cum}'
                    )
                lines.append(f'{name}_bucket{{le="+Inf"}} {m.count}')
                lines.append(f"{name}_sum {_fmt(m.sum)}")
                lines.append(f"{name}_count {m.count}")
            else:
                lines.append(f"{name} {_fmt(m.value)}")
        return "\n".join(lines) + ("\n" if lines else "")


def _fmt(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


class NullRegistry:
    """API-compatible black hole handed out when telemetry is off."""

    def counter(self, name: str, help: str = "") -> _NullMetric:
        return _NULL_METRIC

    def gauge(self, name: str, help: str = "") -> _NullMetric:
        return _NULL_METRIC

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DURATION_BUCKETS) -> _NullMetric:
        return _NULL_METRIC

    def get(self, name: str):
        return None

    def snapshot(self) -> Dict[str, object]:
        return {}

    def reset(self) -> None:
        pass

    def render_prometheus(self) -> str:
        return ""


_REGISTRY = MetricsRegistry()
_NULL_REGISTRY = NullRegistry()


def get_registry():
    """The process registry — or the null registry when the Context
    knob ``telemetry_enabled`` is off. Call sites fetch handles once
    (at construction), so toggling the knob affects components built
    AFTER the toggle; the bench's A/B runs rely on exactly that."""
    from dlrover_tpu.common.config import get_context

    if not getattr(get_context(), "telemetry_enabled", True):
        return _NULL_REGISTRY
    return _REGISTRY


def process_registry() -> MetricsRegistry:
    """The real registry regardless of the enable knob (exposition/CLI
    paths, which must dump whatever was recorded)."""
    return _REGISTRY
