"""The metric/event/span name registry — the ONE place observability
names live.

Every metric name handed to the registry (``counter()``, ``gauge()``,
``histogram()``) and every event kind handed to ``emit_event()`` must be
a constant from this module: the AST lint rule DLR007 rejects string
literals at telemetry call sites anywhere else in the package, so the
name table in ``docs/observability.md`` can never silently drift from
the code, and two subsystems can never claim the same series with
slightly different spellings.

Prometheus conventions: ``_total`` counters, ``_seconds`` durations,
unitless gauges named for what they measure.
"""

from __future__ import annotations

# -- worker / executor --------------------------------------------------------

# per-optimizer-step wall time, observed at materialization (the lagged
# window means one observation per step, dt shared across a drained group)
STEP_TIME = "dlrover_step_time_seconds"
# host time spent DISPATCHING one train-step call (tracing + enqueue,
# never device compute): the async-pipeline "is Python the bottleneck?"
# series PR 3 made invisible
STEP_DISPATCH_TIME = "dlrover_step_dispatch_seconds"
# host time blocked in device_get materializing the oldest in-flight
# call — the ONE device sync of the pipeline (≈ device-bound time)
STEP_HOST_SYNC_TIME = "dlrover_step_host_sync_seconds"
# in-flight dispatch window occupancy right after a dispatch
DISPATCH_WINDOW_OCCUPANCY = "dlrover_dispatch_window_occupancy"
# how many steps behind the newest dispatch the just-materialized
# metrics are (the "lagged-metric age" of the PR 3 ring)
LAGGED_METRIC_AGE = "dlrover_lagged_metric_age_steps"
TRAIN_STEPS = "dlrover_train_steps_total"
NONFINITE_STEPS = "dlrover_nonfinite_steps_total"
NONFINITE_ROLLBACKS = "dlrover_nonfinite_rollbacks_total"
PREEMPT_NOTICES = "dlrover_preemption_notices_total"
EVAL_TIME = "dlrover_eval_seconds"

# -- live elastic recovery ----------------------------------------------------

# in-process scale events absorbed without a process restart
LIVE_RESHARDS = "dlrover_live_reshards_total"
# drain -> snapshot -> rebuild -> reshard -> ready, wall seconds
LIVE_RESHARD_TIME = "dlrover_live_reshard_seconds"
# host-DRAM TrainState snapshot (device_get) wall seconds
SNAPSHOT_TIME = "dlrover_state_snapshot_seconds"
# in-process compiled-program cache of ElasticTrainer: a same-topology
# resume that hits it pays ZERO recompiles
PROGRAM_CACHE_HITS = "dlrover_program_cache_hits_total"
PROGRAM_CACHE_MISSES = "dlrover_program_cache_misses_total"

# -- peer-redundant host snapshots (checkpoint-free recovery) -----------------
# Worker side: the SnapshotReplicator's push cycles and its in-DRAM
# ReplicaStore; fetch side: the peer-rebuild stream a recovering worker
# runs instead of an Orbax restore.

REPLICA_PUSHES = "dlrover_replica_pushes_total"
REPLICA_PUSH_FAILURES = "dlrover_replica_push_failures_total"
REPLICA_PUSH_TIME = "dlrover_replica_push_seconds"
REPLICA_BYTES_PUSHED = "dlrover_replica_bytes_pushed_total"
# peer-replica bytes resident in this worker's DRAM (budget-bounded:
# admission degrades the plan before this can OOM a worker)
REPLICA_STORE_BYTES = "dlrover_replica_store_bytes"
# chunk frames rejected by the length-prefix/crc32 checks (holder-side
# on put, fetcher-side on read — silent bitrot becomes a counted fault)
REPLICA_CHUNK_CORRUPTIONS = "dlrover_replica_chunk_corruptions_total"
# chunk fetches retried or failed over to the next replica holder
REPLICA_FETCH_RETRIES = "dlrover_replica_fetch_retries_total"
# the checkpoint-free rebuild itself: peer-fetch + device_put wall
# seconds, and the bytes streamed out of peer DRAM (vs storage: 0)
PEER_REBUILD_TIME = "dlrover_peer_rebuild_seconds"
PEER_REBUILD_BYTES = "dlrover_peer_rebuild_bytes_fetched_total"

# -- recovery readiness (continuous durability audit) --------------------------
# Master-side auditor (master/monitor/readiness.py): the
# ReplicaDirectory's assignments swept against live store inventory()
# facts. Per-node gauges are {node=}-labeled, absent-not-zero, and
# retracted when the node leaves the directory.

# 1 = every owner region of this node is held by >= k live, fresh,
# crc-committed holders; 0 = at risk (the DIAG_DURABILITY verdict
# carries the evidence). Absent until the first sweep sees the node.
READINESS_COVERAGE = "dlrover_readiness_owner_coverage"
# how many steps the node's newest fully-held replica group trails its
# reported step (fresh means <= stale_factor x the master cadence)
READINESS_STALENESS = "dlrover_readiness_staleness_steps"
# the priced recovery ladder: predicted MTTR of rung {rung=} for node
# {node=}, seconds (calibrated decomposition, EMA-corrected against
# realized incidents)
READINESS_PREDICTED_MTTR = "dlrover_readiness_predicted_mttr_seconds"
# best survivable rung index for the node (0=live_reshard,
# 1=peer_rebuild, 2=storage_restore, 3=init)
READINESS_BEST_RUNG = "dlrover_readiness_best_rung"
# audit sweeps completed, and the wall seconds one sweep costs
READINESS_SWEEPS = "dlrover_readiness_sweeps_total"
READINESS_SWEEP_TIME = "dlrover_readiness_sweep_seconds"
# durability verdicts flagged by the auditor (clears ride the shared
# DIAG_RECOVERIES counter like every other diagnosis verdict)
DIAG_DURABILITY_FLAGS = "dlrover_diagnosis_durability_total"

# ReplicaDirectory admission facts as labeled gauges (previously
# event-only): per-holder assigned replica load and remaining budget
# headroom in MB ({node=}; headroom absent when the holder is
# uncapped), plus the plan-wide admitted k and how far below the
# requested k the budget degraded it
REPLICA_HOLDER_LOAD_MB = "dlrover_replica_holder_load_mb"
REPLICA_HOLDER_HEADROOM_MB = "dlrover_replica_holder_headroom_mb"
REPLICA_ASSIGNED_K = "dlrover_replica_assigned_k"
REPLICA_DEGRADED_K = "dlrover_replica_degraded_k"

# -- rpc client ---------------------------------------------------------------

# transient-RPC retries taken by the client channel (the retry budget
# spent): a synchronized burst after a master blip shows here first
RPC_RETRIES = "dlrover_rpc_retries_total"

# -- persistent XLA compile cache ---------------------------------------------

COMPILE_CACHE_HITS = "dlrover_compile_cache_hits_total"
COMPILE_CACHE_MISSES = "dlrover_compile_cache_misses_total"
COMPILE_CACHE_ENTRIES = "dlrover_compile_cache_entries"

# -- master reporting from the worker ----------------------------------------

MASTER_REPORTS = "dlrover_master_reports_total"
MASTER_REPORT_FAILURES = "dlrover_master_report_failures_total"

# -- checkpoint ---------------------------------------------------------------

CKPT_SAVES = "dlrover_checkpoint_saves_total"
CKPT_SAVE_TIME = "dlrover_checkpoint_save_stage_seconds"
CKPT_MIRROR_TIME = "dlrover_checkpoint_mirror_seconds"
CKPT_MIRROR_TIMEOUTS = "dlrover_checkpoint_mirror_timeouts_total"
CKPT_RESTORE_TIME = "dlrover_checkpoint_restore_seconds"
CKPT_RESTORES = "dlrover_checkpoint_restores_total"

# -- agent --------------------------------------------------------------------

AGENT_WORKER_RESTARTS = "dlrover_agent_worker_restarts_total"
AGENT_HANG_DETECTIONS = "dlrover_agent_hang_detections_total"
AGENT_WORKER_FAILURES = "dlrover_agent_worker_failures_total"
RDZV_ROUNDS = "dlrover_rendezvous_rounds_total"
RDZV_TIME = "dlrover_rendezvous_seconds"

# -- master -------------------------------------------------------------------

MASTER_GLOBAL_STEP = "dlrover_master_global_step"
MASTER_TRAIN_SPEED = "dlrover_master_train_speed_steps_per_second"
MASTER_FAILURE_REPORTS = "dlrover_master_failure_reports_total"
MASTER_RUNTIME_SAMPLES = "dlrover_master_runtime_samples_total"

# -- diagnosis ----------------------------------------------------------------

ERROR_REPORTS = "dlrover_error_reports_total"
ERRORS_DEDUPED = "dlrover_error_reports_deduped_total"

# -- cluster diagnosis plane (per-node runtime series on the master) ----------

# worker-side: NodeRuntimeReport pushes sent / lost (the hook never
# raises into the train loop)
NODE_RUNTIME_REPORTS = "dlrover_node_runtime_reports_total"
NODE_RUNTIME_REPORT_FAILURES = "dlrover_node_runtime_report_failures_total"
# master-side per-node gauges (labeled {node="<id>"}), refreshed on
# every ingested report — the /metrics view of the node series
NODE_STEP_P50 = "dlrover_node_step_time_p50_seconds"
NODE_STEP_P95 = "dlrover_node_step_time_p95_seconds"
NODE_DISPATCH_P50 = "dlrover_node_dispatch_p50_seconds"
NODE_HOST_SYNC_P50 = "dlrover_node_host_sync_p50_seconds"
NODE_WINDOW_OCCUPANCY = "dlrover_node_dispatch_window_occupancy"
NODE_RSS_MB = "dlrover_node_rss_mb"
NODE_DEVICE_MEM_MB = "dlrover_node_device_mem_mb"
NODE_STEPS_TOTAL = "dlrover_node_steps_total"
NODE_REPORT_AGE = "dlrover_node_report_age_seconds"
# master-side ingest counter + verdict counters
NODE_REPORTS_INGESTED = "dlrover_master_node_reports_total"
DIAG_STRAGGLERS = "dlrover_diagnosis_stragglers_total"
DIAG_NODE_HANGS = "dlrover_diagnosis_node_hangs_total"
DIAG_RECOVERIES = "dlrover_diagnosis_recoveries_total"

# -- runtime optimizer (the telemetry -> planner -> live-reshard loop) --------

# re-plan passes run by the master-side optimizer (one per trigger that
# survived the cooldown gate: straggler verdict, recovery, world change)
OPTIMIZER_REPLANS = "dlrover_optimizer_replans_total"
# plans published to workers / suppressed by hysteresis-cooldown-dedup
OPTIMIZER_PLANS_CHOSEN = "dlrover_optimizer_plans_chosen_total"
OPTIMIZER_PLANS_REJECTED = "dlrover_optimizer_plans_rejected_total"
# calibration passes fitting the planner's cost terms to measured series
OPTIMIZER_CALIBRATIONS = "dlrover_optimizer_calibrations_total"
# worker-side: live plan applications (drain -> retune/reshard -> resume)
OPTIMIZER_PLANS_APPLIED = "dlrover_optimizer_plans_applied_total"
# wall seconds of one live plan application on the worker
OPTIMIZER_APPLY_TIME = "dlrover_optimizer_apply_seconds"
# candidate plans the memory-feasibility gate rejected BEFORE pricing
# (compiled/predicted peak HBM above the device budget)
OPTIMIZER_PLANS_MEMORY_REJECTED = (
    "dlrover_optimizer_plans_memory_rejected_total"
)

# -- performance attribution (device-time & HBM accounting) -------------------
# Derived from the per-compiled-program attribution record
# (telemetry.attribution: exact FLOPs / bytes-accessed / peak HBM read
# at compile time) fused with measured step times at materialization.
# Gauges are created ONLY once a record was captured — absent means
# "not measured", never 0.

# live model-FLOPs utilization: compiled per-device FLOPs/step over
# (measured step seconds x device peak) — utils/prof.derived_mfu
ATTR_MFU = "dlrover_attribution_mfu"
# compiled FLOPs / bytes-accessed: low values = HBM-bound on TPU
ATTR_ARITH_INTENSITY = "dlrover_attribution_arithmetic_intensity"
# clamped (1 - ideal compute seconds / measured step seconds): an
# UPPER bound on the un-overlapped communication share of the step
ATTR_EXPOSED_COMM_FRAC = "dlrover_attribution_exposed_comm_fraction"
# the static record, exported for scrape-side math
ATTR_FLOPS_PER_STEP = "dlrover_attribution_flops_per_step"
ATTR_PEAK_HBM_MB = "dlrover_attribution_compiled_peak_hbm_mb"
ATTR_COMM_PREDICTED_S = "dlrover_attribution_predicted_comm_seconds"
# device HBM headroom: bytes_limit - bytes_in_use where the backend
# exposes memory stats (absent on CPU — never a fake 0)
ATTR_HBM_HEADROOM_MB = "dlrover_attribution_hbm_headroom_mb"

# master-side per-node mirrors (labeled {node="<id>"}), fed by the
# NodeRuntimeReport push — the cluster view of the same quantities
NODE_MFU = "dlrover_node_mfu"
NODE_EXPOSED_COMM_FRAC = "dlrover_node_exposed_comm_fraction"
NODE_FLOPS_PER_STEP = "dlrover_node_flops_per_step"
NODE_PEAK_HBM_MB = "dlrover_node_compiled_peak_hbm_mb"
NODE_HBM_HEADROOM_MB = "dlrover_node_hbm_headroom_mb"

# -- data plane (shard dispatch & input pipeline) -----------------------------
# Worker side instruments the path batch data takes to the device
# (sharding client RPCs, the H2D prefetcher, the executor's wait for
# the next host batch); master side accounts the shard queues. The
# derived INPUT_WAIT_FRAC / NODE_INPUT_WAIT_FRAC gauges follow the
# absent-not-zero discipline of ATTR_MFU: no gauge exists before the
# first measured window, and per-dataset shard gauges exist only
# between the first dispatched shard and dataset completion.

# worker-side: ShardingClient (the master's todo/doing window)
DATA_SHARD_FETCH_TIME = "dlrover_data_shard_fetch_seconds"
DATA_SHARDS_FETCHED = "dlrover_data_shards_fetched_total"
DATA_SHARDS_COMPLETED = "dlrover_data_shards_completed_total"
# batch-done credits whose RPC failed and were re-queued for the next
# report (the credit is restored, never silently dropped)
DATA_BATCH_REPORT_RETRIES = "dlrover_data_batch_report_retries_total"
# worker-side: the H2D prefetcher (DevicePreloader / DevicePrefetcher)
DATA_PREFETCH_QUEUE_DEPTH = "dlrover_data_prefetch_queue_depth"
# producer wait: the pump blocked handing a ready batch to a full
# queue (consumer-slow — the healthy direction)
DATA_PRODUCER_WAIT_TIME = "dlrover_data_producer_wait_seconds"
# consumer wait: the train loop blocked on an empty prefetch queue
# (producer-slow — the input-bound direction)
DATA_CONSUMER_WAIT_TIME = "dlrover_data_consumer_wait_seconds"
# worker-side: executor host time blocked fetching the next batch
INPUT_WAIT_TIME = "dlrover_input_wait_seconds"
# fraction of the last materialization window spent waiting on input
# (absent until the first measured window — never a fake 0)
INPUT_WAIT_FRAC = "dlrover_input_wait_fraction"

# master-side shard lifecycle, labeled {dataset="<name>"} — created at
# the first dispatched shard, retracted when the dataset completes
DATA_SHARDS_TODO = "dlrover_data_shards_todo"
DATA_SHARDS_DOING = "dlrover_data_shards_doing"
DATA_SHARDS_DONE = "dlrover_data_shards_done"
DATA_EPOCH = "dlrover_data_epoch"
DATA_EPOCH_PROGRESS = "dlrover_data_epoch_progress"
# dispatch -> completion wall seconds of one shard
DATA_SHARD_LATENCY = "dlrover_data_shard_latency_seconds"
# shards requeued by the timeout monitor (straggler mitigation — each
# recovery risks duplicate data, so it is counted and evented)
DATA_SHARDS_TIMEOUT_RECOVERED = (
    "dlrover_data_shards_timeout_recovered_total"
)
# master-side per-node consumption, labeled {node="<id>"}
DATA_NODE_SHARDS_COMPLETED = "dlrover_data_node_shards_completed_total"
DATA_NODE_RECORDS_DONE = "dlrover_data_node_records_done_total"
# master-side per-node mirror of the worker's input-wait fraction
# (rides NodeRuntimeReport like NODE_MFU; absent until measured)
NODE_INPUT_WAIT_FRAC = "dlrover_node_input_wait_fraction"

# -- serving tier (dlrover_tpu.serving) ---------------------------------------
# Worker side: the continuous-batching decode loop; master side: the
# request router's ledger (the PR 9 shard ledger generalized).

# worker-side decode loop
SERVE_DECODE_STEPS = "dlrover_serve_decode_steps_total"
SERVE_TOKENS = "dlrover_serve_tokens_total"
SERVE_PREFILL_CHUNKS = "dlrover_serve_prefill_chunks_total"
SERVE_ADMISSIONS = "dlrover_serve_admissions_total"
SERVE_SLOT_OCCUPANCY = "dlrover_serve_slot_occupancy"
SERVE_STEP_TIME = "dlrover_serve_decode_step_seconds"
# worker-side elasticity: live serving-world resizes (requests held,
# never dropped)
SERVE_RESIZES = "dlrover_serve_resizes_total"
SERVE_RESIZE_TIME = "dlrover_serve_resize_seconds"
# master-side router ledger (requests, not shards)
SERVE_REQUESTS_SUBMITTED = "dlrover_serve_requests_submitted_total"
SERVE_REQUESTS_COMPLETED = "dlrover_serve_requests_completed_total"
SERVE_REQUESTS_QUEUED = "dlrover_serve_requests_queued"
SERVE_REQUESTS_LEASED = "dlrover_serve_requests_leased"
# requests DROPPED (lost without completion or re-lease): the resize
# wedge pins this at exactly zero
SERVE_REQUESTS_DROPPED = "dlrover_serve_requests_dropped_total"
# leases that expired and were re-queued to a live worker (the shard
# re-dispatch machinery re-pointed at requests — duplicate decode
# work, so counted and evented like DATA_SHARDS_TIMEOUT_RECOVERED)
SERVE_LEASES_EXPIRED = "dlrover_serve_leases_expired_total"
# per-request latency accounting on the master. The full SLO
# decomposition: queue-wait (enqueue -> lease), TTFT (admit -> first
# token), TPOT (inter-token: (e2e - ttft) / (tokens - 1)), e2e — all
# on the serving LATENCY_BUCKETS (sub-ms resolution; the seconds-scale
# DURATION_BUCKETS would flatten a decode-step-scale latency into its
# first bucket). SERVE_PREFILL_TIME is worker-side (admit -> prompt
# fully prefilled).
SERVE_TTFT_TIME = "dlrover_serve_ttft_seconds"
SERVE_E2E_TIME = "dlrover_serve_e2e_seconds"
SERVE_QUEUE_WAIT_TIME = "dlrover_serve_queue_wait_seconds"
SERVE_TPOT_TIME = "dlrover_serve_tpot_seconds"
SERVE_PREFILL_TIME = "dlrover_serve_prefill_seconds"
# tokens generated per completed request: a COUNT, not a duration —
# it takes explicit count-scale buckets (metrics.COUNT_BUCKETS); the
# registry refuses duration buckets on a non-``_seconds`` histogram
SERVE_TOKENS_PER_REQUEST = "dlrover_serve_tokens_per_request"
# worker-side shared prefix pool (radix-indexed KV reuse, copy-on-
# admit): hit/miss on admission, pages LRU-evicted from the pool,
# prefill tokens NOT recomputed because their pages were copied from
# the pool, and the pool occupancy gauges the HBM gate prices
SERVE_PREFIX_HITS = "dlrover_serve_prefix_hits_total"
SERVE_PREFIX_MISSES = "dlrover_serve_prefix_misses_total"
SERVE_PREFIX_EVICTIONS = "dlrover_serve_prefix_evictions_total"
SERVE_PREFIX_SAVED_TOKENS = "dlrover_serve_prefix_saved_prefill_tokens_total"
SERVE_PREFIX_POOL_USED_PAGES = "dlrover_serve_prefix_pool_used_pages"
SERVE_PREFIX_POOL_BYTES = "dlrover_serve_prefix_pool_bytes"
# master-side router: requests leased to the worker whose pool already
# holds their prefix pages (soft session affinity)
SERVE_PREFIX_AFFINITY_ROUTED = "dlrover_serve_prefix_affinity_routed_total"
# speculative decode (n-gram draft + batched multi-token verify):
# drafted = accepted + wasted at every grain — the conservation the
# router ledger checks. The accept-rate gauge is -1 until the first
# draft (no-evidence sentinel, mirrors the prefix hit-rate prior).
SERVE_SPEC_VERIFY_STEPS = "dlrover_serve_spec_verify_steps_total"
SERVE_SPEC_DRAFTED = "dlrover_serve_spec_drafted_tokens_total"
SERVE_SPEC_ACCEPTED = "dlrover_serve_spec_accepted_tokens_total"
SERVE_SPEC_WASTED = "dlrover_serve_spec_wasted_tokens_total"
SERVE_SPEC_ACCEPT_RATE = "dlrover_serve_spec_accept_rate"

# -- serving SLO plane (dlrover_tpu/serving/slo.py + master/monitor/
# serve_slo.py) ---------------------------------------------------------------
# master-side per-serve-node gauges (labeled {node="<id>"}), fed by
# the ServeRuntimeReportHook push through the NodeRuntimeReport path —
# the serving twin of the NODE_* training series
NODE_SERVE_DECODE_P50 = "dlrover_node_serve_decode_p50_seconds"
NODE_SERVE_DECODE_P95 = "dlrover_node_serve_decode_p95_seconds"
NODE_SERVE_TOKENS_PER_S = "dlrover_node_serve_tokens_per_second"
NODE_SERVE_SLOT_OCCUPANCY = "dlrover_node_serve_slot_occupancy"
NODE_SERVE_QUEUE_LEN = "dlrover_node_serve_queue_len"
NODE_SERVE_SLOTS = "dlrover_node_serve_slots"
NODE_SERVE_STEPS_TOTAL = "dlrover_node_serve_decode_steps_total"
NODE_SERVE_SPEC_ACCEPT_RATE = "dlrover_node_serve_spec_accept_rate"
# master-side SLO verdict engine: violations flagged / recovered after
# multi-window burn-rate confirmation, plus the current burn rate per
# declared target (labeled {slo="<target>"}; burn > 1 = out of SLO)
SERVE_SLO_VIOLATIONS = "dlrover_serve_slo_violations_total"
SERVE_SLO_RECOVERIES = "dlrover_serve_slo_recoveries_total"
SERVE_SLO_BURN_RATE = "dlrover_serve_slo_burn_rate"
# SLO/idle-driven serving scale proposals handed to the auto-scaler
SERVE_SCALE_PROPOSALS = "dlrover_serve_scale_proposals_total"


class EventKind:
    """Event-timeline record kinds (``telemetry.events``). Failure-edge
    kinds pair with recovery-edge kinds in the MTTR derivation
    (``telemetry.mttr``)."""

    # rendezvous lifecycle
    RDZV_JOIN = "rdzv_join"
    RDZV_COMPLETE = "rdzv_complete"
    RDZV_TIMEOUT = "rdzv_timeout"
    # scaling
    SCALE_PLAN_APPLIED = "scale_plan_applied"
    # live in-process recovery (failure edge -> recovery edge): the
    # world changed under a surviving process; drain + snapshot +
    # rebuild + reshard happen without a restart
    LIVE_RESHARD_BEGIN = "live_reshard_begin"
    LIVE_RESHARD_DONE = "live_reshard_done"
    # host-DRAM TrainState snapshot taken (the reshard/rollback source)
    STATE_SNAPSHOT = "state_snapshot"
    # agent chose to delegate a survivable membership change to the
    # workers' in-process reshard instead of restarting them
    LIVE_RESHARD_DELEGATED = "live_reshard_delegated"
    # peer-redundant host snapshots. PUSHED records a completed
    # replication cycle (step, peers, bytes); the failure-class edges
    # (DLR008: all carry error codes) mark a peer push that could not
    # land (dead peer / budget refusal), a budget-degraded plan, and a
    # holder dying mid-fetch (the fallback-to-next-replica edge).
    # PEER_REBUILD_BEGIN -> PEER_REBUILD_DONE bracket the checkpoint-
    # free recovery (the mttr "peer_rebuild" scenario);
    # PEER_REBUILD_FALLBACK is the terminal degradation to the
    # Orbax/mirror storage path.
    REPLICA_PUSHED = "replica_pushed"
    REPLICA_PUSH_FAILED = "replica_push_failed"
    REPLICA_PLAN_DEGRADED = "replica_plan_degraded"
    REPLICA_HOLDER_LOST = "replica_holder_lost"
    PEER_REBUILD_BEGIN = "peer_rebuild_begin"
    PEER_REBUILD_DONE = "peer_rebuild_done"
    PEER_REBUILD_FALLBACK = "peer_rebuild_fallback"
    # preemption (failure edge -> recovery edge)
    PREEMPT_NOTICE = "preempt_notice"
    PREEMPT_DRAIN_DONE = "preempt_drain_done"
    # checkpoint
    CKPT_SAVE = "ckpt_save"
    CKPT_MIRROR = "ckpt_mirror"
    CKPT_MIRROR_TIMEOUT = "ckpt_mirror_timeout"
    CKPT_RESTORE = "ckpt_restore"
    # numerics (failure edge -> recovery edge)
    NONFINITE_STEP = "nonfinite_step"
    ROLLBACK_RESTORED = "rollback_restored"
    # agent lifecycle (failure edges -> WORKERS_STARTED recovery edge)
    HANG_DETECTED = "hang_detected"
    WORKER_FAILED = "worker_failed"
    AGENT_RESTART = "agent_restart"
    WORKERS_STARTED = "workers_started"
    # run lifecycle
    TRAIN_START = "train_start"
    TRAIN_END = "train_end"
    # first materialized step after TRAIN_START: its latency is the
    # trace+compile(+restore) cost — the goodput ledger's compile bucket
    COMPILE_FIRST_STEP = "compile_first_step"
    # diagnosis
    ERROR_REPORT = "error_report"
    # cluster diagnosis verdicts (master-side detector, evidence
    # attached: node p50/p95, peer median, ratio, confirm windows)
    DIAG_STRAGGLER = "diag_straggler"
    DIAG_NODE_HANG = "diag_node_hang"
    DIAG_RECOVERED = "diag_recovered"
    # recovery-readiness plane (master/monitor/readiness.py).
    # DIAG_DURABILITY (failure-class, DLR008) flags ONE node whose
    # owner regions fail the durability audit — coverage lost,
    # replicas stale past the cadence allowance, or budget-degraded k
    # — with the sweep's evidence attached; cleared by DIAG_RECOVERED
    # (was=durability) once a later sweep passes.
    # READINESS_DEGRADED -> READINESS_RESTORED bracket the CLUSTER
    # posture edge (any node at risk -> none), the mttr
    # "durability_at_risk" scenario. READINESS_SWEEP summarizes a
    # sweep's verdict table, emitted only when the posture changes.
    DIAG_DURABILITY = "diag_durability"
    READINESS_DEGRADED = "readiness_degraded"
    READINESS_RESTORED = "readiness_restored"
    READINESS_SWEEP = "readiness_sweep"
    # runtime optimization loop. Master side: one REPLAN per evaluated
    # trigger (candidate table attached), then CHOSEN (plan published to
    # workers) or REJECTED (hysteresis / cooldown-dedup / already
    # optimal); CALIBRATED records the predicted-vs-observed correction
    # factors each pass fits. Worker side: APPLY_BEGIN -> APPLY_DONE
    # bracket the live drain -> retune/reshard -> resume (the mttr
    # "replan" scenario pairs them), and APPLIED lands once the
    # post-plan window measured the realized speedup against the
    # decision's prediction.
    OPTIMIZER_REPLAN = "optimizer_replan"
    OPTIMIZER_CALIBRATED = "optimizer_calibrated"
    OPTIMIZER_PLAN_CHOSEN = "optimizer_plan_chosen"
    OPTIMIZER_PLAN_REJECTED = "optimizer_plan_rejected"
    OPTIMIZER_APPLY_BEGIN = "optimizer_apply_begin"
    OPTIMIZER_APPLY_DONE = "optimizer_apply_done"
    OPTIMIZER_APPLIED = "optimizer_applied"
    # performance attribution: one record per compiled program (exact
    # FLOPs, bytes-accessed, per-collective bytes, compiled peak HBM)
    # captured through the AOT path and keyed by the program cache —
    # the forensic source of `tpurun attribution --events`
    ATTRIBUTION_CAPTURED = "attribution_captured"
    # data plane: the master's timeout monitor requeued doing shards
    # of a slow/dead worker (failure-class: the shard will be re-read
    # — duplicate data risk — so the edge carries an error code), and
    # a dataset's epoch drained (todo and doing both empty; carries
    # the cumulative shard/record accounting — the forensic source of
    # `tpurun data --events`)
    DATA_SHARD_TIMEOUT = "data_shard_timeout"
    DATA_EPOCH_END = "data_epoch_end"
    # serving tier: run lifecycle, the live serving-world resize
    # (failure edge -> recovery edge for the serving_resize MTTR
    # scenario), and the failure-class request edges (eviction when a
    # request cannot fit the pool; a lease expiring on a dead worker
    # and re-queueing — both carry error codes, DLR008)
    SERVE_START = "serve_start"
    SERVE_END = "serve_end"
    SERVE_RESIZE_BEGIN = "serve_resize_begin"
    SERVE_RESIZE_DONE = "serve_resize_done"
    SERVE_REQUEST_EVICTED = "serve_request_evicted"
    SERVE_LEASE_EXPIRED = "serve_lease_expired"
    # per-request lifecycle (every record carries the request's trace
    # id, minted at Router.submit, so `tpurun trace --events` renders
    # one lane per request with flow arrows across the router and
    # worker pids): submitted/leased/completed on the router,
    # prefill-chunk/first-token/done on the worker
    SERVE_REQUEST_SUBMITTED = "serve_request_submitted"
    SERVE_REQUEST_LEASED = "serve_request_leased"
    SERVE_REQUEST_COMPLETED = "serve_request_completed"
    SERVE_PREFILL_CHUNK = "serve_prefill_chunk"
    SERVE_FIRST_TOKEN = "serve_first_token"
    SERVE_REQUEST_DONE = "serve_request_done"
    # shared prefix pool: a request admitted with matched pages copied
    # from the pool (carries hit_tokens — the prefill it skipped), and
    # a page LRU-evicted to make room for a publish. Both are INFO
    # edges of normal operation (a full pool degrades to miss-and-
    # prefill, never an error), so neither is DLR008 error-coded.
    SERVE_PREFIX_HIT = "serve_prefix_hit"
    SERVE_PREFIX_EVICTED = "serve_prefix_evicted"
    # serving SLO plane: a declared SLO target violated for the
    # confirmation windows (failure-class — carries an error code and
    # the burn-rate evidence; DLR008), its recovery, and the scale
    # proposal the policy loop hands the auto-scaler. VIOLATION ->
    # RECOVERED pairs into the mttr/goodput `serving_scale` scenario.
    SERVE_SLO_VIOLATION = "serve_slo_violation"
    SERVE_SLO_RECOVERED = "serve_slo_recovered"
    SERVE_SCALE_PROPOSED = "serve_scale_proposed"


class SpanName:
    """Span names for the Chrome/Perfetto trace export
    (``telemetry.tracing``)."""

    STEP_DISPATCH = "step_dispatch"
    HOST_SYNC = "host_sync"
    LIVE_RESHARD = "live_reshard"
    STATE_SNAPSHOT = "state_snapshot"
    CKPT_SAVE_STAGE = "ckpt_save_stage"
    CKPT_MIRROR = "ckpt_mirror"
    CKPT_RESTORE = "ckpt_restore"
    RENDEZVOUS = "rendezvous"
    EVALUATE = "evaluate"
    RPC = "rpc"  # prefix; full name is "rpc.<MessageType>"
    # serving: host spans on the worker (decode dispatch, prefill
    # chunk) and router (lease/complete handling) pids
    SERVE_DECODE = "serve_decode_step"
    SERVE_PREFILL = "serve_prefill_chunk"
    SERVE_LEASE = "serve_lease"
    SERVE_COMPLETE = "serve_complete"
