"""Incident/trace-ID propagation across master, agent, and workers.

A trace id is minted at FAILURE DETECTION (the agent seeing a dead or
hung worker, the executor seeing a non-finite step, the master's
straggler detector confirming a verdict) and then rides three channels
so every event record of the incident can be stitched back into one
causally-ordered view:

  in-process    a ``contextvars.ContextVar`` — ``emit_event`` stamps
                the ambient id onto every record it writes
  cross-process over gRPC invocation metadata (``rpc/client.py``
                attaches the header, ``rpc/server.py`` restores it
                around the handler), so a worker's ``report_failure``
                stamps the master's ingress-side events too
  cross-restart over the worker environment (``DLROVER_TPU_TRACE_ID``):
                the agent hands the open incident's id to the processes
                it relaunches, so the recovered round's startup events
                carry the id of the incident they recover from

The merged Perfetto export (``telemetry.correlate``) groups records by
``trace_id`` regardless of which process emitted them.
"""

from __future__ import annotations

import contextvars
import os
import uuid
from contextlib import contextmanager
from typing import Iterator, Optional

TRACE_ID_ENV = "DLROVER_TPU_TRACE_ID"
# gRPC metadata keys must be lowercase
TRACE_ID_METADATA_KEY = "dlrover-trace-id"

_ambient: contextvars.ContextVar[str] = contextvars.ContextVar(
    "dlrover_tpu_trace_id", default=""
)


def new_trace_id() -> str:
    """A fresh incident id (short, log-greppable, globally unique
    enough for one job's timeline)."""
    return "inc-" + uuid.uuid4().hex[:16]


def current_trace_id() -> str:
    """The ambient incident id: the context variable when set, else the
    environment (a worker relaunched as part of an incident inherits
    the id from the agent); "" when no incident is open."""
    tid = _ambient.get()
    if tid:
        return tid
    return os.environ.get(TRACE_ID_ENV, "")


def set_trace_id(trace_id: str) -> "contextvars.Token[str]":
    """Set the ambient id; returns the token for ``reset_trace_id``."""
    return _ambient.set(trace_id)


def reset_trace_id(token: "contextvars.Token[str]") -> None:
    _ambient.reset(token)


def clear_trace_id() -> None:
    """Drop the ambient id unconditionally (incident recovered)."""
    _ambient.set("")


@contextmanager
def trace_scope(trace_id: Optional[str] = None) -> Iterator[str]:
    """Run the body under ``trace_id`` (minting one when None); the
    previous ambient id is restored on exit."""
    tid = trace_id or new_trace_id()
    token = _ambient.set(tid)
    try:
        yield tid
    finally:
        _ambient.reset(token)
