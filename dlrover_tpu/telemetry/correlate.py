"""Cross-process trace correlation: merge an event timeline into one
Perfetto view.

The host-span export (``tracing.export_chrome_trace``) covers ONE
process. An incident, though, threads through three: the agent detects
the failure, the master ingests the report, the relaunched worker
recovers — each appending to the shared JSONL timeline with its own
``pid`` and (when an incident trace id was ambient, see
``trace_context``) a shared ``trace_id``.

``export_merged_trace`` renders that file as Trace Event Format JSON
that https://ui.perfetto.dev opens directly:

  * every record becomes an instant event on its emitting process's
    track (named ``node<id>/pid<pid>``), args carrying the full record;
  * each failure→recovery incident (the MTTR pairing) becomes a
    complete-event span on a synthetic "incidents" track, so downtime
    is visible as a bar, not two dots;
  * records sharing a ``trace_id`` are joined by flow arrows in emit
    order — the causally-ordered path of the incident across processes.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from dlrover_tpu.telemetry.mttr import derive_incidents

# Perfetto wants process-scoped ids; the synthetic incident track uses
# a pid real processes cannot take
INCIDENT_TRACK_PID = 0


def merged_trace_events(events: List[Dict]) -> List[Dict]:
    ordered = sorted(events, key=lambda r: r.get("ts", 0.0))
    out: List[Dict] = []
    seen_pids: Dict[int, str] = {}
    flows: Dict[str, List[Dict]] = {}

    for rec in ordered:
        pid = int(rec.get("pid", 0) or 0)
        node = rec.get("node", "?")
        seen_pids.setdefault(pid, f"node{node}/pid{pid}")
        ev = {
            "name": rec.get("kind", "event"),
            "cat": "events",
            "ph": "i",
            "s": "p",  # process-scoped instant
            "ts": int(rec.get("ts", 0.0) * 1e6),
            "pid": pid,
            "tid": pid,
            "args": {k: v for k, v in rec.items() if k != "kind"},
        }
        out.append(ev)
        tid = rec.get("trace_id")
        if tid:
            flows.setdefault(tid, []).append(ev)

    # incident spans (downtime bars) on the synthetic track
    seen_pids[INCIDENT_TRACK_PID] = "incidents"
    for i, inc in enumerate(derive_incidents(ordered)):
        if inc["started_ts"] is None or inc["recovered_ts"] is None:
            continue
        out.append({
            "name": inc["scenario"],
            "cat": "incident",
            "ph": "X",
            "ts": int(inc["started_ts"] * 1e6),
            "dur": max(1, int(
                (inc["recovered_ts"] - inc["started_ts"]) * 1e6)),
            "pid": INCIDENT_TRACK_PID,
            "tid": i,
            "args": {k: v for k, v in inc.items()},
        })

    # flow arrows: consecutive records of one trace_id, in emit order
    flow_id = 0
    for tid, chain in flows.items():
        if len(chain) < 2:
            continue
        flow_id += 1
        for j, ev in enumerate(chain):
            out.append({
                "name": tid,
                "cat": "trace_id",
                "ph": "s" if j == 0 else ("f" if j == len(chain) - 1
                                          else "t"),
                "bp": "e",
                "id": flow_id,
                "ts": ev["ts"],
                "pid": ev["pid"],
                "tid": ev["tid"],
            })

    # process-name metadata so tracks read as nodes, not raw pids
    for pid, name in seen_pids.items():
        out.append({
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "args": {"name": name},
        })
    return out


def export_merged_trace(events: List[Dict], path: str) -> int:
    """Write the merged view; returns the number of trace events."""
    trace_events = merged_trace_events(events)
    payload = {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
        "otherData": {"exporter": "dlrover_tpu.telemetry.correlate"},
    }
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh)
    return len(trace_events)


def incident_records(events: List[Dict],
                     trace_id: Optional[str] = None) -> Dict[str, List[Dict]]:
    """Records grouped by trace id (one incident each); ``trace_id``
    narrows to a single incident."""
    groups: Dict[str, List[Dict]] = {}
    for rec in sorted(events, key=lambda r: r.get("ts", 0.0)):
        tid = rec.get("trace_id")
        if not tid or (trace_id is not None and tid != trace_id):
            continue
        groups.setdefault(tid, []).append(rec)
    return groups
