"""Cross-process trace correlation: merge an event timeline into one
Perfetto view.

The host-span export (``tracing.export_chrome_trace``) covers ONE
process. An incident, though, threads through three: the agent detects
the failure, the master ingests the report, the relaunched worker
recovers — each appending to the shared JSONL timeline with its own
``pid`` and (when an incident trace id was ambient, see
``trace_context``) a shared ``trace_id``.

``export_merged_trace`` renders that file as Trace Event Format JSON
that https://ui.perfetto.dev opens directly:

  * every record becomes an instant event on its emitting process's
    track (named ``node<id>/pid<pid>``), args carrying the full record;
  * each failure→recovery incident (the MTTR pairing) becomes a
    complete-event span on a synthetic "incidents" track, so downtime
    is visible as a bar, not two dots;
  * records sharing a ``trace_id`` are joined by flow arrows in emit
    order — the causally-ordered path of the incident across processes.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

from dlrover_tpu.telemetry.mttr import derive_incidents
from dlrover_tpu.telemetry.names import EventKind

# Perfetto wants process-scoped ids; the synthetic incident track uses
# a pid real processes cannot take
INCIDENT_TRACK_PID = 0
# synthetic per-request track: one tid ROW per serve request (its
# lifecycle span from submit to completion), so the serving view reads
# as one Perfetto lane per request with the flow arrows of its
# trace_id pointing at the real router/worker pid events
REQUEST_TRACK_PID = -1

_SERVE_REQUEST_KINDS = {
    EventKind.SERVE_REQUEST_SUBMITTED,
    EventKind.SERVE_REQUEST_LEASED,
    EventKind.SERVE_PREFILL_CHUNK,
    EventKind.SERVE_FIRST_TOKEN,
    EventKind.SERVE_REQUEST_DONE,
    EventKind.SERVE_REQUEST_COMPLETED,
    EventKind.SERVE_REQUEST_EVICTED,
    EventKind.SERVE_LEASE_EXPIRED,
}


def merged_trace_events(events: List[Dict]) -> List[Dict]:
    ordered = sorted(events, key=lambda r: r.get("ts", 0.0))
    out: List[Dict] = []
    seen_pids: Dict[int, str] = {}
    flows: Dict[str, List[Dict]] = {}

    for rec in ordered:
        pid = int(rec.get("pid", 0) or 0)
        node = rec.get("node", "?")
        seen_pids.setdefault(pid, f"node{node}/pid{pid}")
        ev = {
            "name": rec.get("kind", "event"),
            "cat": "events",
            "ph": "i",
            "s": "p",  # process-scoped instant
            "ts": int(rec.get("ts", 0.0) * 1e6),
            "pid": pid,
            "tid": pid,
            "args": {k: v for k, v in rec.items() if k != "kind"},
        }
        out.append(ev)
        tid = rec.get("trace_id")
        if tid:
            flows.setdefault(tid, []).append(ev)

    # per-request lanes: each request trace id whose lifecycle events
    # appear in the timeline becomes one complete-event span (first ->
    # last lifecycle event) on its own tid row of the request track
    request_rows: Dict[str, List[Dict]] = {}
    for rec in ordered:
        if rec.get("kind") in _SERVE_REQUEST_KINDS and \
                rec.get("trace_id"):
            request_rows.setdefault(rec["trace_id"], []).append(rec)
    if request_rows:
        seen_pids[REQUEST_TRACK_PID] = "serve requests"
    for row, (tid_key, chain) in enumerate(sorted(
            request_rows.items(),
            key=lambda kv: kv[1][0].get("ts", 0.0))):
        t0 = chain[0].get("ts", 0.0)
        t1 = chain[-1].get("ts", t0)
        pids = sorted({int(r.get("pid", 0) or 0) for r in chain})
        out.append({
            "name": str(chain[0].get("request_id", tid_key)),
            "cat": "serve_request",
            "ph": "X",
            "ts": int(t0 * 1e6),
            "dur": max(1, int((t1 - t0) * 1e6)),
            "pid": REQUEST_TRACK_PID,
            "tid": row,
            "args": {
                "trace_id": tid_key,
                "lifecycle": [r.get("kind") for r in chain],
                "pids": pids,
            },
        })

    # incident spans (downtime bars) on the synthetic track
    seen_pids[INCIDENT_TRACK_PID] = "incidents"
    for i, inc in enumerate(derive_incidents(ordered)):
        if inc["started_ts"] is None or inc["recovered_ts"] is None:
            continue
        out.append({
            "name": inc["scenario"],
            "cat": "incident",
            "ph": "X",
            "ts": int(inc["started_ts"] * 1e6),
            "dur": max(1, int(
                (inc["recovered_ts"] - inc["started_ts"]) * 1e6)),
            "pid": INCIDENT_TRACK_PID,
            "tid": i,
            "args": {k: v for k, v in inc.items()},
        })

    # flow arrows: consecutive records of one trace_id, in emit order
    flow_id = 0
    for tid, chain in flows.items():
        if len(chain) < 2:
            continue
        flow_id += 1
        for j, ev in enumerate(chain):
            out.append({
                "name": tid,
                "cat": "trace_id",
                "ph": "s" if j == 0 else ("f" if j == len(chain) - 1
                                          else "t"),
                "bp": "e",
                "id": flow_id,
                "ts": ev["ts"],
                "pid": ev["pid"],
                "tid": ev["tid"],
            })

    # process-name metadata so tracks read as nodes, not raw pids
    for pid, name in seen_pids.items():
        out.append({
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "args": {"name": name},
        })
    return out


def export_merged_trace(events: List[Dict], path: str) -> int:
    """Write the merged view; returns the number of trace events."""
    trace_events = merged_trace_events(events)
    payload = {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
        "otherData": {"exporter": "dlrover_tpu.telemetry.correlate"},
    }
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh)
    return len(trace_events)


def incident_records(events: List[Dict],
                     trace_id: Optional[str] = None) -> Dict[str, List[Dict]]:
    """Records grouped by trace id (one incident each); ``trace_id``
    narrows to a single incident."""
    groups: Dict[str, List[Dict]] = {}
    for rec in sorted(events, key=lambda r: r.get("ts", 0.0)):
        tid = rec.get("trace_id")
        if not tid or (trace_id is not None and tid != trace_id):
            continue
        groups.setdefault(tid, []).append(rec)
    return groups
