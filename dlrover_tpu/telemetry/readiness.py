"""Priced recovery ladder: rung constants, the calibrated MTTR pricer,
and the pure event-timeline derivations behind ``tpurun readiness
--events`` and ``tpurun mttr --predict``.

The recovery ladder (docs/elasticity.md) has four rungs a failing node
can come back through — live_reshard, peer_rebuild, storage_restore,
init — and until now the framework always walked them top-down by
availability. ElasWave's rung-pricing contract (PAPERS.md, 2510.00606)
makes the rung a PRICED decision instead: each rung carries a predicted
MTTR from calibrated observations, and every realized recovery feeds an
EMA correction back into the price, so the prediction converges on this
cluster's actual behavior instead of a datasheet guess.

The peer_rebuild price is the BENCH_r14 decomposition:

    drain + fetch_bytes / link_bw + device_put(bytes)

where ``link_bw`` is calibrated from the replicator's OWN push cycles —
a push frames and streams exactly the bytes a rebuild fetches back,
over the same RPC path between the same hosts, so the replication plane
continuously measures the recovery plane's transfer term without ever
injecting a failure. The observation-only rungs (live_reshard,
storage_restore, init) are priced from the EMA of realized incidents of
their scenario, falling back to a stated prior before the first one.

Everything in this module is master-state-free: the ``RungPricer`` is a
plain calibration object the master's ReadinessAuditor owns, and the
``predict_report`` / ``readiness_view`` derivations read only the event
timeline, so the CLI works forensically on a dead job's JSONL.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from dlrover_tpu.telemetry.mttr import derive_incidents
from dlrover_tpu.telemetry.names import EventKind

# the recovery ladder, cheapest rung first; the gauge encodes a rung as
# its index here (0=live_reshard .. 3=init)
RUNG_LIVE_RESHARD = "live_reshard"
RUNG_PEER_REBUILD = "peer_rebuild"
RUNG_STORAGE_RESTORE = "storage_restore"
RUNG_INIT = "init"
RUNG_LADDER = (
    RUNG_LIVE_RESHARD,
    RUNG_PEER_REBUILD,
    RUNG_STORAGE_RESTORE,
    RUNG_INIT,
)
RUNG_INDEX = {r: i for i, r in enumerate(RUNG_LADDER)}

# which mttr scenario realizes which rung, for the EMA correction: a
# closed live-reshard incident prices the live_reshard rung, a closed
# peer rebuild the peer_rebuild rung, and a worker-failure incident
# (process relaunch + storage/mirror restore) the storage_restore rung.
# Nothing realizes init — a from-scratch start is not an incident — so
# its price stays the prior.
SCENARIO_RUNG = {
    "live_reshard": RUNG_LIVE_RESHARD,
    "peer_rebuild": RUNG_PEER_REBUILD,
    "worker_failure": RUNG_STORAGE_RESTORE,
}

# priors (seconds) quoted before the first calibrating observation:
# deliberately pessimistic so an uncalibrated ladder never talks the
# planner OUT of a cheaper rung it has no evidence for
_RUNG_PRIORS = {
    RUNG_LIVE_RESHARD: 1.0,
    RUNG_PEER_REBUILD: 5.0,
    RUNG_STORAGE_RESTORE: 30.0,
    RUNG_INIT: 120.0,
}

# device_put prior before the first observed rebuild measures it
# (host-DRAM -> device transfer; conservative for PCIe-class paths)
_PUT_BW_PRIOR = 2.0e9  # bytes/s


def _ema(prev: Optional[float], obs: float, alpha: float) -> float:
    return obs if prev is None else prev + alpha * (obs - prev)


@dataclass
class RungPricer:
    """Calibration state + the pricing function for the four rungs.

    Thread-compat note: callers (the ReadinessAuditor) serialize access
    under their own lock; the pricer itself holds none.
    """

    alpha: float = 0.3
    # transfer-path calibration, EMA'd over replicator push cycles:
    # effective bytes/s of slice+frame+stream for ONE peer's worth of
    # region bytes (fixed per-cycle overhead included, which is what
    # makes small-state predictions honest)
    link_bw: Optional[float] = None
    # device_put bytes/s, EMA'd over realized rebuild put legs
    put_bw: Optional[float] = None
    # drain seconds a live rung pays before state moves (EMA over
    # realized live reshards' total is folded into ema_realized; this
    # term is the drain a peer_rebuild of a LIVE node would add — a
    # DEAD node has nothing left to drain, so blast-radius pricing
    # passes drain_s=0)
    drain_s: float = 0.0
    # absolute realized-MTTR EMA per rung (observation-priced rungs)
    ema_realized: Dict[str, float] = field(default_factory=dict)
    # multiplicative correction per rung: EMA of realized/predicted
    # whenever a recovery event carries both stamps
    corr: Dict[str, float] = field(default_factory=dict)
    # bookkeeping: how many observations each calibration term has seen
    observations: Dict[str, int] = field(default_factory=dict)

    def _count(self, term: str) -> None:
        self.observations[term] = self.observations.get(term, 0) + 1

    # -- calibration feeds ---------------------------------------------------

    def observe_push(self, push_bytes: float, push_seconds: float) -> None:
        """One replicator push cycle: the continuous, failure-free
        measurement of the rebuild transfer path."""
        if push_bytes <= 0 or push_seconds <= 0:
            return
        self.link_bw = _ema(
            self.link_bw, push_bytes / push_seconds, self.alpha)
        self._count("push")

    def observe_put(self, put_bytes: float, put_seconds: float) -> None:
        if put_bytes <= 0 or put_seconds <= 0:
            return
        self.put_bw = _ema(
            self.put_bw, put_bytes / put_seconds, self.alpha)
        self._count("put")

    def observe_realized(self, rung: str, realized_s: float,
                         predicted_s: Optional[float] = None) -> None:
        """A closed incident's realized MTTR for ``rung``. When the
        recovery event also carried the prediction made BEFORE the
        recovery ran, the ratio feeds the rung's multiplicative
        correction; the absolute EMA updates either way."""
        if rung not in RUNG_INDEX or realized_s < 0:
            return
        self.ema_realized[rung] = _ema(
            self.ema_realized.get(rung), realized_s, self.alpha)
        if predicted_s is not None and predicted_s > 0:
            ratio = min(10.0, max(0.1, realized_s / predicted_s))
            self.corr[rung] = _ema(
                self.corr.get(rung), ratio, self.alpha)
        self._count(rung)

    def update_from_incidents(self, incidents: List[Dict]) -> None:
        """Fold a batch of closed mttr incidents in (the "every time
        ``tpurun mttr`` closes an incident" contract — the auditor calls
        this over the tail of the shared events file)."""
        for inc in incidents:
            rung = SCENARIO_RUNG.get(inc.get("scenario", ""))
            realized = inc.get("recovery_seconds")
            if rung is None or realized is None:
                continue
            self.observe_realized(rung, float(realized))

    # -- pricing -------------------------------------------------------------

    def predict(self, rung: str, region_bytes: float = 0.0,
                drain_s: Optional[float] = None) -> float:
        """Predicted MTTR (seconds) of ``rung`` for a node whose owner
        regions total ``region_bytes``. ``drain_s`` defaults to the
        calibrated drain for live rungs; blast-radius pricing (the node
        is DEAD) passes 0 — there is nothing left to drain."""
        if rung == RUNG_PEER_REBUILD:
            drain = self.drain_s if drain_s is None else drain_s
            link = self.link_bw
            fetch = (region_bytes / link) if (link and link > 0) else None
            put = region_bytes / (self.put_bw or _PUT_BW_PRIOR)
            if fetch is None:
                base = self.ema_realized.get(
                    rung, _RUNG_PRIORS[rung])
            else:
                base = drain + fetch + put
            return max(0.0, base * self.corr.get(rung, 1.0))
        if rung not in RUNG_INDEX:
            raise ValueError(f"unknown recovery rung: {rung!r}")
        base = self.ema_realized.get(rung, _RUNG_PRIORS[rung])
        return max(0.0, base * self.corr.get(rung, 1.0))

    def table(self, region_bytes: float = 0.0,
              drain_s: Optional[float] = None) -> Dict[str, float]:
        """The per-rung predicted-MTTR table, cheapest-ladder order."""
        return {
            rung: round(self.predict(rung, region_bytes, drain_s), 6)
            for rung in RUNG_LADDER
        }

    def to_dict(self) -> Dict:
        """Calibration snapshot for the readiness report."""
        return {
            "link_bw_bytes_per_s": (
                round(self.link_bw, 1) if self.link_bw else None),
            "put_bw_bytes_per_s": (
                round(self.put_bw, 1) if self.put_bw else None),
            "drain_s": round(self.drain_s, 6),
            "ema_realized_s": {
                k: round(v, 6) for k, v in self.ema_realized.items()},
            "corrections": {
                k: round(v, 4) for k, v in self.corr.items()},
            "observations": dict(self.observations),
        }


def cheapest_viable_rung(table: Dict[str, float],
                         viable: Dict[str, bool]) -> Optional[str]:
    """The priced choice: among the rungs marked viable, the one with
    the lowest predicted MTTR — ties break toward the ladder's
    traditional (cheapest-first) order because ``table`` iterates in
    RUNG_LADDER order. None when nothing is viable."""
    best: Optional[str] = None
    for rung in RUNG_LADDER:
        if not viable.get(rung):
            continue
        if best is None or table.get(rung, float("inf")) < table.get(
                best, float("inf")):
            best = rung
    return best


# -- forensic derivations (pure functions over the event timeline) ------------


def predict_report(events: List[Dict]) -> Dict:
    """``tpurun mttr --predict``: per-incident predicted-vs-realized
    columns, derived purely from the timeline. An incident gains the
    prediction columns only when its recovery event was stamped with
    ``predicted_mttr_s`` (the priced-ladder paths stamp both predicted
    and realized); unstamped incidents keep ``predicted_s: None`` —
    absent means "this recovery was not priced", never 0."""
    ordered = sorted(events, key=lambda r: r.get("ts", 0.0))
    stamped: Dict = {}
    for rec in ordered:
        if rec.get("predicted_mttr_s") is None:
            continue
        key = (rec.get("kind", ""), round(rec.get("ts", 0.0), 6))
        stamped[key] = rec
    rows: List[Dict] = []
    priced = 0
    within_2x = 0
    for inc in derive_incidents(ordered):
        row = {
            "scenario": inc["scenario"],
            "node": inc.get("node", ""),
            "started_ts": inc["started_ts"],
            "realized_s": inc["recovery_seconds"],
            "predicted_s": None,
            "rung": None,
            "ratio": None,
        }
        rec = stamped.get((
            inc.get("recovery_kind") or "",
            round(inc["recovered_ts"] or -1.0, 6),
        ))
        if rec is not None:
            try:
                predicted = float(rec["predicted_mttr_s"])
            except (TypeError, ValueError):
                predicted = None
            if predicted is not None:
                realized = rec.get(
                    "realized_mttr_s", inc["recovery_seconds"])
                row["predicted_s"] = round(predicted, 6)
                row["rung"] = rec.get("rung")
                if realized is not None:
                    realized = float(realized)
                    row["realized_s"] = round(realized, 6)
                    if realized > 0:
                        row["ratio"] = round(predicted / realized, 3)
                priced += 1
                if (realized is not None and
                        predicted <= 2.0 * realized + 0.05 and
                        realized <= 2.0 * predicted + 0.05):
                    within_2x += 1
        rows.append(row)
    return {
        "metric": "recovery_mttr_predicted_vs_realized",
        "incidents": rows,
        "priced": priced,
        "within_2x": within_2x,
        "source": "event_timeline",
    }


def readiness_view(events: List[Dict]) -> Dict:
    """The forensic readiness report: replay the durability verdict
    edges (DIAG_DURABILITY flags, DIAG_RECOVERED ``was=durability``
    clears) and the posture edges to the state the auditor held at the
    timeline's end — what ``tpurun readiness --events`` shows, and what
    the live/forensic agreement gate pins against the RPC view."""
    at_risk: Dict[str, Dict] = {}
    posture = "ready"
    last_sweep: Optional[Dict] = None
    sweeps = 0
    for rec in sorted(events, key=lambda r: r.get("ts", 0.0)):
        kind = rec.get("kind", "")
        if kind == EventKind.DIAG_DURABILITY:
            node = str(rec.get("diag_node", ""))
            at_risk[node] = {
                "node_id": rec.get("diag_node"),
                "error_code": rec.get("error_code", ""),
                "since_ts": rec.get("ts"),
                "trace_id": rec.get("trace_id", ""),
                "evidence": {
                    k: v for k, v in rec.items()
                    if k in ("missing_regions", "held", "required",
                             "staleness_steps", "allowed_steps",
                             "degraded", "requested", "admitted",
                             "owner_step", "holders")
                },
            }
        elif (kind == EventKind.DIAG_RECOVERED
              and rec.get("was") == "durability"):
            at_risk.pop(str(rec.get("diag_node", "")), None)
        elif kind == EventKind.READINESS_DEGRADED:
            posture = "degraded"
        elif kind == EventKind.READINESS_RESTORED:
            posture = "ready"
        elif kind == EventKind.READINESS_SWEEP:
            sweeps += 1
            last_sweep = {
                k: rec.get(k)
                for k in ("ts", "at_risk", "nodes", "owners",
                          "posture", "sweep_seconds")
                if rec.get(k) is not None
            }
    if at_risk and posture == "ready":
        # a flag without its posture edge (rotated-away file): the
        # verdict table wins — degraded is the honest summary
        posture = "degraded"
    return {
        "posture": posture,
        "at_risk": at_risk,
        "at_risk_nodes": sorted(at_risk),
        "last_sweep": last_sweep,
        "sweep_events": sweeps,
        "source": "event_timeline",
    }
