"""Prometheus text exposition over HTTP, served from the agent/master.

A stdlib ``ThreadingHTTPServer`` on a daemon thread — no new
dependencies, good enough for a per-process scrape endpoint:

  GET /metrics   Prometheus text format (the process registry)
  GET /events    last N timeline records as JSON (?n=100)
  GET /healthz   200 ok

Wire-up: the local master starts one when the Context knob
``telemetry_metrics_port`` is > 0 (env ``DLROVER_TPU_METRICS_PORT``),
and ``tpurun`` passes ``--metrics_port`` through to the agent process.
``tpurun metrics [--addr host:port]`` scrapes and prints.
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple
from urllib.parse import urlparse, parse_qs

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.telemetry import events as events_mod
from dlrover_tpu.telemetry.metrics import process_registry

logger = get_logger("telemetry.exporter")


class _Handler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler contract
        parsed = urlparse(self.path)
        if parsed.path == "/metrics":
            body = process_registry().render_prometheus().encode()
            ctype = "text/plain; version=0.0.4; charset=utf-8"
        elif parsed.path == "/events":
            try:
                n = int(parse_qs(parsed.query).get("n", ["100"])[0])
            except ValueError:
                n = 100
            body = json.dumps(events_mod.recent_events(n)).encode()
            ctype = "application/json"
        elif parsed.path == "/healthz":
            body, ctype = b"ok\n", "text/plain"
        else:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # quiet: scrapes are periodic
        logger.debug("exporter: " + fmt, *args)


class MetricsExporter:
    """Owns the server + its daemon serving thread."""

    def __init__(self, port: int = 0, host: str = "0.0.0.0"):
        self._server = ThreadingHTTPServer((host, port), _Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name="metrics-exporter", daemon=True,
        )

    def start(self) -> "MetricsExporter":
        self._thread.start()
        logger.info("metrics exporter serving on :%d", self.port)
        return self

    def stop(self) -> None:
        try:
            self._server.shutdown()
            self._server.server_close()
        except OSError:
            logger.warning("exporter shutdown raced", exc_info=True)


def maybe_start_exporter(port: Optional[int] = None) -> Optional[
        MetricsExporter]:
    """Start an exporter if configured; None when off. ``port`` None
    defers to the ``telemetry_metrics_port`` Context knob (0 = off;
    tests may pass an explicit 0 for an ephemeral port)."""
    from dlrover_tpu.common.config import get_context

    ctx = get_context()
    if not getattr(ctx, "telemetry_enabled", True):
        return None
    if port is None:
        # the short env spelling is the documented operator surface
        # (DLROVER_TPU_METRICS_PORT, like DLROVER_TPU_EVENTS_FILE) and
        # wins when present — including an explicit "0" = off; absent,
        # the Context knob (env-overridable as
        # DLROVER_TPU_TELEMETRY_METRICS_PORT) decides
        env = os.environ.get("DLROVER_TPU_METRICS_PORT")
        try:
            port = (int(env) if env not in (None, "")
                    else int(getattr(ctx, "telemetry_metrics_port", 0)))
        except ValueError:
            logger.error("malformed DLROVER_TPU_METRICS_PORT=%r", env)
            return None
        if port <= 0:
            return None
    try:
        return MetricsExporter(port=port).start()
    except OSError as e:
        logger.error("metrics exporter failed to bind :%s (%s)", port, e)
        return None


def fetch_metrics(addr: str, timeout: float = 5.0) -> Tuple[int, str]:
    """Scrape ``host:port`` (or a full URL); returns (status, body)."""
    import urllib.request

    url = addr if "://" in addr else f"http://{addr}/metrics"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read().decode("utf-8", "replace")
