"""Structured event timeline: append-only JSONL of lifecycle edges.

Every record carries:

  ``kind``        an ``EventKind`` constant (names.py)
  ``ts``          wall-clock epoch seconds (cross-process ordering)
  ``mono``        ``time.monotonic()`` of the emitting process (exact
                  in-process deltas; NOT comparable across processes)
  ``pid``         emitting process id (tells mttr which clock to trust)
  ``node``        node identity from the NodeEnv contract
  ``error_code``  stable machine-readable code ("" when not an error)

plus free-form per-kind fields. The sink is one ``os.write`` of a
single line onto an ``O_APPEND`` fd — POSIX guarantees small appends
are atomic, so the agent and every worker process it spawns can share
one timeline file (the env var rides the worker environment) without
locks or interleaving. MTTR and recovery-count reports are *derived*
from this file (``python -m dlrover_tpu.telemetry mttr``) instead of
being hand-assembled.

The file path comes from ``DLROVER_TPU_EVENTS_FILE`` (or the Context
knob ``telemetry_events_file``), resolved per emit — cheap, and it
keeps tests with different tmp paths honest. No file configured ⇒
records land only in the bounded in-memory ring.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Deque, Dict, List, Optional

from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.common.log import get_logger

logger = get_logger("telemetry.events")

EVENTS_FILE_ENV = "DLROVER_TPU_EVENTS_FILE"
_RING_CAP = 4096

_ring: Deque[Dict] = collections.deque(maxlen=_RING_CAP)
_ring_lock = threading.Lock()
_seq = 0
# one fd per resolved path, kept open for the process lifetime
_fds: Dict[str, int] = {}
_fd_lock = threading.Lock()


def _events_path() -> str:
    path = os.environ.get(EVENTS_FILE_ENV, "")
    if path:
        return path
    from dlrover_tpu.common.config import get_context

    return str(getattr(get_context(), "telemetry_events_file", "") or "")


def _node_identity() -> str:
    return (
        os.environ.get(NodeEnv.NODE_RANK)
        or os.environ.get(NodeEnv.NODE_ID)
        or "0"
    )


def emit_event(kind: str, error_code: str = "", **fields) -> Dict:
    """Append one record to the timeline; returns the record (its
    ``seq`` tags log lines that reference it). Never raises — a full
    disk or revoked fd must not take training down with it."""
    global _seq
    from dlrover_tpu.common.config import get_context

    if not getattr(get_context(), "telemetry_enabled", True):
        return {}
    with _ring_lock:
        _seq += 1
        seq = _seq
    record: Dict = {
        "kind": kind,
        "ts": time.time(),
        "mono": time.monotonic(),
        "pid": os.getpid(),
        "node": _node_identity(),
        "seq": seq,
    }
    if error_code:
        record["error_code"] = error_code
    for k, v in fields.items():
        if v is not None:
            record[k] = v
    with _ring_lock:
        _ring.append(record)
    path = _events_path()
    if path:
        try:
            fd = _fds.get(path)
            if fd is None:
                with _fd_lock:
                    fd = _fds.get(path)
                    if fd is None:
                        d = os.path.dirname(os.path.abspath(path))
                        os.makedirs(d, exist_ok=True)
                        fd = os.open(
                            path,
                            os.O_WRONLY | os.O_CREAT | os.O_APPEND,
                            0o644,
                        )
                        _fds[path] = fd
            line = json.dumps(record, separators=(",", ":")) + "\n"
            os.write(fd, line.encode("utf-8"))
        except OSError:
            logger.warning("event sink write failed for %s", path,
                           exc_info=True)
    return record


def recent_events(n: int = 0) -> List[Dict]:
    """The in-memory ring (newest last); ``n`` limits to the tail."""
    with _ring_lock:
        out = list(_ring)
    return out[-n:] if n else out


def clear_ring() -> None:
    with _ring_lock:
        _ring.clear()


def read_events(path: str) -> List[Dict]:
    """Parse a timeline file; malformed lines (torn writes from a
    killed process) are skipped, not fatal."""
    out: List[Dict] = []
    try:
        with open(path, encoding="utf-8", errors="replace") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and "kind" in rec:
                    out.append(rec)
    except OSError:
        return []
    out.sort(key=lambda r: r.get("ts", 0.0))
    return out


def default_events_path() -> Optional[str]:
    """Where emits currently land, or None."""
    return _events_path() or None
