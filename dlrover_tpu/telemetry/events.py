"""Structured event timeline: append-only JSONL of lifecycle edges.

Every record carries:

  ``kind``        an ``EventKind`` constant (names.py)
  ``ts``          wall-clock epoch seconds (cross-process ordering)
  ``mono``        ``time.monotonic()`` of the emitting process (exact
                  in-process deltas; NOT comparable across processes)
  ``pid``         emitting process id (tells mttr which clock to trust)
  ``node``        node identity from the NodeEnv contract
  ``error_code``  stable machine-readable code ("" when not an error)

plus free-form per-kind fields. The sink is one ``os.write`` of a
single line onto an ``O_APPEND`` fd — POSIX guarantees small appends
are atomic, so the agent and every worker process it spawns can share
one timeline file (the env var rides the worker environment) without
locks or interleaving. MTTR and recovery-count reports are *derived*
from this file (``python -m dlrover_tpu.telemetry mttr``) instead of
being hand-assembled.

The file path comes from ``DLROVER_TPU_EVENTS_FILE`` (or the Context
knob ``telemetry_events_file``), resolved per emit — cheap, and it
keeps tests with different tmp paths honest. No file configured ⇒
records land only in the bounded in-memory ring.

Rotation: the timeline would otherwise grow unboundedly on a
long-running job. When the file passes ``DLROVER_TPU_EVENTS_MAX_MB``
(Context knob ``telemetry_events_max_mb``, default 64) it is renamed to
``<path>.1`` (replacing any previous ``.1``) and a fresh file is
opened. Every emitter re-verifies its cached fd against the path's
inode before writing, so the agent and all its workers — each holding
its own ``O_APPEND`` fd onto the shared path — migrate to the fresh
file on their next emit no matter which process performed the rename;
a write racing the rename lands in ``.1`` (same inode), never lost.
``read_events`` reads the ``.1``/current pair, so MTTR/goodput
derivations see the full retained window.

Incident correlation: when an incident trace id is ambient
(``trace_context`` — set in-process, restored from gRPC metadata, or
inherited from the worker environment), every record is stamped with
``trace_id`` so cross-process timelines merge per incident.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Deque, Dict, List, Optional

try:
    import fcntl
except ImportError:  # non-posix: rotation loses cross-process exclusion
    fcntl = None  # type: ignore[assignment]

from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.common.log import get_logger

logger = get_logger("telemetry.events")

EVENTS_FILE_ENV = "DLROVER_TPU_EVENTS_FILE"
EVENTS_MAX_MB_ENV = "DLROVER_TPU_EVENTS_MAX_MB"
ROTATED_SUFFIX = ".1"
_RING_CAP = 4096

_ring: Deque[Dict] = collections.deque(maxlen=_RING_CAP)
_ring_lock = threading.Lock()
_seq = 0
# one fd per resolved path, kept open for the process lifetime.
# Reentrant: emit_event holds it across resolve→rotate→write so a
# racing rotation cannot close the fd under a writer (a closed — or
# worse, OS-reused — descriptor number would drop or misdirect the
# record); the inner helpers re-acquire it.
_fds: Dict[str, int] = {}
_fd_lock = threading.RLock()


def _events_path() -> str:
    path = os.environ.get(EVENTS_FILE_ENV, "")
    if path:
        return path
    from dlrover_tpu.common.config import get_context

    return str(getattr(get_context(), "telemetry_events_file", "") or "")


def _node_identity() -> str:
    return (
        os.environ.get(NodeEnv.NODE_RANK)
        or os.environ.get(NodeEnv.NODE_ID)
        or "0"
    )


def _max_bytes() -> int:
    """The rotation cap in bytes (0 disables rotation)."""
    env = os.environ.get(EVENTS_MAX_MB_ENV)
    if env not in (None, ""):
        try:
            return max(0, int(float(env) * 1024 * 1024))
        except ValueError:
            logger.warning("malformed %s=%r", EVENTS_MAX_MB_ENV, env)
    from dlrover_tpu.common.config import get_context

    mb = getattr(get_context(), "telemetry_events_max_mb", 64)
    try:
        return max(0, int(float(mb) * 1024 * 1024))
    except (TypeError, ValueError):
        return 64 * 1024 * 1024


def _open_sink(path: str) -> int:
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    return os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)


def _sink_fd(path: str) -> int:
    """The per-path append fd, re-validated against the path's inode:
    after any process rotates (rename + fresh file) the cached fd points
    at the rotated inode and must be reopened. Events are lifecycle-rate
    (not per-step), so the two stat syscalls per emit are cheap."""
    with _fd_lock:
        fd = _fds.get(path)
        if fd is not None:
            try:
                if os.stat(path).st_ino == os.fstat(fd).st_ino:
                    return fd
            except OSError:
                pass  # path unlinked/renamed: fall through and reopen
            try:
                os.close(fd)
            except OSError:
                pass
        fd = _open_sink(path)
        _fds[path] = fd
        return fd


def _maybe_rotate(path: str, fd: int) -> int:
    """Size-capped rotation keeping the shared-append semantics: rename
    the full file to ``<path>.1`` and open a fresh one. Returns the fd
    to write through (the fresh file after a rotation)."""
    cap = _max_bytes()
    if cap <= 0:
        return fd
    try:
        if os.fstat(fd).st_size < cap:
            return fd
        with _fd_lock:
            # EVERYTHING re-validates under the lock against the
            # registry's CURRENT fd, not the caller's: a racing thread
            # may have rotated already and the OS may have reused our
            # old fd number for the fresh file — re-checking size+inode
            # on the caller's fd could rotate twice (clobbering the
            # just-rotated full file with a near-empty one) or close an
            # unrelated descriptor
            fd = _fds.get(path, fd)
            # the agent and its workers each run this check: an
            # exclusive flock on the FULL file's inode serializes the
            # rename across processes, and the post-lock re-validation
            # turns the loser's rotation into a no-op (path now names a
            # different, fresh inode) instead of a second rename that
            # would clobber the just-rotated history
            locked = False
            if fcntl is not None:
                try:
                    fcntl.flock(fd, fcntl.LOCK_EX)
                    locked = True
                except OSError:
                    pass
            try:
                try:
                    st = os.fstat(fd)
                    same = (st.st_size >= cap
                            and os.stat(path).st_ino == st.st_ino)
                except OSError:
                    same = False
                if not same:
                    # already rotated (or externally renamed): write
                    # through the registry's fd — an append onto the
                    # rotated inode still lands in the retained pair,
                    # and the next emit's _sink_fd re-syncs to the
                    # fresh file
                    return fd
                os.replace(path, path + ROTATED_SUFFIX)
            finally:
                if locked:
                    try:
                        fcntl.flock(fd, fcntl.LOCK_UN)
                    except OSError:
                        pass
            try:
                os.close(fd)
            except OSError:
                pass
            fd = _open_sink(path)
            _fds[path] = fd
            return fd
    except OSError:
        logger.warning("event sink rotation failed for %s", path,
                       exc_info=True)
    return fd


def emit_event(kind: str, error_code: str = "", **fields) -> Dict:
    """Append one record to the timeline; returns the record (its
    ``seq`` tags log lines that reference it). Never raises — a full
    disk or revoked fd must not take training down with it."""
    global _seq
    from dlrover_tpu.common.config import get_context

    if not getattr(get_context(), "telemetry_enabled", True):
        return {}
    with _ring_lock:
        _seq += 1
        seq = _seq
    record: Dict = {
        "kind": kind,
        "ts": time.time(),
        "mono": time.monotonic(),
        "pid": os.getpid(),
        "node": _node_identity(),
        "seq": seq,
    }
    if error_code:
        record["error_code"] = error_code
    from dlrover_tpu.telemetry.trace_context import current_trace_id

    tid = current_trace_id()
    if tid:
        record["trace_id"] = tid
    for k, v in fields.items():
        if v is not None:
            record[k] = v
    with _ring_lock:
        _ring.append(record)
    path = _events_path()
    if path:
        try:
            line = json.dumps(record, separators=(",", ":")) + "\n"
            # the lock spans resolve→rotate→write: a concurrent
            # rotation closes registry fds, and writing outside the
            # lock could hit a closed (or OS-reused) descriptor.
            # Events are lifecycle-rate, so serializing emitters is
            # cheap; cross-PROCESS interleaving still needs no lock
            # (single O_APPEND write per record).
            with _fd_lock:
                fd = _maybe_rotate(path, _sink_fd(path))
                os.write(fd, line.encode("utf-8"))
        except OSError:
            logger.warning("event sink write failed for %s", path,
                           exc_info=True)
    return record


def recent_events(n: int = 0) -> List[Dict]:
    """The in-memory ring (newest last); ``n`` limits to the tail."""
    with _ring_lock:
        out = list(_ring)
    return out[-n:] if n else out


def clear_ring() -> None:
    with _ring_lock:
        _ring.clear()


def _read_one(path: str) -> List[Dict]:
    out: List[Dict] = []
    try:
        with open(path, encoding="utf-8", errors="replace") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if isinstance(rec, dict) and "kind" in rec:
                    out.append(rec)
    except OSError:
        return []
    return out


def read_events(path: str) -> List[Dict]:
    """Parse a timeline (the rotated ``<path>.1`` predecessor included,
    so derivations span the full retained window); malformed lines
    (torn writes from a killed process) are skipped, not fatal."""
    out = _read_one(path + ROTATED_SUFFIX) + _read_one(path)
    out.sort(key=lambda r: r.get("ts", 0.0))
    return out


def default_events_path() -> Optional[str]:
    """Where emits currently land, or None."""
    return _events_path() or None
