"""dlrover_tpu: a TPU-native elastic distributed-training framework.

A ground-up JAX/XLA rebuild of the capabilities of DLRover (reference:
Major-333/dlrover): per-job master control plane (rendezvous, dynamic data
sharding, auto-scaling, fault diagnosis), per-host elastic agents that
bootstrap ``jax.distributed``, and a GSPMD/``pjit`` parallelism library in
place of DDP/FSDP/TP wrapper stacks.

Package layout:
  common/    shared types: node model, status flow, config, wire messages
  rpc/       codegen-free gRPC transport (JSON-framed dataclass messages)
  master/    per-job master: job manager, rendezvous, sharding, monitors
  agent/     per-host elastic agent: master client, rendezvous handler
  trainer/   user-facing training API (ElasticTrainer, tpurun CLI)
  parallel/  mesh planning, sharding rules, strategy, accelerate API
  ops/       Pallas kernels: flash attention, ring attention, MoE
  models/    model family: llama, gpt2, moe, deepfm, mnist
  checkpoint/ async Orbax elastic checkpointing
  diagnosis/ hang detection, profiling, failure classification
  native/    C++ host-side pieces (shm batch transport)
"""

__version__ = "0.1.0"

import os as _os

if (
    # PRIMARY platform is cpu — not merely present in a fallback spec
    # like "tpu,cpu", where the accelerator path must keep default
    # codegen and only an actual CPU client would reload CPU AOT
    _os.environ.get("JAX_PLATFORMS", "").lower().split(",")[0].strip()
    == "cpu"
    # empty DLROVER_COMPILE_CACHE_DIR = caching explicitly disabled:
    # no cache, no reason to constrain codegen
    and _os.environ.get("DLROVER_COMPILE_CACHE_DIR", None) != ""
):
    # CPU-pinned process: cap the XLA:CPU ISA BEFORE any jax client can
    # initialize, so persistent-cache entries reload silently and
    # portably (see utils/compile_cache.cap_cpu_isa_for_cache). Package
    # import is the earliest point the library controls — call sites
    # like accelerate() run after user code may already have built a
    # mesh (initializing the client), where the env change is a no-op.
    from dlrover_tpu.utils.compile_cache import (  # noqa: E402
        cap_cpu_isa_for_cache as _cap_cpu_isa,
    )

    _cap_cpu_isa()
    del _cap_cpu_isa

del _os
