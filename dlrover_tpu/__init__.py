"""dlrover_tpu: a TPU-native elastic distributed-training framework.

A ground-up JAX/XLA rebuild of the capabilities of DLRover (reference:
Major-333/dlrover): per-job master control plane (rendezvous, dynamic data
sharding, auto-scaling, fault diagnosis), per-host elastic agents that
bootstrap ``jax.distributed``, and a GSPMD/``pjit`` parallelism library in
place of DDP/FSDP/TP wrapper stacks.

Package layout:
  common/    shared types: node model, status flow, config, wire messages
  rpc/       codegen-free gRPC transport (JSON-framed dataclass messages)
  master/    per-job master: job manager, rendezvous, sharding, monitors
  agent/     per-host elastic agent: master client, rendezvous handler
  trainer/   user-facing training API (ElasticTrainer, tpurun CLI)
  parallel/  mesh planning, sharding rules, strategy, accelerate API
  ops/       Pallas kernels: flash attention, ring attention, MoE
  models/    model family: llama, gpt2, moe, deepfm, mnist
  checkpoint/ async Orbax elastic checkpointing
  diagnosis/ hang detection, profiling, failure classification
  native/    C++ host-side pieces (shm batch transport)
"""

__version__ = "0.1.0"
