"""Codegen-free gRPC server for the master.

Role parity: the gRPC plumbing of ``dlrover/python/master/servicer.py``
(``create_master_service``). Instead of protoc-generated stubs we register a
generic handler for a two-method service:

  /dlrover_tpu.Master/get     — request message -> response message
  /dlrover_tpu.Master/report  — request message -> Response(success)

Both carry JSON-framed dataclass messages (``common.serialize``).
"""

from __future__ import annotations

from concurrent import futures
from typing import Optional, Tuple

import grpc

from dlrover_tpu.common import serialize
from dlrover_tpu.common.log import get_logger

logger = get_logger("rpc.server")

SERVICE_NAME = "dlrover_tpu.Master"


class _GenericHandler(grpc.GenericRpcHandler):
    def __init__(self, servicer):
        self._servicer = servicer
        self._methods = {
            f"/{SERVICE_NAME}/get": servicer.get,
            f"/{SERVICE_NAME}/report": servicer.report,
        }

    def service(self, handler_call_details):
        method = self._methods.get(handler_call_details.method)
        if method is None:
            return None

        def behavior(request, context):
            # restore the caller's incident trace id (if the client
            # attached one) around the handler, so every event the
            # master emits while serving this request carries it
            from dlrover_tpu.telemetry.trace_context import (
                TRACE_ID_METADATA_KEY,
                reset_trace_id,
                set_trace_id,
            )

            tid = ""
            try:
                for key, value in context.invocation_metadata() or ():
                    if key == TRACE_ID_METADATA_KEY:
                        tid = value
                        break
            except (AttributeError, TypeError):
                tid = ""  # non-grpc test doubles without metadata
            if not tid:
                return method(request, context)
            token = set_trace_id(tid)
            try:
                return method(request, context)
            finally:
                reset_trace_id(token)

        return grpc.unary_unary_rpc_method_handler(
            behavior,
            request_deserializer=serialize.loads,
            response_serializer=serialize.dumps,
        )


def build_server(
    servicer,
    port: int = 0,
    max_workers: int = 64,
    host: str = "0.0.0.0",
) -> Tuple[grpc.Server, int]:
    """Create (not start) a server; returns (server, bound_port)."""
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[
            ("grpc.max_send_message_length", 256 * 1024 * 1024),
            ("grpc.max_receive_message_length", 256 * 1024 * 1024),
        ],
    )
    server.add_generic_rpc_handlers((_GenericHandler(servicer),))
    bound = server.add_insecure_port(f"{host}:{port}")
    if bound == 0:
        raise RuntimeError(f"cannot bind master service on port {port}")
    return server, bound


def addr_connectable(addr: str, timeout: float = 3.0) -> bool:
    """Cheap reachability probe (the reference telnets the master addr)."""
    import socket

    host, _, port = addr.rpartition(":")
    try:
        with socket.create_connection((host or "127.0.0.1", int(port)), timeout):
            return True
    except (OSError, ValueError):
        return False
