"""Retrying gRPC client channel for master RPCs.

Role parity: the stub + ``retry_grpc_request`` decorator of
``dlrover/python/elastic_agent/master_client.py:28-48``.
"""

from __future__ import annotations

import functools
import time
from typing import Any, Optional

import grpc

from dlrover_tpu.common import serialize
from dlrover_tpu.common.comm import Response
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.rpc.server import SERVICE_NAME

logger = get_logger("rpc.client")


_TRANSIENT_CODES = {
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.DEADLINE_EXCEEDED,
    grpc.StatusCode.RESOURCE_EXHAUSTED,
}


def retry_rpc(retries: int = 5, backoff: float = 1.0):
    """Retry transient RPC failures with linear backoff; non-transient
    codes (bad method, serialization errors, ...) raise immediately."""

    def decorator(fn):
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            for i in range(retries):
                try:
                    return fn(*args, **kwargs)
                except grpc.RpcError as e:
                    if e.code() not in _TRANSIENT_CODES or i == retries - 1:
                        raise
                    logger.warning(
                        "rpc %s failed (%s), retry %d/%d",
                        fn.__name__, e.code(), i + 1, retries,
                    )
                    time.sleep(backoff * (i + 1))

        return wrapped

    return decorator


class RpcChannel:
    """A thin two-method channel: ``get(msg)`` and ``report(msg)``."""

    def __init__(self, addr: str, timeout: float = 30.0):
        self.addr = addr
        self._timeout = timeout
        self._channel = grpc.insecure_channel(
            addr,
            options=[
                ("grpc.max_send_message_length", 256 * 1024 * 1024),
                ("grpc.max_receive_message_length", 256 * 1024 * 1024),
                ("grpc.enable_retries", 1),
            ],
        )
        self._get = self._channel.unary_unary(
            f"/{SERVICE_NAME}/get",
            request_serializer=serialize.dumps,
            response_deserializer=serialize.loads,
        )
        self._report = self._channel.unary_unary(
            f"/{SERVICE_NAME}/report",
            request_serializer=serialize.dumps,
            response_deserializer=serialize.loads,
        )

    @staticmethod
    def _trace_metadata():
        """Invocation metadata carrying the ambient incident trace id
        (if any), so the server side stamps its ingress events with the
        same id (cross-process incident correlation)."""
        from dlrover_tpu.telemetry.trace_context import (
            TRACE_ID_METADATA_KEY,
            current_trace_id,
        )

        tid = current_trace_id()
        return ((TRACE_ID_METADATA_KEY, tid),) if tid else None

    @retry_rpc()
    def get(self, msg: Any) -> Any:
        # spans cover every master RPC — shard-dispatch get_task, comm
        # world polls, kv ops — at the one choke point (SpanName.RPC)
        from dlrover_tpu.telemetry import SpanName, span

        with span(f"{SpanName.RPC}.get.{type(msg).__name__}",
                  category="rpc"):
            return self._get(msg, timeout=self._timeout,
                             metadata=self._trace_metadata())

    @retry_rpc()
    def report(self, msg: Any) -> Response:
        from dlrover_tpu.telemetry import SpanName, span

        with span(f"{SpanName.RPC}.report.{type(msg).__name__}",
                  category="rpc"):
            return self._report(msg, timeout=self._timeout,
                                metadata=self._trace_metadata())

    def close(self):
        self._channel.close()
