"""Retrying gRPC client channel for master RPCs.

Role parity: the stub + ``retry_grpc_request`` decorator of
``dlrover/python/elastic_agent/master_client.py:28-48``.
"""

from __future__ import annotations

import time
from typing import Any, Optional

import grpc

from dlrover_tpu.common import serialize
from dlrover_tpu.common.comm import Response
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.rpc.server import SERVICE_NAME

logger = get_logger("rpc.client")


_TRANSIENT_CODES = {
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.DEADLINE_EXCEEDED,
    grpc.StatusCode.RESOURCE_EXHAUSTED,
}


def _retry_counter():
    """The retry-budget counter (lazy: telemetry may be configured after
    this module imports). Null-object when telemetry is off."""
    from dlrover_tpu.telemetry import get_registry, names as tm

    return get_registry().counter(
        tm.RPC_RETRIES,
        help="transient master-RPC retries taken by the client channel")


def retry_backoff_s(attempt: int, backoff: float = 1.0,
                    cap: float = 30.0) -> float:
    """Jittered exponential backoff for retry ``attempt`` (0-based):
    ``backoff * 2^attempt`` capped at ``cap``, scaled by a uniform
    [0.5, 1.0) draw. The jitter is the load-bearing part: a master blip
    hits EVERY worker at once, and the old fixed-sleep schedule
    re-synchronized the whole fleet into retry stampedes that landed on
    the recovering master together — per-worker random spread breaks
    the thundering herd."""
    import random

    return min(cap, backoff * (2.0 ** attempt)) * random.uniform(0.5, 1.0)


class RpcChannel:
    """A thin two-method channel: ``get(msg)`` and ``report(msg)``.

    ``retries``/``backoff`` tune the transient-failure policy per
    channel: the master channel keeps the patient default, while e.g.
    the replica fetch path runs a fast-fail channel (a dead holder
    should fall through to the next replica in milliseconds, not burn
    the full backoff ladder)."""

    def __init__(self, addr: str, timeout: float = 30.0,
                 retries: int = 5, backoff: float = 1.0):
        self.addr = addr
        self._timeout = timeout
        self._retries = max(1, int(retries))
        self._backoff = float(backoff)
        self._channel = grpc.insecure_channel(
            addr,
            options=[
                ("grpc.max_send_message_length", 256 * 1024 * 1024),
                ("grpc.max_receive_message_length", 256 * 1024 * 1024),
                ("grpc.enable_retries", 1),
            ],
        )
        self._get = self._channel.unary_unary(
            f"/{SERVICE_NAME}/get",
            request_serializer=serialize.dumps,
            response_deserializer=serialize.loads,
        )
        self._report = self._channel.unary_unary(
            f"/{SERVICE_NAME}/report",
            request_serializer=serialize.dumps,
            response_deserializer=serialize.loads,
        )

    @staticmethod
    def _trace_metadata():
        """Invocation metadata carrying the ambient incident trace id
        (if any), so the server side stamps its ingress events with the
        same id (cross-process incident correlation)."""
        from dlrover_tpu.telemetry.trace_context import (
            TRACE_ID_METADATA_KEY,
            current_trace_id,
        )

        tid = current_trace_id()
        return ((TRACE_ID_METADATA_KEY, tid),) if tid else None

    def _invoke(self, method, verb: str, msg: Any) -> Any:
        # spans cover every master RPC — shard-dispatch get_task, comm
        # world polls, kv ops — at the one choke point (SpanName.RPC)
        from dlrover_tpu.telemetry import SpanName, span

        for i in range(self._retries):
            try:
                with span(f"{SpanName.RPC}.{verb}.{type(msg).__name__}",
                          category="rpc"):
                    return method(msg, timeout=self._timeout,
                                  metadata=self._trace_metadata())
            except grpc.RpcError as e:
                if (
                    e.code() not in _TRANSIENT_CODES
                    or i == self._retries - 1
                ):
                    raise
                _retry_counter().inc()
                delay = retry_backoff_s(i, backoff=self._backoff)
                logger.warning(
                    "rpc %s %s failed (%s), retry %d/%d in %.2fs",
                    verb, type(msg).__name__, e.code(), i + 1,
                    self._retries, delay,
                )
                time.sleep(delay)

    def get(self, msg: Any) -> Any:
        return self._invoke(self._get, "get", msg)

    def report(self, msg: Any) -> Response:
        return self._invoke(self._report, "report", msg)

    def close(self):
        self._channel.close()
