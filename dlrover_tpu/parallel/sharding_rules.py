"""Parameter/activation sharding rules: regex path -> PartitionSpec.

Role parity: the *declarative* replacement for atorch's wrapper stack —
``modules_registry.py`` (shardable-op -> sharded-op map driving automatic
TP), ``zero_optimization.py`` (FSDP wrapping) and the MIP planner's output.
On TPU all of those collapse into: every parameter gets a
``NamedSharding``, and XLA's SPMD partitioner inserts the collectives.

Rule grammar (first match wins):
  (r"attention/(q|k|v)_proj/kernel", ("embed", "tensor"))   explicit spec
  (r".*", FSDP_AUTO)                                        shard largest
                                                            divisible dim
                                                            on the fsdp axis
Axis-name tokens in specs are *mesh* axis names; None replicates that dim.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from dlrover_tpu.common.log import get_logger

logger = get_logger("parallel.rules")

FSDP_AUTO = "FSDP_AUTO"
REPLICATED = "REPLICATED"

SpecLike = Union[str, Tuple, None]
Rule = Tuple[str, SpecLike]


def _flatten_with_paths(tree) -> List[Tuple[str, Any]]:
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        name = "/".join(
            str(getattr(p, "key", getattr(p, "idx", p))) for p in path
        )
        out.append((name, leaf))
    return out


def _auto_fsdp_spec(shape: Sequence[int], mesh_axis_sizes: Dict[str, int],
                    fsdp_axis: str = "fsdp") -> Tuple:
    """Shard the largest dim divisible by the fsdp axis size; replicate if
    nothing divides (small params aren't worth scattering)."""
    size = mesh_axis_sizes.get(fsdp_axis, 1)
    if size <= 1 or not shape:
        return tuple(None for _ in shape)
    best_dim, best_len = -1, 0
    for i, d in enumerate(shape):
        if d % size == 0 and d > best_len:
            best_dim, best_len = i, d
    spec = [None] * len(shape)
    if best_dim >= 0:
        spec[best_dim] = fsdp_axis
    return tuple(spec)


def _normalize_spec(spec: SpecLike, shape: Sequence[int],
                    mesh_axis_sizes: Dict[str, int]) -> Tuple:
    if spec == FSDP_AUTO:
        return _auto_fsdp_spec(shape, mesh_axis_sizes)
    if spec in (REPLICATED, None):
        return tuple(None for _ in shape)
    if isinstance(spec, str):
        raise ValueError(
            f"string spec {spec!r} is ambiguous: use FSDP_AUTO, REPLICATED "
            "or a tuple like (None, 'fsdp')"
        )
    # tuple spec: rank must match exactly (rank-mismatched rules never
    # bind — see spec_for — so this is an internal invariant)
    spec = tuple(spec)
    if len(spec) != len(shape):
        raise ValueError(
            f"spec {spec} has rank {len(spec)} but tensor has rank "
            f"{len(shape)}"
        )
    out = []
    for dim, names in zip(shape, spec):
        if names is None:
            out.append(None)
            continue
        names_t = (names,) if isinstance(names, str) else tuple(names)
        total = 1
        for n in names_t:
            total *= mesh_axis_sizes.get(n, 1)
        if total <= 1 or dim % total != 0:
            out.append(None)  # axis collapsed or indivisible: replicate
        else:
            out.append(names if isinstance(names, str) else names_t)
    return tuple(out)


class ShardingRules:
    def __init__(self, rules: Optional[List[Rule]] = None,
                 default: SpecLike = FSDP_AUTO):
        self.rules = list(rules or [])
        self.default = default

    def spec_for(self, path: str, shape: Sequence[int],
                 mesh_axis_sizes: Dict[str, int]) -> Tuple:
        for pattern, spec in self.rules:
            if not re.search(pattern, path):
                continue
            # a tuple spec only binds at its exact rank; rank-mismatched
            # rules fall through (lets stacked [L, ...] and unstacked
            # variants of the same param coexist in one rule list)
            if isinstance(spec, (tuple, list)) and len(spec) != len(shape):
                continue
            return _normalize_spec(spec, shape, mesh_axis_sizes)
        return _normalize_spec(self.default, shape, mesh_axis_sizes)

    def tree_shardings(self, mesh, tree_shapes):
        """Map a pytree of ShapeDtypeStruct/arrays -> NamedShardings."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))

        flat = _flatten_with_paths(tree_shapes)
        specs = {}
        for path, leaf in flat:
            shape = getattr(leaf, "shape", ())
            specs[path] = self.spec_for(path, shape, axis_sizes)

        def to_sharding(path_leaf):
            path, leaf = path_leaf
            return NamedSharding(mesh, PartitionSpec(*specs[path]))

        shardings = [to_sharding(pl) for pl in flat]
        treedef = jax.tree_util.tree_structure(tree_shapes)
        return jax.tree_util.tree_unflatten(treedef, shardings)


def batch_sharding(mesh, spec_axes=(("data", "fsdp"),)):
    """NamedSharding for input batches: leading (batch) dim split across
    the data-parallel axes."""
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec(*spec_axes))


# -- canonical rule sets ----------------------------------------------------

def llama_rules() -> ShardingRules:
    """Megatron-style TP + FSDP for llama-family transformers.

    Parity map (atorch -> here):
      ColumnParallelLinear (layers.py:380)  -> kernel last dim on "tensor"
      RowParallelLinear    (layers.py:227)  -> kernel first dim on "tensor"
      VocabParallelEmbedding (layers.py:540)-> embedding vocab dim sharded
    """
    return ShardingRules(rules=[
        # scan-stacked layer params carry a leading layer dim (fsdp-sharded
        # where divisible gives ZeRO-3-style param scatter for free)
        (r"layers/.*(q_proj|k_proj|v_proj)/kernel$",
         ("fsdp", None, "tensor")),
        (r"layers/.*o_proj/kernel$", ("fsdp", "tensor", None)),
        (r"layers/.*(gate_proj|up_proj)/kernel$", ("fsdp", None, "tensor")),
        (r"layers/.*down_proj/kernel$", ("fsdp", "tensor", None)),
        # MoE blocks: experts over the (data x fsdp) submesh
        (r"layers/.*experts/up/kernel$",
         (None, ("data", "fsdp"), None, "tensor")),
        (r"layers/.*experts/down/kernel$",
         (None, ("data", "fsdp"), "tensor", None)),
        (r"layers/.*router/kernel$", REPLICATED),
        # unstacked variants (per-layer module trees)
        (r"(q_proj|k_proj|v_proj)/kernel$", (None, "tensor")),
        (r"o_proj/kernel$", ("tensor", None)),
        (r"(gate_proj|up_proj)/kernel$", (None, "tensor")),
        (r"down_proj/kernel$", ("tensor", None)),
        # embeddings / head: vocab-parallel
        (r"embed_tokens/embedding$", ("tensor", "fsdp")),
        (r"lm_head/kernel$", ("fsdp", "tensor")),
        # norms replicate
        (r"(norm|ln)[^/]*/(scale|bias)$", REPLICATED),
        (r".*", FSDP_AUTO),
    ])


def llama_pp_rules() -> ShardingRules:
    """Pipeline-parallel llama: the stacked layer dim lands on "pipe" so
    each pipeline stage holds its contiguous chunk of layers
    (``parallel.pipeline`` reshapes [L, ...] -> [P, L/P, ...] in-program,
    a local reshape since L is pipe-sharded). TP stays on "tensor"."""
    return ShardingRules(rules=[
        (r"layers/.*(q_proj|k_proj|v_proj)/kernel$",
         ("pipe", None, "tensor")),
        (r"layers/.*o_proj/kernel$", ("pipe", "tensor", None)),
        (r"layers/.*(gate_proj|up_proj)/kernel$", ("pipe", None, "tensor")),
        (r"layers/.*down_proj/kernel$", ("pipe", "tensor", None)),
        (r"layers/.*experts/up/kernel$",
         ("pipe", ("data", "fsdp"), None, "tensor")),
        (r"layers/.*experts/down/kernel$",
         ("pipe", ("data", "fsdp"), "tensor", None)),
        (r"layers/.*router/kernel$", ("pipe", None, None)),
        (r"layers/.*(input_norm|post_norm)/scale$", ("pipe", None)),
        (r"embed_tokens/embedding$", ("tensor", "fsdp")),
        (r"lm_head/kernel$", ("fsdp", "tensor")),
        (r"(norm|ln)[^/]*/(scale|bias)$", REPLICATED),
        (r".*", FSDP_AUTO),
    ])


def bert_rules() -> ShardingRules:
    """BERT-family encoders: same Megatron TP layout as llama plus the
    three-table embedding block and the MLM head."""
    return ShardingRules(rules=[
        (r"layers/.*(q_proj|k_proj|v_proj)/kernel$",
         ("fsdp", None, "tensor")),
        (r"layers/.*(q_proj|k_proj|v_proj)/bias$", ("fsdp", "tensor")),
        (r"layers/.*o_proj/kernel$", ("fsdp", "tensor", None)),
        (r"layers/.*up_proj/kernel$", ("fsdp", None, "tensor")),
        (r"layers/.*up_proj/bias$", ("fsdp", "tensor")),
        (r"layers/.*down_proj/kernel$", ("fsdp", "tensor", None)),
        (r"embeddings/word/embedding$", ("tensor", "fsdp")),
        (r"embeddings/(position|token_type)/embedding$", (None, "fsdp")),
        (r"mlm_head/kernel$", ("fsdp", "tensor")),
        (r"mlm_head/bias$", ("tensor",)),
        (r"(norm|ln)[^/]*/(scale|bias)$", REPLICATED),
        (r".*", FSDP_AUTO),
    ])


def bert_pp_rules() -> ShardingRules:
    """Pipeline-parallel BERT: stacked layer dim on "pipe" (layer bias
    vectors and the o/down biases included); embeddings, pooler and the
    MLM head stay outside the pipe."""
    return ShardingRules(rules=[
        (r"layers/.*(q_proj|k_proj|v_proj|up_proj)/kernel$",
         ("pipe", None, "tensor")),
        (r"layers/.*(q_proj|k_proj|v_proj|up_proj)/bias$",
         ("pipe", "tensor")),
        (r"layers/.*(o_proj|down_proj)/kernel$", ("pipe", "tensor", None)),
        (r"layers/.*(o_proj|down_proj)/bias$", ("pipe", None)),
        (r"layers/.*(attn_norm|ffn_norm)/(scale|bias)$", ("pipe", None)),
        (r"embeddings/word/embedding$", ("tensor", "fsdp")),
        (r"embeddings/(position|token_type)/embedding$", (None, "fsdp")),
        (r"mlm_head/kernel$", ("fsdp", "tensor")),
        (r"mlm_head/bias$", ("tensor",)),
        (r"(norm|ln)[^/]*/(scale|bias)$", REPLICATED),
        (r".*", FSDP_AUTO),
    ])


def clip_rules() -> ShardingRules:
    """CLIP dual encoder: both towers' stacked blocks reuse the llama
    TP/FSDP layout (paths are nested under text/ and vision/)."""
    return llama_rules()


def neox_rules() -> ShardingRules:
    """GPT-NeoX / GLM family: llama's Megatron column/row layout plus the
    bias vectors — a column-parallel projection's bias shards with its
    output dim; a row-parallel projection's bias replicates (it adds after
    the reduce)."""
    return ShardingRules(rules=[
        (r"layers/.*(q_proj|k_proj|v_proj|up_proj)/kernel$",
         ("fsdp", None, "tensor")),
        (r"layers/.*(q_proj|k_proj|v_proj|up_proj)/bias$",
         ("fsdp", "tensor")),
        (r"layers/.*(o_proj|down_proj)/kernel$", ("fsdp", "tensor", None)),
        (r"layers/.*(o_proj|down_proj)/bias$", ("fsdp", None)),
        (r"layers/.*(input_norm|post_norm)/(scale|bias)$", ("fsdp", None)),
        (r"embed_tokens/embedding$", ("tensor", "fsdp")),
        (r"(pos|block_pos)_embed/embedding$", (None, "fsdp")),
        (r"lm_head/kernel$", ("fsdp", "tensor")),
        (r"(norm|ln|final_norm)[^/]*/(scale|bias)$", REPLICATED),
        (r".*", FSDP_AUTO),
    ])


def glm_rules() -> ShardingRules:
    """GLM shares NeoX's biased-projection layout; the 2D position tables
    get their own fsdp rule (in neox_rules already)."""
    return neox_rules()


def neox_pp_rules() -> ShardingRules:
    """Pipeline-parallel NeoX/GLM: like ``llama_pp_rules``, the stacked
    layer dim lands on "pipe" (each stage holds its chunk locally); bias
    vectors follow their kernels' tensor split."""
    return ShardingRules(rules=[
        (r"layers/.*(q_proj|k_proj|v_proj|up_proj)/kernel$",
         ("pipe", None, "tensor")),
        (r"layers/.*(q_proj|k_proj|v_proj|up_proj)/bias$",
         ("pipe", "tensor")),
        (r"layers/.*(o_proj|down_proj)/kernel$", ("pipe", "tensor", None)),
        (r"layers/.*(o_proj|down_proj)/bias$", ("pipe", None)),
        (r"layers/.*(input_norm|post_norm)/(scale|bias)$", ("pipe", None)),
        (r"embed_tokens/embedding$", ("tensor", "fsdp")),
        (r"(pos|block_pos)_embed/embedding$", (None, "fsdp")),
        (r"lm_head/kernel$", ("fsdp", "tensor")),
        (r"(norm|ln|final_norm)[^/]*/(scale|bias)$", REPLICATED),
        (r".*", FSDP_AUTO),
    ])


def glm_pp_rules() -> ShardingRules:
    """GLM pipeline layout = NeoX's (same biased-projection family)."""
    return neox_pp_rules()


def gpt2_pp_rules() -> ShardingRules:
    """Pipeline-parallel GPT-2: stacked layer dim on "pipe", Megatron
    column/row split on "tensor"; the tied embed/head table and learned
    positions stay outside the pipe (fsdp/tensor sharded)."""
    return ShardingRules(rules=[
        (r"layers/.*(q_proj|k_proj|v_proj|up_proj)/kernel$",
         ("pipe", None, "tensor")),
        (r"layers/.*up_proj/bias$", ("pipe", "tensor")),
        (r"layers/.*(o_proj|down_proj)/kernel$", ("pipe", "tensor", None)),
        (r"layers/.*down_proj/bias$", ("pipe", None)),
        (r"layers/.*(ln_1|ln_2)/(scale|bias)$", ("pipe", None)),
        (r"embed_tokens/embedding$", ("tensor", "fsdp")),
        (r"embed_pos/embedding$", (None, "fsdp")),
        (r"(norm|ln)[^/]*/(scale|bias)$", REPLICATED),
        (r".*", FSDP_AUTO),
    ])


def moe_rules() -> ShardingRules:
    """Expert-parallel MoE: expert weight blocks sharded on the expert
    (data x fsdp) submesh; router replicated."""
    rules = llama_rules().rules
    return ShardingRules(rules=[
        # leading dim = experts, sharded over the (data x fsdp) submesh
        (r"experts/.*kernel$", (("data", "fsdp"), None, "tensor")),
        (r"router/kernel$", REPLICATED),
        *rules,
    ])


def moe_ep_rules() -> ShardingRules:
    """Expert-parallel MoE for the DROPLESS ``dispatch="grouped_ep"``
    path (``ops.moe._moe_compute_grouped_ep``): expert weight blocks
    sharded on the (data x fsdp) expert submesh like ``moe_rules``, but
    the expert FFN dims stay UNSHARDED — the grouped Pallas kernel runs
    per shard inside a shard_map, so a "tensor" split of d_ff would
    force an all-gather at the shard_map boundary every layer instead
    of a partitioned matmul. Dense (attention) params keep the llama
    TP/FSDP layout."""
    rules = llama_rules().rules
    return ShardingRules(rules=[
        # stacked [L, E, D, F] layer variants first (rank-4 binds here)
        (r"layers/.*experts/(up|down)/kernel$",
         (None, ("data", "fsdp"), None, None)),
        # unstacked [E, D, F] module trees (direct moe_ffn params)
        (r"experts/(up|down)/kernel$", (("data", "fsdp"), None, None)),
        (r"router/kernel$", REPLICATED),
        *rules,
    ])
