"""Device-mesh planning.

Role parity: ``atorch/atorch/distributed/distributed.py:318-402``
(``create_parallel_group`` building nested NCCL process groups from
``[("tensor",4),("pipe",2),("data",2)]``). TPU-first: the same nested
topology is a single ``jax.sharding.Mesh`` whose axis order controls which
axes ride the fast ICI links; XLA lowers collectives from shardings, so no
process groups are ever materialized.

Axis convention (outer -> inner):
  "pipe"   pipeline stages            (DCN-friendly, least traffic)
  "data"   pure data parallel         (gradient psum only)
  "fsdp"   data parallel + param/optimizer sharding (ZeRO-3 analogue)
  "seq"    sequence/context parallel  (ring attention neighbors on ICI)
  "tensor" megatron-style op sharding (most traffic, innermost => ICI)
  "expert" MoE expert parallel (aliases fsdp/data in most configs)
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from dlrover_tpu.common.log import get_logger

logger = get_logger("parallel.mesh")

MESH_AXES = ("pipe", "data", "fsdp", "seq", "tensor")
EXPERT_AXIS = "expert"


@dataclass
class MeshPlan:
    """Declarative mesh shape; -1 on at most one axis means 'infer'.

    ``expert`` does not get its own mesh dimension: expert parallelism
    reuses the (data x fsdp) submesh (the reference's expert process groups
    are also carved out of the data-parallel ranks,
    ``atorch/modules/moe/moe_layer.py:29``).
    """

    pipe: int = 1
    data: int = -1
    fsdp: int = 1
    seq: int = 1
    tensor: int = 1

    def axis_sizes(self) -> Dict[str, int]:
        return {
            "pipe": self.pipe, "data": self.data, "fsdp": self.fsdp,
            "seq": self.seq, "tensor": self.tensor,
        }

    def resolve(self, num_devices: int) -> "MeshPlan":
        """Fill the -1 axis so the product equals num_devices."""
        sizes = self.axis_sizes()
        unknown = [k for k, v in sizes.items() if v == -1]
        if len(unknown) > 1:
            raise ValueError(f"at most one -1 axis allowed: {sizes}")
        known = math.prod(v for v in sizes.values() if v != -1)
        if unknown:
            if num_devices % known:
                raise ValueError(
                    f"{num_devices} devices not divisible by fixed axes "
                    f"{sizes}"
                )
            sizes[unknown[0]] = num_devices // known
        elif known != num_devices:
            raise ValueError(
                f"mesh {sizes} wants {known} devices, have {num_devices}"
            )
        return MeshPlan(**sizes)

    def adjust_to_world(self, num_devices: int) -> "MeshPlan":
        """Refit for a new world size after elastic scale up/down.

        Parity with ``atorch/auto/accelerate.py:309-356``
        (``adjust_strategy`` refits the data-parallel degree and keeps the
        model-parallel axes): tensor/seq/pipe are topology-bound choices,
        so the data and fsdp axes absorb the change, preferring fsdp.
        """
        model_par = self.pipe * self.seq * self.tensor
        if num_devices % model_par:
            raise ValueError(
                f"world of {num_devices} devices cannot hold model-parallel "
                f"factor {model_par} (pipe x seq x tensor)"
            )
        dp_total = num_devices // model_par
        old_fsdp = max(1, self.fsdp)
        # keep fsdp as close to the old degree as divisibility allows:
        # the largest divisor of dp_total not exceeding the old degree
        # (shrinking fsdp raises per-device param memory, so shrink least).
        fsdp = max(
            (d for d in _divisors(dp_total) if d <= old_fsdp), default=1
        )
        data = dp_total // fsdp
        return MeshPlan(pipe=self.pipe, data=data, fsdp=fsdp,
                        seq=self.seq, tensor=self.tensor)

    def build(self, devices: Optional[Sequence] = None):
        """Materialize a ``jax.sharding.Mesh``.

        Axis order is outer->inner so the most communication-hungry axis
        ("tensor") maps to the most-adjacent devices (ICI neighbors on a
        TPU torus; ``mesh_utils`` handles the physical assignment).
        """
        import jax
        from jax.experimental import mesh_utils
        from jax.sharding import Mesh

        devices = list(devices) if devices is not None else jax.devices()
        plan = self.resolve(len(devices))
        shape = tuple(plan.axis_sizes()[a] for a in MESH_AXES)
        try:
            device_array = mesh_utils.create_device_mesh(
                shape, devices=devices
            )
        except (ValueError, AssertionError):
            device_array = np.asarray(devices).reshape(shape)
        return Mesh(device_array, MESH_AXES)

    @property
    def dp_degree(self) -> int:
        return max(1, self.data) * max(1, self.fsdp)


def topology_key(devices: Optional[Sequence] = None) -> str:
    """Stable identity of a device set, for compiled-program caching.

    Two worlds with the same key can reuse each other's compiled SPMD
    programs verbatim (same platform, same device identities, same
    order ⇒ same HLO, same executable). ``ElasticTrainer`` keys its
    in-process program cache on this so a live reshard BACK to a
    topology it already compiled for — the scale-down-then-recover
    pattern — pays zero recompiles; ``utils.compile_cache`` keys the
    persistent on-disk cache on the env-derived analogue
    (``topology_hint``), which needs no backend.
    """
    import jax

    devices = list(devices) if devices is not None else jax.devices()
    return "|".join(
        f"{getattr(d, 'platform', '?')}:{getattr(d, 'id', '?')}"
        for d in devices
    )


def mesh_axes_key(plan: MeshPlan) -> str:
    """Stable identity of a mesh FACTORIZATION ("pipe.data.fsdp.seq.
    tensor") — the one format shared by the trainer's program-cache key,
    the runtime optimizer's candidate/cooldown keys, and mesh dedup, so
    an axis added to MeshPlan cannot silently diverge them."""
    return (f"{plan.pipe}.{plan.data}.{plan.fsdp}"
            f".{plan.seq}.{plan.tensor}")


def single_device_plan() -> MeshPlan:
    return MeshPlan(pipe=1, data=1, fsdp=1, seq=1, tensor=1)


def candidate_plans(num_devices: int,
                    max_model_parallel: Optional[int] = None) -> List[MeshPlan]:
    """Enumerate plausible mesh shapes for the auto-tuner.

    Parity with the strategy-generation half of atorch's search engine
    (``auto/engine/sg_algo/combination_sg.py``): candidates are the
    divisor factorizations of the device count over (fsdp, tensor), with
    data absorbing the rest; seq/pipe candidates are added by the tuner
    only when the model asks for them (long context / stages).
    """
    plans = []
    max_mp = max_model_parallel or num_devices
    for tensor in _divisors(num_devices):
        if tensor > max_mp:
            continue
        rest = num_devices // tensor
        for fsdp in _divisors(rest):
            data = rest // fsdp
            plans.append(
                MeshPlan(pipe=1, data=data, fsdp=fsdp, seq=1, tensor=tensor)
            )
    return plans


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]
