"""Distributed acceleration engine: rank0 searches, all ranks execute.

Role parity: ``atorch/atorch/auto/engine/executor.py:36`` +
``auto/accelerate.py:563-614`` — rank0 hosts an AccelerationEngine;
every rank runs an EngineClient loop pulling tasks (ANALYSE / DRYRUN /
SETUP_PARALLEL_GROUP / FINISH) over RPC and reporting results. Here the
engine serves Strategy candidates (from ``parallel.search``), collects
dryrun timings into a ``StrategyInfoCollection``, and finishes every
client with the winning strategy — which each rank applies via
``accelerate`` (the SETUP_PARALLEL_GROUP equivalent: on TPU the mesh is
built per-process from the same Strategy, no NCCL group plumbing).
"""

from __future__ import annotations

import threading
from dataclasses import field
from typing import Callable, Dict, List, Optional, Sequence

from dlrover_tpu.common import serialize
from dlrover_tpu.common.comm import Response
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.parallel.search import StrategyInfo, StrategyInfoCollection
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.rpc.client import RpcChannel
from dlrover_tpu.rpc.server import build_server

logger = get_logger("parallel.engine")


class TaskType:
    ANALYSE = "analyse"
    DRYRUN = "dryrun"
    WAIT = "wait"
    FINISH = "finish"
    FAIL = "fail"


@serialize.message
class EngineTaskRequest:
    node_rank: int = 0


@serialize.message
class EngineTask:
    task_id: int = -1
    task_type: str = TaskType.WAIT
    strategy_json: str = ""
    payload: Dict = field(default_factory=dict)


@serialize.message
class EngineTaskResult:
    task_id: int = -1
    node_rank: int = 0
    ok: bool = True
    step_time_s: float = 0.0
    peak_memory_bytes: int = 0
    error: str = ""
    payload: Dict = field(default_factory=dict)


class AccelerationEngineServicer:
    """Serves candidates round-robin to whichever rank asks next;
    finishes everyone once all candidates are scored (or the budget is
    spent)."""

    def __init__(self, candidates: Sequence[Strategy],
                 analyse_first: bool = True):
        self._lock = threading.Lock()
        self._candidates = list(candidates)
        if not self._candidates:
            raise ValueError("engine needs at least one candidate strategy")
        self._next = 0
        self._outstanding: Dict[int, Strategy] = {}
        self._analyse_done = not analyse_first
        self.collection = StrategyInfoCollection()
        self.analysis: Dict = {}

    # -- transport entry points ---------------------------------------------

    def get(self, request, context=None) -> EngineTask:
        if not isinstance(request, EngineTaskRequest):
            return EngineTask(task_type=TaskType.FAIL)
        with self._lock:
            if not self._analyse_done:
                self._analyse_done = True
                return EngineTask(task_id=-2, task_type=TaskType.ANALYSE)
            if self._next < len(self._candidates):
                task_id = self._next
                strategy = self._candidates[task_id]
                self._next += 1
                self._outstanding[task_id] = strategy
                return EngineTask(
                    task_id=task_id, task_type=TaskType.DRYRUN,
                    strategy_json=strategy.to_json(),
                )
            if self._outstanding:
                return EngineTask(task_type=TaskType.WAIT)
            best = self.collection.best
            if best is None:
                return EngineTask(task_type=TaskType.FAIL)
            return EngineTask(
                task_type=TaskType.FINISH,
                strategy_json=best.strategy.to_json(),
            )

    def report(self, request, context=None) -> Response:
        if not isinstance(request, EngineTaskResult):
            return Response(success=False, reason="unknown message")
        with self._lock:
            if request.task_id == -2:  # analysis result
                self.analysis.update(request.payload)
                return Response(success=True)
            strategy = self._outstanding.pop(request.task_id, None)
            if strategy is None:
                return Response(success=False, reason="unknown task")
            self.collection.add(StrategyInfo(
                strategy=strategy,
                step_time_s=request.step_time_s,
                peak_memory_bytes=request.peak_memory_bytes,
                error="" if request.ok else (request.error or "failed"),
            ))
        return Response(success=True)


class AccelerationEngine:
    """rank0-hosted engine service (``AccelerationEngine.start_service``
    parity)."""

    def __init__(self, candidates: Sequence[Strategy], port: int = 0):
        self.servicer = AccelerationEngineServicer(candidates)
        self._server, self.port = build_server(self.servicer, port=port)
        self.addr = f"127.0.0.1:{self.port}"

    def start(self):
        self._server.start()
        logger.info("acceleration engine at :%d", self.port)

    def stop(self, grace: float = 1.0):
        self._server.stop(grace)

    @property
    def best_strategy(self) -> Optional[Strategy]:
        best = self.servicer.collection.best
        return best.strategy if best else None


class EngineClient:
    """Per-rank task loop (``EngineClient`` / ``run_task`` parity).

    ``dryrun_fn(strategy) -> StrategyInfo`` measures one candidate;
    ``analyse_fn() -> dict`` reports device/model facts (rank0 only
    receives the ANALYSE task once).
    """

    def __init__(
        self,
        addr: str,
        node_rank: int,
        dryrun_fn: Callable[[Strategy], StrategyInfo],
        analyse_fn: Optional[Callable[[], Dict]] = None,
        poll_interval: float = 0.2,
    ):
        self._channel = RpcChannel(addr)
        self._rank = node_rank
        self._dryrun = dryrun_fn
        self._analyse = analyse_fn
        self._interval = poll_interval

    def run(self, max_tasks: int = 1000) -> Strategy:
        """Execute tasks until FINISH; returns the winning strategy."""
        import time

        for _ in range(max_tasks):
            task: EngineTask = self._channel.get(
                EngineTaskRequest(node_rank=self._rank)
            )
            if task.task_type == TaskType.FINISH:
                return Strategy.from_json(task.strategy_json)
            if task.task_type == TaskType.FAIL:
                raise RuntimeError("engine search failed: no viable strategy")
            if task.task_type == TaskType.WAIT:
                time.sleep(self._interval)
                continue
            if task.task_type == TaskType.ANALYSE:
                payload = self._analyse() if self._analyse else {}
                self._channel.report(EngineTaskResult(
                    task_id=task.task_id, node_rank=self._rank,
                    payload=payload,
                ))
                continue
            # DRYRUN
            strategy = Strategy.from_json(task.strategy_json)
            try:
                info = self._dryrun(strategy)
                self._channel.report(EngineTaskResult(
                    task_id=task.task_id, node_rank=self._rank,
                    ok=info.ok, step_time_s=info.step_time_s,
                    peak_memory_bytes=info.peak_memory_bytes,
                    error=info.error,
                ))
            except Exception as e:  # noqa: BLE001 — report, keep looping
                self._channel.report(EngineTaskResult(
                    task_id=task.task_id, node_rank=self._rank,
                    ok=False, error=f"{type(e).__name__}: {e}"[:200],
                ))
        raise RuntimeError("engine task budget exhausted without FINISH")

    def close(self):
        self._channel.close()
