"""Distributed acceleration engine: rank0 searches, all ranks execute.

Role parity: ``atorch/atorch/auto/engine/executor.py:36`` +
``auto/accelerate.py:563-614`` — rank0 hosts an AccelerationEngine;
every rank runs an EngineClient loop pulling tasks (ANALYSE / DRYRUN /
SETUP_PARALLEL_GROUP / FINISH) over RPC and reporting results. Here the
engine serves Strategy candidates (from ``parallel.search``), collects
dryrun timings into a ``StrategyInfoCollection``, and finishes every
client with the winning strategy — which each rank applies via
``accelerate`` (the SETUP_PARALLEL_GROUP equivalent: on TPU the mesh is
built per-process from the same Strategy, no NCCL group plumbing).
"""

from __future__ import annotations

import threading
from dataclasses import field
from typing import Callable, Dict, List, Optional, Sequence

from dlrover_tpu.common import serialize
from dlrover_tpu.common.comm import Response
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.parallel.search import StrategyInfo, StrategyInfoCollection
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.rpc.client import RpcChannel
from dlrover_tpu.rpc.server import build_server

logger = get_logger("parallel.engine")


class TaskType:
    ANALYSE = "analyse"
    DRYRUN = "dryrun"
    WAIT = "wait"
    FINISH = "finish"
    FAIL = "fail"


@serialize.message
class EngineTaskRequest:
    node_rank: int = 0


@serialize.message
class EngineTask:
    task_id: int = -1
    task_type: str = TaskType.WAIT
    strategy_json: str = ""
    payload: Dict = field(default_factory=dict)


@serialize.message
class EngineTaskResult:
    task_id: int = -1
    node_rank: int = 0
    ok: bool = True
    step_time_s: float = 0.0
    peak_memory_bytes: int = 0
    error: str = ""
    payload: Dict = field(default_factory=dict)


class AccelerationEngineServicer:
    """Serves candidates round-robin to whichever rank asks next;
    finishes everyone once all candidates are scored (or the budget is
    spent).

    Fault tolerance (reference ``executor.py:36`` task lifecycle): an
    outstanding DRYRUN whose rank goes silent past ``task_timeout_s`` is
    reassigned to the next asking rank; after ``max_attempts`` the
    candidate is recorded as failed instead of wedging every other rank
    in WAIT forever — in an elastic job the search itself must survive a
    worker loss."""

    def __init__(self, candidates: Sequence[Strategy],
                 analyse_first: bool = True,
                 task_timeout_s: float = 600.0,
                 max_attempts: int = 2):
        self._lock = threading.Lock()
        self._candidates = list(candidates)
        if not self._candidates:
            raise ValueError("engine needs at least one candidate strategy")
        self._next = 0
        # task_id -> (strategy, rank, deadline)
        self._outstanding: Dict[int, tuple] = {}
        self._retry: List[int] = []
        self._attempts: Dict[int, int] = {}
        self._timeout = task_timeout_s
        self._max_attempts = max_attempts
        self._analyse_done = not analyse_first
        self.collection = StrategyInfoCollection()
        self.analysis: Dict = {}

    def mark_rank_failed(self, rank: int):
        """Immediately reassign every task outstanding on a dead rank.

        The timeout is only the backstop: the master's failure reports
        know a rank died within seconds (reference: the executor keys off
        live task state, ``atorch/auto/engine/executor.py:36``), so wire
        ``report_failure`` -> this and the search never stalls a full
        ``task_timeout_s`` on a known-dead worker."""
        with self._lock:
            for task_id in [
                t for t, (_, r, _) in self._outstanding.items() if r == rank
            ]:
                self._release_task(task_id, f"rank {rank} died")

    def _release_task(self, task_id: int, reason: str):
        """Under the lock: pop an outstanding task and either queue it
        for reassignment or record the candidate as failed (shared by
        the timeout backstop and the dead-rank fast path)."""
        strategy, rank, _ = self._outstanding.pop(task_id)
        if self._attempts[task_id] < self._max_attempts:
            logger.warning(
                "task %d on rank %d released (%s); reassigning",
                task_id, rank, reason,
            )
            self._retry.append(task_id)
        else:
            logger.warning(
                "task %d failed after %d attempts (%s)",
                task_id, self._attempts[task_id], reason,
            )
            self.collection.add(StrategyInfo(
                strategy=strategy,
                error=f"{reason} after {self._attempts[task_id]} attempts",
            ))

    def _reap_expired(self):
        """Under the lock: move timed-out tasks to retry or fail them."""
        import time

        now = time.monotonic()
        for task_id in [
            t for t, (_, _, deadline) in self._outstanding.items()
            if now > deadline
        ]:
            self._release_task(task_id, "dryrun timeout")

    def _assign(self, task_id: int, rank: int) -> EngineTask:
        import time

        strategy = self._candidates[task_id]
        self._attempts[task_id] = self._attempts.get(task_id, 0) + 1
        self._outstanding[task_id] = (
            strategy, rank, time.monotonic() + self._timeout
        )
        return EngineTask(
            task_id=task_id, task_type=TaskType.DRYRUN,
            strategy_json=strategy.to_json(),
        )

    # -- transport entry points ---------------------------------------------

    def get(self, request, context=None) -> EngineTask:
        if not isinstance(request, EngineTaskRequest):
            return EngineTask(task_type=TaskType.FAIL)
        with self._lock:
            if not self._analyse_done:
                self._analyse_done = True
                return EngineTask(task_id=-2, task_type=TaskType.ANALYSE)
            self._reap_expired()
            if self._retry:
                return self._assign(self._retry.pop(0), request.node_rank)
            if self._next < len(self._candidates):
                task_id = self._next
                self._next += 1
                return self._assign(task_id, request.node_rank)
            if self._outstanding:
                return EngineTask(task_type=TaskType.WAIT)
            best = self.collection.best
            if best is None:
                return EngineTask(task_type=TaskType.FAIL)
            return EngineTask(
                task_type=TaskType.FINISH,
                strategy_json=best.strategy.to_json(),
            )

    def report(self, request, context=None) -> Response:
        if not isinstance(request, EngineTaskResult):
            return Response(success=False, reason="unknown message")
        with self._lock:
            if request.task_id == -2:  # analysis result
                self.analysis.update(request.payload)
                return Response(success=True)
            entry = self._outstanding.get(request.task_id)
            if entry is None:
                # late report for a task already completed or failed
                return Response(success=False, reason="unknown task")
            if entry[1] != request.node_rank:
                # late report from a rank whose task was reassigned to
                # another rank — only the current assignee's counts
                return Response(success=False, reason="task reassigned")
            del self._outstanding[request.task_id]
            strategy = entry[0]
            self.collection.add(StrategyInfo(
                strategy=strategy,
                step_time_s=request.step_time_s,
                peak_memory_bytes=request.peak_memory_bytes,
                error="" if request.ok else (request.error or "failed"),
            ))
        return Response(success=True)


class AccelerationEngine:
    """rank0-hosted engine service (``AccelerationEngine.start_service``
    parity)."""

    def __init__(self, candidates: Sequence[Strategy], port: int = 0,
                 task_timeout_s: float = 600.0, max_attempts: int = 2):
        self.servicer = AccelerationEngineServicer(
            candidates, task_timeout_s=task_timeout_s,
            max_attempts=max_attempts,
        )
        self._server, self.port = build_server(self.servicer, port=port)
        self.addr = f"127.0.0.1:{self.port}"
        self._watch_stop: Optional[threading.Event] = None

    def start(self):
        self._server.start()
        logger.info("acceleration engine at :%d", self.port)

    def stop(self, grace: float = 1.0):
        if self._watch_stop is not None:
            self._watch_stop.set()
        self._server.stop(grace)

    @property
    def best_strategy(self) -> Optional[Strategy]:
        best = self.servicer.collection.best
        return best.strategy if best else None

    def mark_rank_failed(self, rank: int):
        """Failure-report hook: reassign the dead rank's tasks now
        instead of waiting out the timeout backstop."""
        self.servicer.mark_rank_failed(rank)

    def watch_failures(self, master_client, poll_secs: float = 2.0):
        """Poll the master's failure reports and reassign dead ranks'
        tasks within seconds — ``task_timeout_s`` stays only as the
        backstop (reference: the executor keys off live task state,
        ``atorch/auto/engine/executor.py:36``)."""
        if self._watch_stop is not None:
            return
        self._watch_stop = threading.Event()
        since = -1.0  # < 0 = baseline probe: master clock, no history
        primed = False
        import time as _time

        watch_start_mono = _time.monotonic()

        def loop():
            nonlocal since, primed
            while not self._watch_stop.is_set():
                # advancing window (with 1 s overlap), not a seen-set: a
                # rank that restarts and dies AGAIN must be re-marked;
                # duplicate marks are harmless (only outstanding tasks of
                # that rank get reassigned). Window starts are MASTER
                # clock, so cross-host skew can't drop records. The
                # baseline probe (since<0) returns no ranks; the first
                # window then reaches BACK by the (skew-free, monotonic)
                # time elapsed since the watch started, so a failure
                # landing before the first successful poll is still
                # caught while pre-watch history is excluded.
                local_now = _time.time()
                try:
                    ranks, server_time = master_client.failed_nodes_since(
                        since_timestamp=since
                    )
                    if primed:
                        for rank in ranks:
                            self.mark_rank_failed(rank)
                    # older masters omit server_time: degrade to the
                    # local clock rather than going inert
                    base = server_time or local_now
                    if not primed:
                        back = _time.monotonic() - watch_start_mono + 1.0
                        since = base - back
                        primed = True
                    else:
                        since = base - 1.0
                except Exception:  # noqa: BLE001 — keep watching
                    logger.exception("failure watch poll failed")
                self._watch_stop.wait(poll_secs)

        threading.Thread(target=loop, name="engine-failure-watch",
                         daemon=True).start()


class EngineClient:
    """Per-rank task loop (``EngineClient`` / ``run_task`` parity).

    ``dryrun_fn(strategy) -> StrategyInfo`` measures one candidate;
    ``analyse_fn() -> dict`` reports device/model facts (rank0 only
    receives the ANALYSE task once).
    """

    def __init__(
        self,
        addr: str,
        node_rank: int,
        dryrun_fn: Callable[[Strategy], StrategyInfo],
        analyse_fn: Optional[Callable[[], Dict]] = None,
        poll_interval: float = 0.2,
    ):
        self._channel = RpcChannel(addr)
        self._rank = node_rank
        self._dryrun = dryrun_fn
        self._analyse = analyse_fn
        self._interval = poll_interval

    def run(self, max_tasks: int = 1000) -> Strategy:
        """Execute tasks until FINISH; returns the winning strategy."""
        import time

        for _ in range(max_tasks):
            task: EngineTask = self._channel.get(
                EngineTaskRequest(node_rank=self._rank)
            )
            if task.task_type == TaskType.FINISH:
                return Strategy.from_json(task.strategy_json)
            if task.task_type == TaskType.FAIL:
                raise RuntimeError("engine search failed: no viable strategy")
            if task.task_type == TaskType.WAIT:
                time.sleep(self._interval)
                continue
            if task.task_type == TaskType.ANALYSE:
                payload = self._analyse() if self._analyse else {}
                self._channel.report(EngineTaskResult(
                    task_id=task.task_id, node_rank=self._rank,
                    payload=payload,
                ))
                continue
            # DRYRUN
            strategy = Strategy.from_json(task.strategy_json)
            try:
                info = self._dryrun(strategy)
                self._channel.report(EngineTaskResult(
                    task_id=task.task_id, node_rank=self._rank,
                    ok=info.ok, step_time_s=info.step_time_s,
                    peak_memory_bytes=info.peak_memory_bytes,
                    error=info.error,
                ))
            except Exception as e:  # noqa: BLE001 — report, keep looping
                self._channel.report(EngineTaskResult(
                    task_id=task.task_id, node_rank=self._rank,
                    ok=False, error=f"{type(e).__name__}: {e}"[:200],
                ))
        raise RuntimeError("engine task budget exhausted without FINISH")

    def close(self):
        self._channel.close()
