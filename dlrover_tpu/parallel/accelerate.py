"""``accelerate`` — one call from (model, optimizer, strategy) to a
sharded, compiled, elastic-ready train step.

Role parity: ``auto_accelerate`` (``atorch/atorch/auto/accelerate.py:395``).
Where the reference mutates the model through a stack of wrappers
(DDP/FSDP/TP rewrites/AMP/checkpoint), the TPU version is purely
functional: parameters and optimizer state get ``NamedSharding``s from the
strategy's rules, the train step is ``jit``-ed with those shardings, and
XLA's SPMD partitioner inserts every collective. Gradient accumulation (the
fixed-global-batch elasticity lever) is a ``lax.scan`` over microbatches.
"""

from __future__ import annotations

import dataclasses
import functools
from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

import flax.struct

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.parallel.sharding_rules import batch_sharding
from dlrover_tpu.parallel.strategy import Strategy

logger = get_logger("parallel.accelerate")

# loss_fn contract: (params, batch, rng) -> (scalar_loss, aux_dict)
LossFn = Callable[[Any, Any, Any], Tuple[jnp.ndarray, dict]]


@flax.struct.dataclass
class TrainState:
    step: jnp.ndarray
    params: Any
    opt_state: Any
    # error-feedback residual of the low-precision gradient path
    # (``grad_precision`` != "bf16"): the decompression error of the
    # last step's quantized gradients, param-shaped and param-sharded,
    # added back before the next quantize so the error telescopes
    # instead of accumulating. Part of the TRAINING STATE proper — it
    # rides HostSnapshot, checkpoint save/restore and live reshard
    # exactly like optimizer moments. None when the gradient wire is
    # exact (the default), so existing checkpoints and states are
    # structurally unchanged.
    wire_residual: Any = None


@dataclass
class AccelerateResult:
    train_step: Callable  # (state, batch, rng) -> (state, metrics)
    eval_step: Callable  # (state, batch) -> metrics
    init_fn: Callable  # (rng) -> sharded TrainState
    mesh: Any
    state_sharding: Any
    batch_spec: Any
    strategy: Strategy
    # multi-step fusion (steps_per_call > 1): one dispatch runs a
    # lax.scan over K stacked batches — (state, batches[K,...],
    # rngs[K,2]) -> (state, stacked metrics). None when K == 1.
    train_step_multi: Optional[Callable] = None
    steps_per_call: int = 1
    stacked_batch_spec: Any = None

    def compiled_cache_size(self) -> int:
        """Executables held by this result's jitted programs (the
        train step and, when built, the K-step scan). A loop that ran
        N steps with an unchanged delta here recompiled nothing — the
        zero-recompile gate of the warm-restart / live-reshard paths
        and of ``bench.py``'s timed regions."""
        total = 0
        for fn in (self.train_step, self.train_step_multi):
            if fn is None:
                continue
            inner = getattr(fn, "__wrapped__", fn)
            size = getattr(inner, "_cache_size", None)
            if callable(size):
                total += int(size())
        return total

    def shard_batch(self, batch, stacked: bool = False):
        """Host batch -> mesh-sharded global batch.

        Fully-addressable mesh (single process, or a local-subset
        mesh): ``batch`` is the whole global batch. Multi-host mesh:
        each process passes its PROCESS-LOCAL rows — the shard its
        data loader owns under the master's data-sharding service —
        and the global array is assembled across hosts
        (``put_global_batch``). This is the multi-host data plane the
        reference reaches via per-rank torch DataLoader sharding +
        NCCL.

        ``stacked``: the batch carries a leading ``steps_per_call``
        axis (the ``train_step_multi`` input shape); the row dimension
        validated on the multi-host path is axis 1.
        """
        if stacked:
            return put_global_batch(batch, self.stacked_batch_spec,
                                    self.strategy.global_batch_size,
                                    row_axis=1)
        return put_global_batch(batch, self.batch_spec,
                                self.strategy.global_batch_size)


def put_global_batch(batch, sharding, global_rows: int = 0,
                     row_axis: int = 0):
    """Host rows -> a sharded global batch.

    A fully-addressable sharding (single process, or a mesh of only
    this process's devices) goes through plain ``device_put`` with the
    batch as the whole global batch. A sharding spanning OTHER
    processes' devices — the real multi-host case, where ``device_put``
    raises on non-addressable devices — assembles the global array
    from each process's PROCESS-LOCAL rows
    (``jax.make_array_from_process_local_data``). When ``global_rows``
    is known, the local row count is validated loudly: feeding the
    global batch on the multi-host path would otherwise silently
    assemble a process_count-times larger batch of duplicated rows.
    ``row_axis``: where the batch-row dimension sits (1 for the
    ``steps_per_call``-stacked shape ``[K, rows, ...]``).
    """
    if getattr(sharding, "is_fully_addressable", True):
        return jax.device_put(batch, sharding)
    import numpy as np

    rows = jax.tree.leaves(batch)[0].shape[row_axis]
    expected = global_rows // jax.process_count() if global_rows else 0
    if expected and rows != expected:
        raise ValueError(
            f"a multi-host sharding takes PROCESS-LOCAL rows: expected "
            f"{expected} rows/process (global batch {global_rows} over "
            f"{jax.process_count()} processes), got {rows}"
        )
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(
            sharding, np.asarray(x)
        ),
        batch,
    )


def _remat_wrap(loss_fn: LossFn, policy_name: str) -> LossFn:
    from dlrover_tpu.ops.remat import apply_remat

    return apply_remat(loss_fn, policy_name or "none")


def resolve_grad_precision(requested: Optional[str] = None) -> str:
    """The effective gradient-path precision at BUILD time: an explicit
    request wins, else the Context knob (``grad_precision``). A
    quantized choice degrades to "bf16" (logged, never raised) when
    the backend fails the fp8 probe. Build-time — not trace-time like
    the dense gathers — because a quantized gradient path changes the
    STRUCTURE of TrainState (the error-feedback residual), which a
    live retune cannot swap under a running state."""
    from dlrover_tpu.common.config import get_context
    from dlrover_tpu.ops.quantize import GRAD_PRECISIONS

    p = (requested or "").strip()
    if not p:
        p = str(getattr(get_context(), "grad_precision", "bf16")
                or "bf16").strip() or "bf16"
    if p not in GRAD_PRECISIONS:
        raise ValueError(
            f"unknown grad precision {p!r}; choose one of "
            f"{GRAD_PRECISIONS}"
        )
    if p != "bf16":
        from dlrover_tpu.ops.shard_compat import fp8_wire_supported

        if not fp8_wire_supported():
            logger.warning(
                "grad precision %r requested but the backend fails the "
                "fp8 probe; gradients stay exact (bf16 path)", p,
            )
            return "bf16"
    return p


def _apply_grad_wire(grads, residual, grad_precision: str):
    """(effective grads, new residual): the error-feedback quantized
    gradient path, per float leaf (blocks along each leaf's last dim,
    computed SHARDWISE — the transform is elementwise over the
    param-sharded gradient tree, so it adds zero collective traffic).
    Non-float leaves pass through untouched."""
    from dlrover_tpu.ops.quantize import error_feedback_qdq

    feedback = grad_precision != "fp8_nofb"

    def one(g, r):
        if (r is None or getattr(g, "ndim", 0) == 0
                or not jnp.issubdtype(jnp.asarray(g).dtype,
                                      jnp.floating)):
            return g, r
        gq, nr = error_feedback_qdq(g, r, feedback=feedback)
        return gq, nr

    flat_g, treedef = jax.tree_util.tree_flatten(grads)
    flat_r = treedef.flatten_up_to(residual)
    pairs = [one(g, r) for g, r in zip(flat_g, flat_r)]
    new_g = jax.tree_util.tree_unflatten(treedef, [p[0] for p in pairs])
    new_r = jax.tree_util.tree_unflatten(treedef, [p[1] for p in pairs])
    return new_g, new_r


def accelerate(
    init_fn: Callable[[Any], Any],
    loss_fn: LossFn,
    optimizer,
    example_batch: Any,
    strategy: Optional[Strategy] = None,
    rng: Optional[jax.Array] = None,
    devices: Optional[Sequence] = None,
    extra_metrics_fn: Optional[Callable] = None,
    steps_per_call: int = 1,
    grad_precision: Optional[str] = None,
) -> AccelerateResult:
    """Build the sharded training program.

    Args:
      init_fn: rng -> params pytree (abstractly evaluated; params are
        materialized directly into their shardings, so 100B-scale models
        never exist unsharded — the ``meta_model_utils`` parity).
      loss_fn: (params, batch, rng) -> (loss, aux dict).
      optimizer: an optax GradientTransformation.
      example_batch: host-local example with GLOBAL batch dimension.
      strategy: mesh/rules/remat/dtype/accum decisions (default: all-fsdp).
      steps_per_call: K > 1 additionally compiles ``train_step_multi``,
        a ``lax.scan`` over K stacked batches (one host dispatch per K
        optimizer steps — the dispatch-overhead amortization lever of
        the async pipelined executor). Donation and per-step semantics
        are preserved; metrics come back stacked along a leading K axis.
      grad_precision: "bf16" (exact, default) | "fp8" — quantize the
        per-shard gradient tree with an ERROR-FEEDBACK residual
        carried in ``TrainState.wire_residual`` (zeros at init,
        param-shaped/-sharded). None resolves the Context knob
        (``grad_precision``). Resolved at BUILD time: the residual
        changes the TrainState structure, so it cannot flip under a
        live retune the way the dense-gather wire can.
    """
    from dlrover_tpu.common.config import get_context
    from dlrover_tpu.utils.compile_cache import enable_compile_cache

    # make every train-step compile land in the persistent cache so a
    # restarted (preempted/rescaled) job warm-starts its compiles
    enable_compile_cache()
    if get_context().jax_debug_nans:
        # opt-in NaN trap (DLROVER_TPU_JAX_DEBUG_NANS=1): jit re-runs the
        # offending op un-jitted and raises at the first NaN — the
        # debug-flag counterpart of the reference's error monitor
        jax.config.update("jax_debug_nans", True)

    strategy = strategy or Strategy()
    rng = rng if rng is not None else jax.random.PRNGKey(0)

    batch_rows = jax.tree.leaves(example_batch)[0].shape[0]
    if strategy.global_batch_size and strategy.global_batch_size != batch_rows:
        raise ValueError(
            f"strategy.global_batch_size={strategy.global_batch_size} but "
            f"the example batch has {batch_rows} rows"
        )
    if batch_rows % max(1, strategy.grad_accum_steps):
        raise ValueError(
            f"grad_accum_steps={strategy.grad_accum_steps} does not divide "
            f"the global batch of {batch_rows} rows"
        )
    strategy = dataclasses.replace(strategy, global_batch_size=batch_rows)

    mesh = strategy.mesh.build(devices)
    rules = strategy.rules()
    loss_fn = _remat_wrap(loss_fn, strategy.remat_policy)

    from jax.sharding import NamedSharding, PartitionSpec

    replicated = NamedSharding(mesh, PartitionSpec())
    batch_spec = batch_sharding(mesh)

    grad_precision = resolve_grad_precision(grad_precision)

    def make_state(r) -> TrainState:
        params = init_fn(r)
        residual = None
        if grad_precision != "bf16":
            # error-feedback residual: zeros, param-shaped — sharded
            # like the params (the rules match the mirrored
            # wire_residual/... paths), so it reshards with them
            residual = jax.tree.map(jnp.zeros_like, params)
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=optimizer.init(params),
            wire_residual=residual,
        )

    abstract_state = jax.eval_shape(make_state, rng)
    state_sharding = rules.tree_shardings(mesh, abstract_state)

    sharded_init = jax.jit(make_state, out_shardings=state_sharding)

    accum = max(1, strategy.grad_accum_steps)
    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)

    def _accumulate_grads(params, batch, step_rng):
        """Microbatch scan keeping the global batch semantics fixed."""
        def split_mb(x):
            b = x.shape[0]
            return x.reshape((accum, b // accum) + x.shape[1:])

        microbatches = jax.tree.map(split_mb, batch)
        rngs = jax.random.split(step_rng, accum)

        def body(carry, mb_rng):
            grad_sum, loss_sum = carry
            mb, r = mb_rng
            (loss, aux), grads = grad_fn(params, mb, r)
            carry = (
                jax.tree.map(jnp.add, grad_sum, grads),
                loss_sum + loss,
            )
            return carry, aux

        zeros = jax.tree.map(jnp.zeros_like, params)
        (grad_sum, loss_sum), aux_stack = lax.scan(
            body, (zeros, jnp.zeros(())), (microbatches, rngs)
        )
        grads = jax.tree.map(lambda g: g / accum, grad_sum)
        aux = jax.tree.map(lambda a: a.mean(axis=0), aux_stack)
        return grads, loss_sum / accum, aux

    def train_step(state: TrainState, batch, step_rng):
        if accum == 1:
            (loss, aux), grads = grad_fn(state.params, batch, step_rng)
        else:
            grads, loss, aux = _accumulate_grads(
                state.params, batch, step_rng
            )
        new_residual = state.wire_residual
        if state.wire_residual is not None and grad_precision != "bf16":
            # low-precision gradient path with error feedback: the
            # optimizer (and everything downstream — norm, finite
            # gate) consumes the decompressed gradients the quantized
            # wire delivers; the decompression error rides forward in
            # the state so it telescopes instead of compounding
            grads, new_residual = _apply_grad_wire(
                grads, state.wire_residual, grad_precision
            )
        if hasattr(optimizer, "update_with_grad_fn"):
            # two-gradient optimizers (WSAM/SAM family): hand them a full
            # forward/backward at arbitrary params on this same batch
            def full_grad_fn(p):
                if accum == 1:
                    return grad_fn(p, batch, step_rng)[1]
                return _accumulate_grads(p, batch, step_rng)[0]

            updates, new_opt_state = optimizer.update_with_grad_fn(
                grads, state.opt_state, state.params, full_grad_fn
            )
        else:
            updates, new_opt_state = optimizer.update(
                grads, state.opt_state, state.params
            )
        import optax

        new_params = optax.apply_updates(state.params, updates)
        grad_norm = optax.global_norm(grads)
        metrics = {
            # loss_fn aux entries (e.g. the MoE load-balance signals
            # moe_dropped_frac / moe_expert_load) ride the step metrics;
            # reserved keys below win on collision
            **aux,
            "loss": loss,
            "grad_norm": grad_norm,
            # NaN/overflow guardrail (reference: the error monitor's
            # silent-NaN failure class): any non-finite grad propagates
            # into the global norm, so this is a free full-tree check
            # the executor routes to report_failure
            "finite": jnp.isfinite(loss) & jnp.isfinite(grad_norm),
            "step": state.step + 1,
        }
        if extra_metrics_fn is not None:
            metrics.update(extra_metrics_fn(state.params, grads))
        new_state = TrainState(
            step=state.step + 1, params=new_params,
            opt_state=new_opt_state, wire_residual=new_residual,
        )
        return new_state, metrics

    def eval_step(state: TrainState, batch):
        loss, aux = loss_fn(state.params, batch, jax.random.PRNGKey(0))
        return {"loss": loss, **aux}

    def _mesh_ctx():
        """A context establishing ``mesh`` as the ambient mesh: the
        current API (``jax.sharding.set_mesh``) when present, else the
        legacy thread-resources context (``with mesh:`` — old jax),
        which in-model shard_maps and sharding constraints equally
        resolve against."""
        set_mesh = getattr(jax.sharding, "set_mesh", None)
        if set_mesh is None:
            return mesh
        return set_mesh(mesh)

    def _under_mesh(fn):
        """Trace under a mesh context so in-model sharding constraints
        (pipeline stages, manual annotations) resolve against our mesh."""
        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            try:
                ctx = _mesh_ctx()
            except ValueError:
                # already inside a trace (e.g. eval_shape over init_fn):
                # the caller's mesh context governs
                return fn(*args, **kwargs)
            with ctx:
                return fn(*args, **kwargs)

        if hasattr(fn, "lower"):
            def lower(*args, **kwargs):
                with _mesh_ctx():
                    return fn.lower(*args, **kwargs)

            wrapped.lower = lower
        return wrapped

    jit_train_step = _under_mesh(jax.jit(
        train_step,
        in_shardings=(state_sharding, batch_spec, replicated),
        out_shardings=(state_sharding, replicated),
        donate_argnums=(0,),
    ))
    jit_eval_step = _under_mesh(jax.jit(
        eval_step,
        in_shardings=(state_sharding, batch_spec),
        out_shardings=replicated,
    ))

    steps_per_call = max(1, int(steps_per_call))
    jit_train_step_multi = None
    stacked_batch_spec = None
    if steps_per_call > 1:
        # one compiled region running K optimizer steps: an outer
        # lax.scan over the stacked batches, around whatever inner
        # microbatch-accumulation scan train_step already contains.
        # XLA annotates the while op with known_trip_count=K, which is
        # exactly the weighting the G106 collective audit applies, so
        # per-step collective bytes stay auditable.
        stacked_batch_spec = NamedSharding(
            mesh, PartitionSpec(None, *batch_spec.spec)
        )

        def train_step_multi(state: TrainState, batches, step_rngs):
            def body(s, batch_rng):
                b, r = batch_rng
                return train_step(s, b, r)

            return lax.scan(body, state, (batches, step_rngs))

        jit_train_step_multi = _under_mesh(jax.jit(
            train_step_multi,
            in_shardings=(state_sharding, stacked_batch_spec, replicated),
            out_shardings=(state_sharding, replicated),
            donate_argnums=(0,),
        ))

    logger.info(
        "accelerate: mesh=%s accum=%d rules=%s remat=%s steps_per_call=%d"
        " grad_precision=%s",
        dict(zip(mesh.axis_names, mesh.devices.shape)),
        accum, strategy.rule_set, strategy.remat_policy or "none",
        steps_per_call, grad_precision,
    )
    return AccelerateResult(
        train_step=jit_train_step,
        eval_step=jit_eval_step,
        init_fn=_under_mesh(sharded_init),
        mesh=mesh,
        state_sharding=state_sharding,
        batch_spec=batch_spec,
        strategy=strategy,
        train_step_multi=jit_train_step_multi,
        steps_per_call=steps_per_call,
        stacked_batch_spec=stacked_batch_spec,
    )
