"""Module replacement optimization.

Role parity: ``atorch/atorch/auto/opt_lib/module_replace_optimization.py:134``
— the reference swaps HF attention modules for FlashAttention versions by
class surgery. Functional JAX models have no module tree; a "module" is a
config-selected implementation, so replacement is a registered config
transform (e.g. flip the attention impl to the Pallas flash kernel, or a
dense FFN to MoE).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List

from dlrover_tpu.common.log import get_logger

logger = get_logger("parallel.module_replace")

# replacement name -> (model family -> config transform)
_REGISTRY: Dict[str, Dict[str, Callable]] = {}


def register_replacement(name: str, model_family: str):
    def deco(fn):
        _REGISTRY.setdefault(name, {})[model_family] = fn
        return fn

    return deco


def available_replacements(model_family: str = "") -> List[str]:
    if not model_family:
        return sorted(_REGISTRY)
    return sorted(
        name for name, fams in _REGISTRY.items() if model_family in fams
    )


def apply_replacements(config, model_family: str,
                       replacements: List[str]):
    """Fold the named replacements over a model config."""
    for name in replacements:
        fams = _REGISTRY.get(name)
        if fams is None or model_family not in fams:
            raise ValueError(
                f"no replacement {name!r} for model family "
                f"{model_family!r}; have {available_replacements(model_family)}"
            )
        config = fams[model_family](config)
        logger.info("applied %s to %s config", name, model_family)
    return config


# -- built-ins (the reference ships FA swaps for its HF families) -----------


@register_replacement("flash_attention", "llama")
@register_replacement("flash_attention", "gpt2")
@register_replacement("flash_attention", "bert")
def _use_flash(config):
    return dataclasses.replace(config, use_flash=True)


@register_replacement("reference_attention", "llama")
@register_replacement("reference_attention", "gpt2")
@register_replacement("reference_attention", "bert")
def _use_reference(config):
    return dataclasses.replace(config, use_flash=False)


@register_replacement("ring_attention", "llama")
def _use_ring(config):
    # requires a mesh with a "seq" axis at accelerate() time
    return dataclasses.replace(config, seq_axis="seq")
