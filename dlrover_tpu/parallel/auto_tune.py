"""Dryrun profiling + strategy search.

Role parity: atorch's acceleration engine — ``dry_runner/dry_runner.py``
(timed profile steps), ``auto/engine/executor.py`` + ``sg_algo`` (candidate
generation and scoring). The TPU version scores candidates by compiling the
real train step (XLA cost analysis gives FLOPs/bytes for free) and timing a
few steps; the search space is the mesh-factorization catalog from
``mesh.candidate_plans``.
"""

from __future__ import annotations

import dataclasses
import time
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional, Sequence

import jax

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.parallel.accelerate import AccelerateResult, accelerate
from dlrover_tpu.parallel.mesh import MeshPlan, candidate_plans
from dlrover_tpu.parallel.strategy import Strategy

logger = get_logger("parallel.tune")


@dataclass
class DryrunReport:
    strategy: Strategy
    compile_time_s: float = 0.0
    step_time_s: float = 0.0
    flops_per_step: float = 0.0
    peak_memory_bytes: int = 0
    error: str = ""

    @property
    def ok(self) -> bool:
        return not self.error

    @property
    def device_flops_per_s(self) -> float:
        if self.step_time_s <= 0:
            return 0.0
        return self.flops_per_step / self.step_time_s


def _process_local_slice(batch, process_count: int, process_index: int):
    """This process's contiguous row share of a GLOBAL example batch.

    Raises when the rows don't divide evenly: floor division would
    silently drop the trailing ``rows % process_count`` rows, and the
    assembled global batch would no longer match
    ``strategy.global_batch_size`` (the dryrun would then profile a
    different program than production runs).
    """
    rows = jax.tree.leaves(batch)[0].shape[0]
    if rows % process_count:
        raise ValueError(
            f"dryrun example batch has {rows} rows, not divisible by "
            f"process_count={process_count}: the per-process slice "
            f"would silently drop the trailing {rows % process_count} "
            f"row(s). Pad or trim the example batch to a multiple of "
            f"the process count."
        )
    share = rows // process_count
    return jax.tree.map(
        lambda x: x[share * process_index: share * (process_index + 1)],
        batch,
    )


def dryrun(result: AccelerateResult, example_batch, rng=None,
           warmup_steps: int = 1, profile_steps: int = 3,
           trace_dir: str = "") -> DryrunReport:
    """Compile + a few timed steps (``ATORCH_DRYRUN_*`` parity).

    ``trace_dir``: capture the timed steps as an xprof trace (open with
    tensorboard/xprof) — the per-op view when the aggregate numbers in
    the report aren't enough."""
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    report = DryrunReport(strategy=result.strategy)
    try:
        state = result.init_fn(rng)
        if jax.process_count() > 1:
            # shard_batch's multi-process contract takes PROCESS-LOCAL
            # rows; every engine node holds the same GLOBAL example, so
            # slice this process's share (otherwise the dryrun would
            # assemble — and time — a process_count-times larger batch)
            example_batch = _process_local_slice(
                example_batch, jax.process_count(), jax.process_index()
            )
        batch = result.shard_batch(example_batch)

        t0 = time.time()
        lowered = result.train_step.lower(state, batch, rng)
        compiled = lowered.compile()
        report.compile_time_s = time.time() - t0

        # the shared legacy-jax shims (utils/prof): list-vs-dict cost
        # analysis and the one peak-residency accounting
        from dlrover_tpu.utils.prof import (
            compiled_peak_bytes,
            cost_analysis_dict,
        )

        report.flops_per_step = float(
            cost_analysis_dict(compiled).get("flops", 0.0))
        report.peak_memory_bytes = compiled_peak_bytes(compiled)

        for _ in range(warmup_steps):
            state, _metrics = compiled(state, batch, rng)
        jax.block_until_ready(state)
        if trace_dir:
            jax.profiler.start_trace(trace_dir)
        try:
            t0 = time.time()
            for _ in range(profile_steps):
                state, _metrics = compiled(state, batch, rng)
            jax.block_until_ready(state)
            report.step_time_s = (time.time() - t0) / max(1, profile_steps)
        finally:
            if trace_dir:
                jax.profiler.stop_trace()
    except Exception as e:  # candidate infeasible (OOM, bad factorization)
        report.error = f"{type(e).__name__}: {e}"
        logger.info("dryrun failed for %s: %s",
                    report.strategy.mesh, report.error[:200])
    return report


def search_strategy(
    init_fn: Callable,
    loss_fn: Callable,
    optimizer,
    example_batch,
    base_strategy: Optional[Strategy] = None,
    candidates: Optional[Sequence[MeshPlan]] = None,
    devices: Optional[Sequence] = None,
    max_candidates: int = 8,
    profile_steps: int = 3,
    model_spec=None,
) -> tuple:
    """Try candidate meshes; return (best_strategy, all_reports).

    The reference's engine distributes ANALYSE/TUNE/DRYRUN tasks over
    ranks; here every candidate compiles against the same devices, so the
    loop is local and the winning strategy is broadcast via the master's
    ParallelConfig push instead.

    ``model_spec`` (a ``planner.ModelSpec``): when given, the analytic
    planner orders the candidates before the budget truncation, so the
    measured search spends its compiles on the cost model's best guesses
    instead of dropping candidates in enumeration order.
    """
    base = base_strategy or Strategy()
    n_devices = len(devices) if devices is not None else jax.device_count()
    plans = list(candidates) if candidates is not None else candidate_plans(
        n_devices
    )
    if model_spec is not None and len(plans) > 1:
        from dlrover_tpu.parallel import planner

        scored = [
            # resolve -1 (infer) axes first: estimate() would clamp
            # them to 1 and misprice the plan
            planner.estimate(p.resolve(n_devices), model_spec,
                             remat_policy=base.remat_policy)
            for p in plans
        ]
        # predicted-feasible first (fastest first), predicted-OOM last —
        # kept in the pool so a wrong memory model only demotes, never
        # eliminates
        scored.sort(key=lambda s: (not s.fits, s.step_time_s))
        plans = [s.plan for s in scored]
    if len(plans) > max_candidates:
        logger.info(
            "search: truncating %d candidates to %d (dropped: %s)",
            len(plans), max_candidates,
            [p.axis_sizes() for p in plans[max_candidates:]],
        )
        plans = plans[:max_candidates]
    reports: List[DryrunReport] = []
    for plan in plans:
        strategy = dataclasses.replace(base, mesh=plan)
        try:
            result = accelerate(
                init_fn, loss_fn, optimizer, example_batch,
                strategy=strategy, devices=devices,
            )
        except Exception as e:
            reports.append(DryrunReport(strategy=strategy,
                                        error=f"{type(e).__name__}: {e}"))
            continue
        reports.append(
            dryrun(result, example_batch, profile_steps=profile_steps)
        )
    viable = [r for r in reports if r.ok and r.step_time_s > 0]
    if not viable:
        raise RuntimeError(
            "no viable strategy found; errors: "
            + "; ".join(r.error[:100] for r in reports)
        )
    best = min(viable, key=lambda r: r.step_time_s)
    logger.info(
        "search: best mesh %s at %.4fs/step over %d candidates",
        best.strategy.mesh, best.step_time_s, len(reports),
    )
    return best.strategy, reports
