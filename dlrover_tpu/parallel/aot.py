"""AOT compile-and-fit proof on virtual TPU topologies.

Role parity: atorch's dryrun/analyse stage (``atorch/atorch/auto/
accelerate.py:563-614``, ``dry_runner.py:12``) profiles a candidate
strategy on live GPUs before committing to it. The TPU-native superpower
is doing this with *no hardware at all*: XLA's TPU compiler is
hermetic, so we AOT-compile the full jitted train step against a
deviceless ``TopologyDescription`` (e.g. a v5p 2x2x4 slice = v5p-32)
and read compiled memory/cost analysis — proving a model FITS and
measuring its per-step FLOPs before a single chip is allocated.

This is the BASELINE "Llama-2-7B on v5p-32" viability proof: run

    python -m dlrover_tpu.parallel.aot --model llama2_7b \
        --topology v5:2x2x4 --gen v5p --batch 16

and it prints one JSON line with the chosen mesh, per-device HBM usage
vs capacity, and the analytic MFU the planner predicts at that step's
measured FLOP count.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Dict, Optional

from dlrover_tpu.common.log import get_logger

logger = get_logger("parallel.aot")

# TensorCore-count naming (v5p-32 = 16 chips) -> topology strings
KNOWN_TOPOLOGIES = {
    "v5p-16": "v5:2x2x2",
    "v5p-32": "v5:2x2x4",
    "v5p-64": "v5:2x4x4",
    "v5p-128": "v5:4x4x4",
}


@dataclass
class AotReport:
    model: str
    topology: str
    n_devices: int
    mesh: Dict[str, int]
    params: int
    global_batch: int
    seq_len: int
    fits: bool
    hbm_per_device_bytes: float
    hbm_capacity_bytes: float
    flops_per_step: float
    predicted_step_time_s: float
    predicted_mfu: float
    compile_time_s: float
    # graph-lint findings (dlrover_tpu.analysis) when the caller asked
    # for the lint pass; None = pass not run
    lint_findings: Optional[list] = None

    def to_json(self) -> str:
        d = dict(self.__dict__)
        if d.get("lint_findings") is None:
            d.pop("lint_findings", None)
        else:
            d["lint_findings"] = [
                {"rule": f.rule_id, "message": f.message}
                for f in d["lint_findings"]
            ]
        d["hbm_per_device_gb"] = round(d.pop("hbm_per_device_bytes") / 1e9, 2)
        d["hbm_capacity_gb"] = round(d.pop("hbm_capacity_bytes") / 1e9, 2)
        d["flops_per_step"] = float(f"{d['flops_per_step']:.4g}")
        d["predicted_step_time_s"] = round(d["predicted_step_time_s"], 4)
        d["predicted_mfu"] = round(d["predicted_mfu"], 4)
        d["compile_time_s"] = round(d["compile_time_s"], 1)
        return json.dumps(d)


_LIBTPU_LOCKFILE = "/tmp/libtpu_lockfile"


def _get_topology_desc_serialized(topologies, topology: str,
                                  wait_budget_s: float = 1800.0,
                                  poll_s: float = 15.0):
    """``get_topology_desc`` with libtpu single-host serialization.

    libtpu holds ``/tmp/libtpu_lockfile`` for the LIFETIME of the
    process that initialized it; a second initialization on the same
    host aborts ("Internal error when accessing libtpu multi-process
    lockfile"), and a SIGKILLed holder leaves the file behind so even
    the next solo run aborts. Distinguish the two with a non-blocking
    flock probe: acquirable means the holder is gone (stale file —
    unlink it while STILL holding the lock, and only if the path's
    inode is the one we locked, so a sibling's freshly created live
    lockfile is never deleted out from under it); unacquirable means a
    live sibling compile, so wait. The wait is a TIME budget, not an
    attempt count — queued compiles on one host each hold the lock for
    their full compile (minutes), and several can be ahead of us.
    """
    import time

    # monotonic: an NTP step or VM resume must not stretch or chop
    # the wait budget
    deadline = time.monotonic() + wait_budget_s
    while True:
        try:
            return topologies.get_topology_desc(
                platform="tpu", topology_name=topology
            )
        except Exception as e:  # noqa: BLE001 — only the lockfile retries
            if "libtpu" not in str(e) or "lockfile" not in str(e):
                raise
            if time.monotonic() >= deadline:
                raise
            try:
                import fcntl
                import os as _os

                # NB: holding LOCK_EX here (even briefly, for the
                # inode-checked unlink below) can make a CONCURRENT
                # libtpu init abort instead of block — libtpu errors
                # rather than waits on a held lock. Acceptable: the
                # sibling lands back in this same retry loop and
                # re-inits within the budget; the alternative (probe
                # with LOCK_SH first) still needs the exclusive window
                # for the unlink, so it only narrows the race, not
                # closes it.
                with open(_LIBTPU_LOCKFILE) as fh:
                    try:
                        fcntl.flock(fh, fcntl.LOCK_EX | fcntl.LOCK_NB)
                    except OSError:
                        # a live sibling holds it: wait within budget
                        logger.info(
                            "libtpu lockfile held by a live process; "
                            "polling (%.0fs of budget left)",
                            deadline - time.monotonic(),
                        )
                        time.sleep(
                            max(0.0, min(poll_s,
                                         deadline - time.monotonic()))
                        )
                        continue
                    try:
                        # we hold the flock on OUR opened inode; only
                        # unlink if the path still names that inode — a
                        # sibling may have recreated the file (its live
                        # lock is on a NEW inode our flock says nothing
                        # about)
                        if (_os.fstat(fh.fileno()).st_ino
                                == _os.stat(_LIBTPU_LOCKFILE).st_ino):
                            logger.warning(
                                "removing stale %s (no live holder; a "
                                "killed jax process left it)",
                                _LIBTPU_LOCKFILE,
                            )
                            _os.remove(_LIBTPU_LOCKFILE)
                    except OSError:
                        pass
                    finally:
                        fcntl.flock(fh, fcntl.LOCK_UN)
            except OSError:
                pass  # file vanished: retry immediately
            # brief pause so a pathologically recreating-and-failing
            # init cannot busy-spin the loop until the deadline
            time.sleep(max(0.0, min(0.2, poll_s)))
            continue


def aot_compile_train_step(
    config,
    topology: str = "v5:2x2x4",
    tpu_gen: str = "v5p",
    global_batch: int = 16,
    mesh_plan=None,
    rule_set: str = "llama",
    remat_policy: str = "",
    model_name: str = "llama",
    ring: bool = False,
    head_chunk: int = 0,
    packed_doc_len: int = 0,
    pipeline: Optional[dict] = None,
    graph_lint: bool = False,
) -> AotReport:
    """Compile the full accelerate() train step for ``config`` against a
    deviceless TPU topology; assert HBM fit via memory_analysis.

    ``mesh_plan``: explicit MeshPlan; default = the roofline planner's
    top choice for this model/topology (``planner.plan_mesh``).

    ``ring``: run ring attention over the plan's "seq" axis (requires an
    explicit ``mesh_plan`` with seq > 1) — proves the flash-fused
    long-context multi-chip path lowers and fits at scale, hermetically.

    ``pipeline``: {"num_stages", "num_microbatches", "num_virtual"?,
    "stage_depths"?} — run the decoder through ``apply_pipelined``
    (GPipe / circular interleaved, optionally uneven per-chunk layer
    counts) instead of the plain forward; pair with the "llama_pp"
    rule set and a mesh_plan with pipe > 1.

    ``graph_lint``: run the SPMD graph lint (``dlrover_tpu.analysis``)
    over the winning plan's lowered/compiled artifacts — host callbacks,
    dtype drift, dropped donation, replicated params, and the
    planner-vs-HLO collective byte audit; findings land on
    ``report.lint_findings``.
    """
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from jax.experimental import topologies

    from dlrover_tpu.models import llama
    from dlrover_tpu.parallel import planner
    from dlrover_tpu.parallel.accelerate import accelerate
    from dlrover_tpu.parallel.strategy import Strategy

    topology = KNOWN_TOPOLOGIES.get(topology, topology)
    topo = _get_topology_desc_serialized(topologies, topology)
    devices = list(topo.devices)
    n = len(devices)
    device_spec = planner.TPU_SPECS[tpu_gen]

    model = planner.model_spec_from_llama(config, global_batch)
    effective_remat = remat_policy or getattr(config, "remat_policy", "")
    fallback_plans: list = []
    if mesh_plan is not None:
        # a CLI/user plan may carry an unresolved data=-1 axis; resolve
        # it against the topology NOW — estimate() and the report's
        # mesh dict must see the same chip count accelerate() compiles
        # for, or the artifact's predicted numbers are for a different
        # (smaller) machine
        mesh_plan = mesh_plan.resolve(n)
    if mesh_plan is None:
        scores = planner.plan_mesh(model, n, device_spec,
                                   remat_policy=effective_remat, top_k=3)
        if not scores:
            raise ValueError(f"no mesh plan for {n} devices")
        mesh_plan = scores[0].plan
        # planner proposes, XLA disposes: if the compiled memory analysis
        # contradicts the analytic fit, fall back to the next-ranked plan
        # (the dryrun-loop shape of the reference's search, executed
        # against the hermetic compiler instead of live chips)
        fallback_plans = [s.plan for s in scores[1:]]
        logger.info(
            "planner chose %s (predicted %.3fs/step)",
            mesh_plan, scores[0].step_time_s,
        )

    if ring:
        from dataclasses import replace as _replace

        seq_size = dict(mesh_plan.axis_sizes()).get("seq", 1)
        if seq_size <= 1:
            raise ValueError(
                "ring=True needs an explicit mesh_plan with seq > 1"
            )
        # the exact mesh accelerate() will build — same plan, same
        # device order — so the ring's shard_map axis resolves
        config = _replace(
            config, seq_axis="seq", mesh=mesh_plan.build(devices)
        )

    rng_np = np.random.RandomState(0)
    seq = config.max_seq_len
    ids = rng_np.randint(
        0, config.vocab_size, size=(global_batch, seq + 1)
    )
    batch = {
        "input_ids": jnp.asarray(ids[:, :-1]),
        "labels": jnp.asarray(ids[:, 1:]),
    }
    if packed_doc_len:
        # packed documents (segment ids + cross-document masking in the
        # kernel tiles), the production long-context batch shape
        doc = max(1, min(packed_doc_len, seq))
        seg = (np.arange(seq) // doc).astype(np.int32)
        seg = np.broadcast_to(seg, (global_batch, seq)).copy()
        same_next = np.concatenate(
            [seg[:, :-1] == seg[:, 1:],
             np.zeros((global_batch, 1), bool)], axis=1)
        batch["segment_ids"] = jnp.asarray(seg)
        batch["labels"] = jnp.asarray(
            np.where(same_next, ids[:, 1:], -100))
    if pipeline:
        from dlrover_tpu.models.losses import masked_lm_loss

        def loss_fn(params, batch, rng):
            logits, moe_aux = llama.apply_pipelined(
                params, batch["input_ids"], config,
                num_stages=pipeline["num_stages"],
                num_microbatches=pipeline["num_microbatches"],
                rng=rng,
                num_virtual=pipeline.get("num_virtual", 1),
                stage_depths=pipeline.get("stage_depths"),
            )
            loss = masked_lm_loss(logits, batch["labels"])
            if config.num_experts > 0:
                # apply_pipelined sums the (token-count-invariant)
                # load-balance aux over MICROBATCHES as well as layers;
                # divide by both so the regularizer weight matches the
                # unpipelined make_loss_fn path exactly
                loss = loss + config.moe_aux_weight * moe_aux / (
                    max(1, config.num_layers)
                    * pipeline["num_microbatches"]
                )
            return loss, {}
    else:
        loss_fn = llama.make_loss_fn(config, head_chunk=head_chunk)

    def compile_plan(plan):
        result = accelerate(
            llama.make_init_fn(config),
            loss_fn,
            optax.adafactor(1e-3),
            batch,
            strategy=Strategy(
                mesh=plan, rule_set=rule_set, remat_policy=remat_policy
            ),
            devices=devices,
        )
        key = jax.ShapeDtypeStruct((2,), jnp.uint32)
        abstract_state = jax.eval_shape(
            result.init_fn, jax.random.PRNGKey(0)
        )
        abstract_batch = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), batch
        )
        t0 = time.time()
        lowered = result.train_step.lower(
            abstract_state, abstract_batch, key
        )
        compiled = lowered.compile()
        return compiled, time.time() - t0, lowered, result, abstract_state

    best = None  # (per_device, compiled, compile_time, plan, artifacts)
    last_exc: Optional[Exception] = None
    for plan in [mesh_plan] + fallback_plans:
        try:
            (compiled_i, compile_time_i, lowered_i, result_i,
             abstract_state_i) = compile_plan(plan)
        except Exception as e:  # noqa: BLE001 — plan infeasible for XLA
            last_exc = e
            logger.warning(
                "plan %s failed to compile (%s); trying next-ranked",
                plan, f"{type(e).__name__}: {e}"[:160],
            )
            continue
        # per-device residency: arguments (the sharded state + batch)
        # plus transient temps; donated (alias) bytes not double-counted
        # (the shared shim in utils/prof — one accounting everywhere)
        from dlrover_tpu.utils.prof import compiled_peak_bytes

        per_device_i = compiled_peak_bytes(compiled_i)
        if best is None or per_device_i < best[0]:
            # the lowering artifacts (full StableHLO + traced closures)
            # are only worth keeping alive past the loop when the lint
            # pass will read them
            best = (per_device_i, compiled_i, compile_time_i, plan,
                    (lowered_i, result_i, abstract_state_i)
                    if graph_lint else None)
        if per_device_i <= device_spec.hbm_bytes:
            break
        logger.warning(
            "plan %s compiled but needs %.1f GB > %.0f GB HBM; trying "
            "next-ranked", plan, per_device_i / 1e9,
            device_spec.hbm_bytes / 1e9,
        )
    if best is None:
        # nothing compiled at all — surface the last compiler error
        raise last_exc if last_exc is not None else RuntimeError(
            "no plan compiled"
        )
    per_device, compiled, compile_time, mesh_plan, artifacts = best
    fits = per_device <= device_spec.hbm_bytes

    # XLA cost_analysis does not multiply FLOPs by loop trip counts, so
    # a scan-over-layers model reads ~1/num_layers of the truth; report
    # the max of compiled and analytic executed counts. The *prediction*
    # comes from the calibrated planner roofline (anchored to measured
    # BENCH points, efficiency clamped < 1, so predicted_mfu is always
    # physical — the round-2 artifact claimed 1.31 from an uncalibrated
    # compute term).
    from dlrover_tpu.utils.prof import cost_analysis_dict

    costs = cost_analysis_dict(compiled)
    pipe_kwargs = {}
    if pipeline:
        from dlrover_tpu.ops.remat import remat_enabled

        pipe_kwargs = dict(
            pipe_microbatches=pipeline["num_microbatches"],
            pipe_virtual=pipeline.get("num_virtual", 1),
            stage_depths=pipeline.get("stage_depths"),
            # whether the compiled program ACTUALLY replays each
            # stage's forward: apply_pipelined keys remat_stage off the
            # MODEL config's policy, not the strategy-level string the
            # estimate would otherwise infer from (ADVICE r5 #4 — a
            # blank strategy policy with model-internal remat on used
            # to drop the replay factor from the prediction)
            stage_remat=remat_enabled(
                getattr(config, "remat_policy", "") or ""
            ),
        )
    score = planner.estimate(mesh_plan, model, device_spec,
                             remat_policy=effective_remat,
                             **pipe_kwargs)
    flops = max(float(costs.get("flops", 0.0)) * n,
                score.breakdown["exec_flops"])
    step_time = score.step_time_s
    # MFU convention: MODEL flops (6N+attn), not recompute flops
    predicted_mfu = score.predicted_mfu
    if not 0.0 < predicted_mfu < 1.0:
        raise AssertionError(
            f"cost model produced unphysical MFU {predicted_mfu:.3f} "
            f"(step {step_time:.4f}s, mesh {mesh_plan})"
        )

    report = AotReport(
        model=model_name,
        topology=topology,
        n_devices=n,
        mesh={
            k: v for k, v in mesh_plan.axis_sizes().items() if v > 1
        } if hasattr(mesh_plan, "axis_sizes") else str(mesh_plan),
        params=model.param_count,
        global_batch=global_batch,
        seq_len=seq,
        fits=bool(fits),
        hbm_per_device_bytes=float(per_device),
        hbm_capacity_bytes=float(device_spec.hbm_bytes),
        flops_per_step=flops,
        predicted_step_time_s=float(step_time),
        predicted_mfu=float(predicted_mfu),
        compile_time_s=compile_time,
    )
    if graph_lint:
        from dlrover_tpu.analysis import graph_lint as gl

        lowered, result, abstract_state = artifacts
        param_bytes = sum(
            a.size * a.dtype.itemsize
            for a in jax.tree.leaves(abstract_state.params)
        )
        from dlrover_tpu.common.config import get_context

        lint = gl.lint_artifacts(
            stablehlo=lowered.as_text(),
            optimized_hlo=compiled.as_text(),
            args_info=getattr(lowered, "args_info", None),
            state_sharding=result.state_sharding,
            abstract_state=abstract_state,
            mesh_plan=mesh_plan,
            model_spec=model,
            device_spec=device_spec,
            compute_dtype=jnp.dtype(config.compute_dtype).name,
            total_param_bytes=param_bytes,
            n_state_leaves=len(jax.tree.leaves(abstract_state)),
            pipe_virtual=(pipeline or {}).get("num_virtual", 1),
            # G107: the artifact's own measured residency against the
            # operator budget (default: the generation's HBM capacity)
            peak_hbm_bytes=float(per_device),
            hbm_budget_bytes=(
                float(getattr(get_context(),
                              "device_hbm_budget_bytes", 0.0))
                or float(device_spec.hbm_bytes)
            ),
            label=f"{model_name}@{topology}",
        )
        report.lint_findings = lint.findings
        # G109: the quantization-drift probe must EXECUTE the program,
        # which a deviceless topology cannot — it runs the same model
        # family on the HOST backend's devices instead (the numerics
        # of the quantized wire do not depend on which backend carries
        # it; the bitwise wire tests pin that)
        if (getattr(config, "num_experts", 0) > 0
                and getattr(config, "moe_dispatch", "") == "grouped_ep"):
            try:
                # resolve INSIDE the guard: a malformed precision
                # string (a typo'd env override) must also skip the
                # probe, not kill the fit-proof
                from dlrover_tpu.ops.moe import resolve_moe_precision
                from dlrover_tpu.ops.moe import MoEConfig as _MC

                resolved = resolve_moe_precision(_MC(
                    num_experts=config.num_experts,
                    precision=getattr(config, "moe_precision", ""),
                ))
                if resolved != "bf16":
                    drift_rep = gl.quantization_drift_audit(
                        precision=resolved)
                    report.lint_findings = (
                        list(report.lint_findings)
                        + list(drift_rep.findings))
            except Exception:  # noqa: BLE001 — a host backend without
                # enough devices (or an unresolvable precision knob)
                # skips the probe, it does not kill the fit-proof
                logger.warning(
                    "quantization drift probe skipped", exc_info=True)
        # dense-wire families: the fsdp gather wire (trace-time knob,
        # models/llama.resolve_fsdp_precision) and the error-feedback
        # gradient path (build-time knob, accelerate) each ratchet
        # their own G109 entry when resolved quantized
        try:
            from dlrover_tpu.models.llama import resolve_fsdp_precision

            if resolve_fsdp_precision(config) != "bf16":
                drift_rep = gl.quantization_drift_audit(family="fsdp")
                report.lint_findings = (list(report.lint_findings)
                                        + list(drift_rep.findings))
        except Exception:  # noqa: BLE001 — same contract as the moe
            # probe: skip, never kill the fit-proof
            logger.warning(
                "fsdp drift probe skipped", exc_info=True)
        try:
            from dlrover_tpu.parallel.accelerate import (
                resolve_grad_precision,
            )

            if resolve_grad_precision() != "bf16":
                drift_rep = gl.quantization_drift_audit(family="grad")
                report.lint_findings = (list(report.lint_findings)
                                        + list(drift_rep.findings))
        except Exception:  # noqa: BLE001
            logger.warning(
                "grad drift probe skipped", exc_info=True)
        # the concurrency pass rides the same flag: the artifact this
        # proof blesses is deployed by the very control plane DLR009-011
        # guard, and the whole-package pass costs ~1s next to the
        # compiles above. Findings are baseline-filtered like tpulint's.
        try:
            import os as _os

            import dlrover_tpu as _pkg
            from dlrover_tpu.analysis import concurrency as _conc
            from dlrover_tpu.analysis import findings as _fmod

            _pkg_dir = _os.path.dirname(_os.path.abspath(_pkg.__file__))
            _base = _fmod.Baseline.load(_os.path.join(
                _pkg_dir, "analysis", "baseline.json"))
            _new, _ = _base.filter(_conc.lint_paths_concurrency(
                [_pkg_dir], root=_os.path.dirname(_pkg_dir)))
            report.lint_findings = list(report.lint_findings) + _new
        except Exception:  # noqa: BLE001 — same contract as the
            # drift probes: skip, never kill the fit-proof
            logger.warning("concurrency lint skipped", exc_info=True)
        for f in report.lint_findings:
            logger.warning("graph lint: %s", f.render())
    logger.info("AOT report: %s", report.to_json())
    return report


def main(argv: Optional[list] = None) -> int:
    import argparse

    import jax

    from dlrover_tpu.models import llama

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default="llama2_7b",
                   choices=["llama2_7b", "llama2_13b", "llama3_8b",
                            "llama3_70b", "llama_tiny"])
    p.add_argument("--topology", default="v5p-32",
                   help="v5p-N alias or raw topology (v5:2x2x4)")
    p.add_argument("--gen", default="v5p", choices=["v4", "v5e", "v5p",
                                                    "v6e"])
    p.add_argument("--batch", type=int, default=16)
    p.add_argument("--seq", type=int, default=4096)
    p.add_argument("--remat", default="dots_saveable")
    p.add_argument("--flash", dest="flash", action="store_true",
                   default=None,
                   help="force the Pallas kernel path")
    p.add_argument("--no-flash", dest="flash", action="store_false",
                   help="lower the XLA reference attention instead of "
                        "the Pallas kernel")
    p.add_argument("--mesh", default="",
                   help="override the planner, e.g. data=2,fsdp=4,tensor=2")
    p.add_argument("--ring", action="store_true",
                   help="run ring attention over the mesh's seq axis "
                        "(long-context path; requires --mesh with seq>1)")
    p.add_argument("--head-chunk", type=int, default=0,
                   help="fused chunked lm-head loss chunk size (0=off; "
                        "required at long seq x large vocab, where full "
                        "[B,S,V] f32 logits alone exceed HBM)")
    p.add_argument("--experts", type=int, default=0,
                   help="switch-MoE with N experts (rule_set=moe: "
                        "expert parallelism over the data x fsdp "
                        "submesh)")
    p.add_argument("--packed-doc-len", type=int, default=0,
                   help="pack N-token documents per row (segmented "
                        "fused-mask kernel; composes with --ring)")
    p.add_argument("--pipe-stages", type=int, default=0,
                   help="run the decoder as a pipeline with N stages "
                        "(rule_set=llama_pp; requires --mesh with "
                        "pipe=N)")
    p.add_argument("--pipe-microbatches", type=int, default=0,
                   help="microbatches for the pipeline schedule "
                        "(default: 2*stages)")
    p.add_argument("--pipe-virtual", type=int, default=1,
                   help="virtual stages per physical stage (V>1 = the "
                        "circular interleaved schedule)")
    p.add_argument("--pipe-depths", default="",
                   help="comma-separated per-chunk layer counts in "
                        "visit order (uneven stage split; default "
                        "even)")
    p.add_argument("--lint", action="store_true",
                   help="run the SPMD graph lint (dlrover_tpu.analysis) "
                        "over the compiled artifact, plus the "
                        "concurrency pass (DLR009-011) over the control "
                        "plane; findings print and flip the exit code")
    args = p.parse_args(argv)

    jax.config.update("jax_platforms", "cpu")  # AOT needs no devices

    import jax.numpy as jnp

    factory = getattr(llama, args.model)
    overrides = dict(
        max_seq_len=args.seq,
        param_dtype=jnp.bfloat16,
        compute_dtype=jnp.bfloat16,
        remat_policy=args.remat,
        # tracing happens on a CPU host but the compile targets the TPU
        # topology: force the real Mosaic kernel, never the interpreter
        # emulation the backend-sniffing default would pick
        flash_interpret=False,
    )
    if args.experts:
        overrides["num_experts"] = args.experts
    if args.flash is not None:
        # only override the factory's use_flash when the user asked
        # (llama_tiny deliberately defaults to the XLA reference path)
        overrides["use_flash"] = args.flash
    elif args.model != "llama_tiny":
        # every production-scale model proves the production path: the
        # hermetic TPU compiler lowers Pallas/Mosaic with no devices, so
        # no S^2 tile exists and dots_saveable fits where the XLA
        # reference path OOMs (llama_tiny deliberately stays on the
        # reference path)
        overrides["use_flash"] = True
    config = factory(**overrides)
    mesh_plan = None
    if args.mesh:
        from dlrover_tpu.parallel.mesh import MeshPlan

        mesh_plan = MeshPlan(**{
            k: int(v) for k, v in
            (kv.split("=") for kv in args.mesh.split(","))
        })
    if args.ring and mesh_plan is None:
        p.error("--ring requires --mesh with a seq>1 axis")
    pipeline = None
    if args.pipe_stages:
        if mesh_plan is None:
            p.error("--pipe-stages requires --mesh with a pipe axis "
                    "matching the stage count")
        pipe_size = dict(mesh_plan.axis_sizes()).get("pipe", 1)
        if pipe_size != args.pipe_stages:
            # a mismatched (or absent) pipe axis would silently compile
            # an artifact whose stage dim never lands on "pipe" — the
            # same hard validation the ring path applies to "seq"
            p.error(f"--pipe-stages {args.pipe_stages} needs --mesh "
                    f"with pipe={args.pipe_stages} (got pipe="
                    f"{pipe_size})")
        if args.packed_doc_len:
            p.error("--packed-doc-len does not compose with "
                    "--pipe-stages: apply_pipelined has no segment_ids "
                    "path (packed batches ride the unpipelined apply)")
        if args.head_chunk:
            p.error("--head-chunk does not compose with --pipe-stages: "
                    "the pipelined loss materializes full logits "
                    "(pipe-sharded over the batch dim instead)")
        pipeline = {
            "num_stages": args.pipe_stages,
            "num_microbatches": (args.pipe_microbatches
                                 or 2 * args.pipe_stages),
            "num_virtual": args.pipe_virtual,
        }
        if args.pipe_depths:
            pipeline["stage_depths"] = tuple(
                int(d) for d in args.pipe_depths.split(",")
            )
    # llama_pp carries BOTH the pipe-leading layer rules and the expert
    # submesh rules, so MoE+PP must resolve to it — "moe" has no pipe
    # entry and would compile stage params off the pipe axis silently
    rule_set = ("llama_pp" if pipeline
                else ("moe" if args.experts else "llama"))
    report = aot_compile_train_step(
        config,
        topology=args.topology,
        tpu_gen=args.gen,
        global_batch=args.batch,
        mesh_plan=mesh_plan,
        model_name=args.model + (f"+moe{args.experts}" if args.experts
                                 else "") + ("+pp" if pipeline else ""),
        rule_set=rule_set,
        ring=args.ring,
        head_chunk=args.head_chunk,
        packed_doc_len=args.packed_doc_len,
        pipeline=pipeline,
        graph_lint=args.lint,
    )
    print(report.to_json())
    if report.lint_findings:
        for f in report.lint_findings:
            print(f.render())
        return 1
    return 0 if report.fits else 1


if __name__ == "__main__":
    import sys

    sys.exit(main())
